"""TPUBatchScheduler — the flagship model: snapshot in, assignments out.

Wraps the ops kernels into the one-dispatch scheduling step the rest of
the framework (host scheduler, extender endpoint, benchmarks) calls.  The
north-star replacement for the reference's per-pod scheduling cycle
(pkg/scheduler/schedule_one.go:66): one compiled program filters, scores,
and assigns an entire pending batch with assume-bookkeeping carried on
device.

Two solver paths, routed automatically:
  * greedy scan (ops.assign) — exact one-pod-at-a-time reference
    semantics; handles every constraint family, including gang
    all-or-nothing via its post-pass (ops.assign n_groups).
  * auction (ops.auction) — joint parallel solve for large bursts and
    gang groups; static+resource families only.

Gangs therefore keep all-or-nothing semantics on BOTH routes: a gang
carrying spread/interpod/port constraints routes to greedy and its
incomplete placements are released by the post-pass.

Cluster state is incremental (ops.schema.ClusterState): node and pod
changes touch one tensor row, and per-batch encode cost is O(pending),
the cache.go:185-260 UpdateSnapshot property.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import epochs, retrace
from ..analysis import ledger as _ledger
from ..analysis.markers import hot_path
from ..api import types as api
from ..ops import assign as assign_ops
from ..ops import auction as auction_ops
from ..ops import schema
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig
from ..testing import faults
from .mirror import DeviceClusterMirror
from .partials import PartialsCache

Result = Union[assign_ops.SolveResult, auction_ops.AuctionResult]


class SolveUnhealthy(RuntimeError):
    """The device returned a structurally-broken solve (non-finite score
    for a placed pod, NaN anywhere in the score tensor): the placements
    cannot be trusted.  Treated exactly like an XLA runtime error by the
    circuit breaker."""


class SolveCircuitBreaker:
    """Device-solve circuit breaker (the kube pattern: contain a failing
    dependency, probe for recovery).

    closed     → device solves flow normally.
    open       → the device path failed twice in a row (one retry);
                 every batch routes to the host fallback until the
                 cooldown elapses.
    half-open  → cooldown elapsed: ONE batch probes the device; success
                 closes the breaker, failure re-opens it with a fresh
                 cooldown.

    The breaker deliberately has no failure-rate window: the device
    solve is all-or-nothing per batch, so consecutive-failure semantics
    (fail → retry → trip) match the dispatch shape."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_CODE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

    GUARDED_FIELDS = {
        "state": "_lock",
        "_open_until": "_lock",
        "trips": "_lock",
        "fallbacks": "_lock",
        "probes": "_lock",
    }

    def __init__(self, cooldown: float = 5.0, clock=time.monotonic):
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self._open_until = 0.0
        self.trips = 0       # CLOSED/HALF_OPEN -> OPEN transitions
        self.fallbacks = 0   # batches solved on the host path
        self.probes = 0      # half-open device attempts

    def state_code(self) -> float:
        # the metrics mirror reads this off the scheduling thread while
        # dispatch threads transition the breaker — take the lock (the
        # unlocked read was a graftlint guarded-by finding)
        with self._lock:
            return self._STATE_CODE[self.state]

    def record_fallback(self) -> None:
        """Count a batch solved on the host path (called by the owner's
        _host_fallback — the counter shares the breaker mutex)."""
        with self._lock:
            self.fallbacks += 1

    def fallback_count(self) -> int:
        with self._lock:
            return self.fallbacks

    def allow_device(self) -> bool:
        """True when this batch may use the device: closed, or open with
        the cooldown elapsed (the call transitions to half-open and the
        batch becomes the probe)."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN and self._clock() >= self._open_until:
                self.state = self.HALF_OPEN
                self.probes += 1
                return True
            # open inside the cooldown, or half-open with the probe
            # already in flight on another thread
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state != self.CLOSED:
                self.state = self.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self.trips += 1
            self.state = self.OPEN
            self._open_until = self._clock() + self.cooldown

    def reset(self) -> None:
        """Snap the breaker to closed with no cooldown pending.
        Leadership reconciliation uses this on takeover/restart: the
        open state belongs to the predecessor's device history — the new
        leader re-probes the device instead of inheriting a cooldown it
        never observed (worst case is one retry + re-trip)."""
        with self._lock:
            self.state = self.CLOSED
            self._open_until = 0.0


class DispatchArbiter:
    """Device-admission control for concurrent profile LANES sharing one
    device/mesh (docs/scheduler_loop.md, pipelined multi-lane cycle).

    Each lane runs its own pop→encode→solve pipeline; encodes already
    serialize under the scheduler-cache lock, but device DISPATCH must
    be arbitrated: the arbiter bounds in-flight device solves to `depth`
    (default 2 — double-buffering: lane A's batch N+1 dispatches while
    batch N reads back, and a third program can't pile onto the device
    queue ahead of another lane's turn).  A slot is released by
    DeviceSolve's coalesced decode (or an explicit release on the
    mis-speculation invalidation path).

    The wait is deadline-bounded as a safety valve: a leaked slot (a
    caller that dispatched and never decoded) degrades fairness, never
    wedges a lane — forced admissions are counted in `forced`."""

    GUARDED_FIELDS = {"_inflight": "_cv", "acquires": "_cv", "forced": "_cv"}

    def __init__(self, depth: int = 2, timeout: float = 30.0,
                 clock=time.monotonic):
        self.depth = max(int(depth), 1)
        self.timeout = timeout
        self._clock = clock
        self._cv = threading.Condition()
        self._inflight = 0
        self.acquires = 0
        self.forced = 0

    def acquire(self) -> bool:  # graftlint: disable=purity -- lane admission: the slot wait IS the arbitration; uncontended cost is one mutex acquire
        """Take a dispatch slot; False means the deadline expired and
        admission was forced (the safety valve, not the normal path)."""
        with self._cv:
            self.acquires += 1
            deadline = self._clock() + self.timeout
            while self._inflight >= self.depth:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    self.forced += 1
                    self._inflight += 1
                    _ledger.push("slot", id(self))
                    return False
                self._cv.wait(min(remaining, 0.2))
            self._inflight += 1
            _ledger.push("slot", id(self))
            return True

    def release(self) -> None:  # graftlint: disable=purity -- slot return; reached from the decode path, not between dispatch and readback
        with self._cv:
            # the ledger pop sits BEFORE the below-zero guard on purpose:
            # the guard keeps production counters sane, but a release with
            # no matching acquire is exactly the double-discharge the
            # GRAFTLINT_OBLIGATIONS ledger exists to surface
            _ledger.pop("slot", id(self))
            if self._inflight > 0:
                self._inflight -= 1
            self._cv.notify_all()

    def inflight(self) -> int:
        with self._cv:
            return self._inflight


class HostSolve:
    """A completed host-fallback solve quacking like DeviceSolve: names
    are already materialized, there is no device future to read back and
    no reason tensor (pods it cannot place park with reason -1 and are
    woken by every event — acceptable in degraded mode)."""

    result = None
    wave_count = None
    wave_fallbacks = None
    frag_score = None
    carveouts = None
    contiguous_gangs = None
    carveout_fallbacks = None

    def __init__(self, names: List[Optional[str]]):
        self._names = names
        self.encode_s = 0.0
        self.dispatch_s = 0.0
        self.decode_wait_s = 0.0
        self.deferred_s = 0.0
        self.dispatched_at = time.perf_counter()

    def ready(self) -> bool:
        return True

    def names(self) -> List[Optional[str]]:
        return self._names

    def reasons(self) -> Optional[List[int]]:
        return None

    def release_slot(self) -> None:
        """No-op: the host fallback never held a dispatch slot."""


_FILL_CACHE_MAX = 64  # entries; shape buckets churn as the cluster grows —
                      # evict wholesale so retired multi-MB fills don't pin
                      # device memory forever


def _device_fill_shortcut(
    snap: schema.Snapshot,
    cache: Optional[dict] = None,
    no_bound_pods: bool = False,
    features=None,
    put=None,
) -> schema.Snapshot:
    """Replace constant-filled pod/constraint tables with (cached)
    device-side fills before transfer.

    The [T, N] / [C, N] / [U, N] per-node count arrays (bound pods
    matching each spread/interpod/preferred row) dominate snapshot bytes
    at scale — 67MB for a 20k-node anti-affinity batch — yet burst
    workloads have no bound pods at all, so they are zeros.  Likewise
    most batches carry no host ports / tolerations / preferred terms, so
    those [P, ·] tables are constant 0 or -1.  The fills are cached by
    (shape, dtype, value): device arrays are immutable, so one fill
    serves every later snapshot — a fresh jnp.full per leaf per step
    costs a device dispatch each (~15 ms over a tunneled link), which
    at ~20 constant leaves would cancel the transfer win.  The cluster
    half is skipped — it lives in the device mirror already.

    put: device placement for the fills and pre-wrapped transfers —
    mesh mode passes a replicated-NamedSharding device_put so every
    leaf lands on the same device set as the sharded mirror (mixing
    single-device-committed and mesh-committed jit operands is a
    placement error)."""
    import jax.numpy as jnp

    if put is None:
        put = jax.device_put

    def fill(shape, dtype, value):
        key = (shape, np.dtype(dtype).str, value)
        if cache is None:
            return put(jnp.full(shape, value, dtype))
        hit = cache.get(key)
        if hit is None:
            if len(cache) >= _FILL_CACHE_MAX:
                cache.clear()
            hit = cache[key] = put(jnp.full(shape, value, dtype))
        return hit

    def shortcut(arr):
        a = np.asarray(arr)
        if a.size < 65536:  # transfer beats two scans + a fill kernel
            return arr
        lo = a.min()
        if lo != a.max():
            return arr
        return fill(a.shape, a.dtype, lo.item())

    def mark(arr, is_zero):
        """Bound-count table: zero by construction (replace, no scan) or
        known-nonzero from features_of's .any() (transfer, no re-scan)."""
        a = np.asarray(arr)
        if a.size < 65536:
            return arr
        if is_zero:
            return fill(a.shape, a.dtype, 0.0)
        return put(a)  # pre-wrap: skips shortcut's min/max

    spread_z = terms_z = pref_z = no_bound_pods
    if features is not None and not no_bound_pods:
        spread_z = not features.bound_spread
        terms_z = not features.bound_terms
        pref_z = not features.bound_pref
    if no_bound_pods or features is not None:
        snap = snap._replace(
            spread=snap.spread._replace(
                node_matches=mark(snap.spread.node_matches, spread_z)
            ),
            terms=snap.terms._replace(
                node_matches=mark(snap.terms.node_matches, terms_z),
                node_owners=mark(snap.terms.node_owners, terms_z),
            ),
            prefpod=snap.prefpod._replace(
                node_counts=mark(snap.prefpod.node_counts, pref_z),
                owner_weight=mark(snap.prefpod.owner_weight, pref_z),
            ),
        )

    def passthrough(arr):
        return arr if isinstance(arr, jax.Array) else shortcut(arr)

    rest = jax.tree.map(passthrough, snap._replace(cluster=None))
    return rest._replace(cluster=snap.cluster)


def _packed_device_put(tree, unpack_cache: dict, put=None):
    """device_put with all host leaves coalesced into ONE transfer.

    Over a tunneled device link each per-leaf transfer pays ~10 ms of
    dispatch latency regardless of size; a Snapshot has ~40 host-side
    pod/constraint leaves, so naive device_put costs ~0.4 s even when
    the payload is 2 MB.  Here the host leaves are concatenated into a
    single byte buffer (one transfer) and sliced/bitcast back into
    their shapes by one jitted unpack program, cached per layout.
    Device-resident leaves (mirror tensors, cached fills) pass through
    untouched.

    The staging buffer is double-buffered per layout instead of freshly
    allocated per batch: the allocate+zero of a multi-MB buffer every
    step showed up in encode profiles, and a layout recurs every batch
    once shapes warm up.  Two alternating buffers make the reuse safe
    under JAX's async dispatch — a buffer is rewritten only after a full
    solve/decode cycle of the batch that used its sibling, by which time
    the unpack program consumed it.

    put: placement for the staging buffer (mesh mode passes a
    replicated-NamedSharding device_put — see _device_fill_shortcut)."""
    if put is None:
        put = jax.device_put
    leaves, treedef = jax.tree.flatten(tree)
    host_idx = [i for i, l in enumerate(leaves) if not isinstance(l, jax.Array)]
    if len(host_idx) <= 2:
        # put only the host leaves: re-putting the device-resident ones
        # (the sharded mirror tensors under a mesh) would reshard them
        for i in host_idx:
            leaves[i] = put(leaves[i])
        return jax.tree.unflatten(treedef, leaves)
    arrs = [np.ascontiguousarray(leaves[i]) for i in host_idx]
    offsets, off = [], 0
    for a in arrs:
        off = (off + 3) & ~3  # 4-byte align each segment
        offsets.append(off)
        off += a.nbytes
    specs = tuple(
        (a.shape, a.dtype.str, a.nbytes, o) for a, o in zip(arrs, offsets)
    )
    nbytes = (off + 3) & ~3
    entry = unpack_cache.get(specs)
    if entry is None:
        if len(unpack_cache) >= _FILL_CACHE_MAX:
            unpack_cache.clear()  # retired layouts: drop their executables

        def _unpack(b):
            outs = []
            for shape, dt, seg_bytes, o in specs:
                seg = jax.lax.slice(b, (o,), (o + seg_bytes,))
                outs.append(seg.view(np.dtype(dt)).reshape(shape))
            return tuple(outs)

        entry = unpack_cache[specs] = {
            "unpack": jax.jit(_unpack),
            "bufs": [None, None],
            "flip": 0,
        }
    flip = entry["flip"]
    entry["flip"] = flip ^ 1
    buf = entry["bufs"][flip]
    if buf is None or buf.nbytes < nbytes:
        buf = entry["bufs"][flip] = np.zeros(nbytes, dtype=np.uint8)
    for a, o in zip(arrs, offsets):
        buf[o : o + a.nbytes] = a.view(np.uint8).ravel()
    unpack = entry["unpack"]
    outs = unpack(put(buf[:nbytes]))
    # layout churn recompiles the unpack program: report it to the
    # recompile-discipline tracker like the solver dispatches (specs IS
    # the executable key here)
    retrace.note("snapshot-unpack", unpack, lambda: specs)
    for i, out in zip(host_idx, outs):
        leaves[i] = out
    return jax.tree.unflatten(treedef, leaves)


class DeviceSolve:
    """A dispatched solve held as device futures.

    JAX dispatch is asynchronous: the arrays inside `result` are promises
    the device is still computing.  The decode (device→host readback) is
    deferred until `names()`/`reasons()` is first called, and then runs
    as ONE coalesced device_get of every array the caller will need —
    the previous path paid separate blocking np.asarray round-trips for
    assignment and reasons (each ~10 ms of tunnel latency).  Deferral is
    what lets the scheduling thread overlap batch N's readback with its
    own host work (queue pop window, wave staging) instead of idling on
    the transfer."""

    def __init__(self, result: Result, meta: schema.SnapshotMeta, clock=time.perf_counter):
        self.result = result
        self.meta = meta
        self._clock = clock
        self.dispatched_at = clock()
        self._decoded = None
        # DispatchArbiter slot held for this in-flight solve (multi-lane
        # admission); released by the coalesced decode, or explicitly by
        # the mis-speculation invalidation path (which never decodes)
        self._slot: Optional[DispatchArbiter] = None
        # step wall split, filled by schedule_pending_async / _decode
        self.encode_s = 0.0        # snapshot encode (under the cache lock)
        self.dispatch_s = 0.0      # jit trace/compile + dispatch enqueue
        self.decode_wait_s = 0.0   # time blocked inside device_get
        self.deferred_s = 0.0      # dispatch -> decode-start gap (overlap)

    def ready(self) -> bool:
        """Non-blocking: has the device finished the solve?  Mesh-mode
        results are sharded jax Arrays and answer is_ready like any
        other future — the sharded solve rides the same deferred
        single-coalesced-readback path (decode overlap survives
        sharding)."""
        try:
            return bool(self.result.assignment.is_ready())
        except AttributeError:  # host numpy result (raw-kernel callers)
            return True

    def release_slot(self) -> None:
        """Give the dispatch-arbiter slot back (idempotent).  Runs from
        the decode's finally and from the invalidation path."""
        slot, self._slot = self._slot, None
        if slot is not None:
            slot.release()

    def _decode(self):
        if self._decoded is None:
            t0 = self._clock()
            self.deferred_s = t0 - self.dispatched_at
            tree = {
                "assignment": self.result.assignment,
                "scores": getattr(self.result, "scores", None),
                "reasons": self.result.reasons,  # None stays None
                "wave_count": getattr(self.result, "wave_count", None),
                "wave_fallbacks": getattr(self.result, "wave_fallbacks", None),
                # slice carve-out telemetry (None off the slice family)
                "frag_score": getattr(self.result, "frag_score", None),
                "carveouts": getattr(self.result, "carveouts", None),
                "contiguous_gangs": getattr(
                    self.result, "contiguous_gangs", None
                ),
                "carveout_fallbacks": getattr(
                    self.result, "carveout_fallbacks", None
                ),
            }
            try:
                got = jax.device_get(tree)  # one coalesced readback
            finally:
                # the device finished (or failed) this program — the
                # next lane's dispatch may proceed either way
                self.release_slot()
            self.decode_wait_s = self._clock() - t0
            assignment = np.asarray(got["assignment"])
            # health check (the circuit breaker's non-finite-score trip
            # wire): a NaN score, or a placed pod whose winning score is
            # non-finite, means the solve state is corrupt and none of
            # this batch's placements can be trusted
            if got["scores"] is not None:
                s = np.asarray(got["scores"])[: self.meta.num_pods]
                placed = assignment[: self.meta.num_pods] >= 0
                if np.isnan(s).any() or not np.isfinite(s[placed]).all():
                    raise SolveUnhealthy(
                        "non-finite score tensor in device solve"
                    )
            self._decoded = (
                assignment,
                None if got["reasons"] is None else np.asarray(got["reasons"]),
                None if got["wave_count"] is None else int(got["wave_count"]),
                None if got["wave_fallbacks"] is None
                else int(got["wave_fallbacks"]),
                None if got["frag_score"] is None
                else float(got["frag_score"]),
                None if got["carveouts"] is None else int(got["carveouts"]),
                None if got["contiguous_gangs"] is None
                else int(got["contiguous_gangs"]),
                None if got["carveout_fallbacks"] is None
                else int(got["carveout_fallbacks"]),
            )
        return self._decoded

    def names(self) -> List[Optional[str]]:
        assignment = self._decode()[0][: self.meta.num_pods]
        return [self.meta.node_name(int(i)) for i in assignment]

    def reasons(self) -> Optional[List[int]]:
        decoded = self._decode()[1]
        if decoded is None:
            return None
        return [int(r) for r in decoded[: self.meta.num_pods]]

    @property
    def wave_count(self) -> Optional[int]:
        return self._decode()[2]

    @property
    def wave_fallbacks(self) -> Optional[int]:
        return self._decode()[3]

    @property
    def frag_score(self) -> Optional[float]:
        """Post-solve cluster fragmentation (None off the slice family)."""
        return self._decode()[4]

    @property
    def carveouts(self) -> Optional[int]:
        return self._decode()[5]

    @property
    def contiguous_gangs(self) -> Optional[int]:
        return self._decode()[6]

    @property
    def carveout_fallbacks(self) -> Optional[int]:
        return self._decode()[7]


class SolverPrewarmPool:
    """Background executable warm pool.

    First-of-a-bucket batches eat a 10-40 s XLA compile inside
    schedule_batch.  The pool watches the executable keys the dispatch
    path actually uses and speculatively compiles the NEIGHBOR keys a
    workload is about to need — the adjacent pod-size buckets (churn
    batches walk the bucket ladder) and the bound-flags variant (the
    bound_* FeatureFlags flip once the first batch binds, which is a new
    executable; Scheduler.warmup's round B exists for the same reason)
    — off-thread via jit.lower().compile().  With the persistent
    compilation cache (utils.compilecache, wired on package import) the
    AOT compile lands in the on-disk cache, so the later jit call
    "compiles" in milliseconds instead of re-tracing XLA.

    Compiles release the GIL, so the worker does not stall the
    scheduling thread.  close() drains the queue and joins the worker —
    tearing the interpreter down mid-compile aborts the process, so
    every owner must close (TPUBatchScheduler registers atexit)."""

    GUARDED_FIELDS = {"_seen": "_lock", "_thread": "_lock"}

    def __init__(self, compile_observer=None, max_pending: int = 16):
        import queue as _q

        self._q: "_q.Queue" = _q.Queue(maxsize=max_pending)
        self._seen: set = set()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self.compile_observer = compile_observer
        self.compiled = 0
        self.errors = 0

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._work, name="solver-prewarm", daemon=False
                )
                self._thread.start()

    def _work(self) -> None:
        import queue as _q

        while True:
            try:
                job = self._q.get(timeout=5.0)
            except _q.Empty:
                return  # idle: let the thread retire; re-spawned on demand
            if job is None or self._stop:
                return
            label, compile_fn = job
            t0 = time.perf_counter()
            try:
                compile_fn()
                self.compiled += 1
            except Exception:  # noqa: BLE001 — speculative work only
                self.errors += 1
                logging.getLogger(__name__).debug(
                    "prewarm compile failed for %s", label, exc_info=True
                )
                continue
            if self.compile_observer is not None:
                try:
                    self.compile_observer(time.perf_counter() - t0)
                except Exception:  # noqa: BLE001
                    pass

    def offer(self, key, label: str, compile_fn) -> bool:
        """Enqueue a speculative compile if its key is new.  Never
        blocks: a full queue drops the job (the synchronous compile
        path still works, just cold)."""
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
        try:
            self._q.put_nowait((label, compile_fn))
        except Exception:  # noqa: BLE001 — queue full
            return False
        self._ensure_thread()
        return True

    def mark_seen(self, key) -> bool:
        """Record a key the dispatch path compiled synchronously.
        Returns True when the key was new."""
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            return True

    def close(self, timeout: float = 60.0) -> None:
        self._stop = True
        try:
            self._q.put_nowait(None)
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            t = self._thread
        # snapshot join: a respawned thread sees _stop and exits on its
        # own, so joining a superseded handle is safe — stale here is
        # harmless by design
        if t is not None and t.is_alive():  # graftlint: disable=atomicity -- snapshot join; _stop gates respawn
            t.join(timeout=timeout)


class TPUBatchScheduler:
    """Owns the incremental cluster state (persistent vocabularies) and
    the jitted solvers.

    Stateless usage (one-shot):
        sched = TPUBatchScheduler()
        placements = sched.schedule(nodes, pending_pods, bound_pods)

    Incremental usage (the host scheduler's path):
        sched.add_node(n) / sched.remove_node(name)
        sched.assume(pod, node_name) / sched.forget(pod)
        placements = sched.schedule_pending(pending_pods)
    """

    # Greedy-routed batches at least this large solve through the
    # wavefront path (ops.assign.wavefront_assign): below it the classic
    # scan's executable is cheaper to hold and the wave win is noise.
    WAVEFRONT_MIN_PODS = 64

    def __init__(
        self,
        score_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        limits: Optional[schema.SnapshotLimits] = None,
        mode: str = "auto",  # auto | greedy | auction
        state: Optional[schema.ClusterState] = None,
        mesh=None,  # jax.sharding.Mesh: shard the solve axis across chips
        solve_shard_axis: str = "node",  # node | pod (wavefront-only twin)
        use_mirror: bool = True,  # DeviceClusterMirror feature gate
        use_wavefront: bool = True,  # wave-parallel greedy feature gate
        wave_cap: int = assign_ops.DEFAULT_WAVE_CAP,
        prewarm: Optional[bool] = None,  # None = auto (off on CPU backend)
        arbiter: Optional[DispatchArbiter] = None,  # shared across lanes
        carveout_policy: str = "prefer",  # slice carve-outs: prefer|require|off
        use_partials: bool = True,  # PartialsCache (IncrementalSolve gate)
        partials_resync_interval: int = PartialsCache.DEFAULT_RESYNC_INTERVAL,
    ):
        if state is not None:
            # shared-state instance: multiple scheduler PROFILES solve the
            # same cluster with different score configs (profile.Map —
            # one frameworkImpl per profile over one cache)
            self.builder = state.builder
            self.state = state
        else:
            self.builder = schema.SnapshotBuilder(limits)
            self.state = schema.ClusterState(self.builder)
        self.score_config = score_config
        self.mode = mode
        self.mesh = mesh
        if solve_shard_axis not in ("node", "pod"):
            raise ValueError(
                f"solve_shard_axis must be node|pod, got "
                f"{solve_shard_axis!r}"
            )
        self.solve_shard_axis = solve_shard_axis
        self.use_wavefront = use_wavefront
        self.wave_cap = wave_cap
        # TPU slice carve-out policy (ops/slices.py): "prefer" biases
        # shaped gangs onto contiguous sub-cuboids, "require" filters on
        # them (a gang that can't fit contiguously parks whole), "off"
        # disarms the family (SchedulerConfiguration.slice_carveout_policy)
        if carveout_policy not in ("prefer", "require", "off"):
            raise ValueError(
                f"carveout_policy must be prefer|require|off, got "
                f"{carveout_policy!r}"
            )
        self.carveout_policy = carveout_policy
        # throughput of the most recent snapshot encode (pods/s over the
        # build_from_state wall time) — mirrored into the Registry's
        # scheduler_encode_rows_per_s each cycle
        self.last_encode_rows_per_s = 0.0
        self._greedy = assign_ops.greedy_assign_jit(score_config)
        self._wavefront = assign_ops.wavefront_assign_jit(score_config)
        self._auction = auction_ops.auction_assign_jit(score_config)
        if prewarm is None:
            # speculative background compiles only pay off where compiles
            # are expensive (real accelerators); CPU test runs skip them
            prewarm = jax.default_backend() not in ("cpu",)
        self.prewarm_pool: Optional[SolverPrewarmPool] = (
            SolverPrewarmPool() if prewarm else None
        )
        if self.prewarm_pool is not None:
            import atexit

            atexit.register(self.prewarm_pool.close)
        if mesh is not None:
            # multi-chip: node axis sharded over the mesh (SURVEY §2.7
            # row 8) — all three solver families have sharded twins with
            # placement parity (tests/test_sharded.py,
            # tests/test_sharded_pipeline.py)
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel import sharded as _sharded

            if solve_shard_axis == "pod":
                # pod-axis mesh (PR 16's wide-batch regime): only the
                # wavefront family has a pod-sharded twin — wave members
                # split across chips against replicated node tables and
                # the member axis pads itself to the mesh, so there is
                # no divisibility precondition.  Greedy/auction batches
                # stay single-chip under this axis.
                self._greedy_sharded = self._greedy
                self._wavefront_sharded = _sharded.podsharded_wavefront_jit(
                    mesh, score_config
                )
                self._auction_sharded = self._auction
            else:
                self._greedy_sharded = _sharded.sharded_greedy_jit(
                    mesh, score_config
                )
                self._wavefront_sharded = _sharded.sharded_wavefront_jit(
                    mesh, score_config
                )
                self._auction_sharded = _sharded.sharded_auction_jit(
                    mesh, score_config
                )
            self._mesh_size = int(mesh.devices.size)
            # every host→device transfer in mesh mode targets the mesh's
            # replicated sharding: the solve jits consume the sharded
            # mirror, and jit operands must share one device set
            rep = NamedSharding(mesh, PartitionSpec())
            self._put = lambda x: jax.device_put(x, rep)
        else:
            self._mesh_size = 0
            self._put = jax.device_put
        # batches a configured mesh could not solve sharded (padded node
        # bucket smaller than the mesh) — mirrored into
        # scheduler_sharded_solve_fallbacks
        self.sharded_fallbacks = 0
        self._mirror = DeviceClusterMirror(self.state, mesh=mesh)
        self.use_mirror = use_mirror
        # device-resident Filter/Score partials warm-starting each solve
        # (the incremental O(changes) path, models/partials.py): keyed
        # by pod-class signatures, scatter-refreshed from the same dirty
        # rows the mirror scatters, invalidated/rolled back alongside
        # it.  Needs the mirror (warm rows evaluate against the resident
        # cluster tensors the solve consumes).
        self._partials: Optional[PartialsCache] = (
            PartialsCache(
                self.state, mesh=mesh,
                resync_interval=partials_resync_interval,
            )
            if use_partials and use_mirror
            else None
        )
        # multi-lane device admission: profile lanes sharing one
        # device/mesh pass ONE DispatchArbiter (FrameworkRegistry wires
        # it for multi-profile configs); None = uncontended single lane,
        # no admission overhead on the dispatch path
        self.arbiter = arbiter
        # device-solve circuit breaker: XLA runtime/compile errors and
        # non-finite score tensors retry once, then trip every batch to
        # the host-side per-pod exact-evaluation fallback for a cooldown
        # (docs/robustness.md)
        self.breaker = SolveCircuitBreaker()
        self._fill_cache: dict = {}
        self._unpack_cache: dict = {}
        self.last_result: Optional[Result] = None
        # the effective solve object of the most recent finalize_pending
        # (the caller's DeviceSolve unless the breaker's retry/fallback
        # replaced it)
        self.last_solve = None
        # encode/solve wall split of the most recent schedule_pending —
        # the host scheduler's pipeline-overlap meter reads it: the
        # encode half holds the cache lock (a concurrent wave commit
        # can't overlap it), only the device half truly pipelines
        self.last_timings: Dict[str, float] = {}

    @property
    def shard_count(self) -> int:
        """Mesh size the solver shards over (0 = single chip) —
        mirrored into scheduler_solve_shard_count."""
        return self._mesh_size

    # -- incremental cluster state ---------------------------------------

    def add_node(self, node: api.Node) -> None:
        self.state.add_node(node)

    def update_node(self, node: api.Node) -> None:
        self.state.update_node(node)

    def remove_node(self, name: str) -> None:
        self.state.remove_node(name)

    def assume(self, pod: api.Pod, node_name: str) -> None:
        """Account a placement immediately (cache.go AssumePod)."""
        self.state.add_pod(pod, node_name)

    def forget(self, pod: api.Pod) -> None:
        """Undo an assume / remove a bound pod (ForgetPod/RemovePod)."""
        self.state.remove_pod(pod)

    # -- scheduling -------------------------------------------------------

    # Batches at least this large route to the joint auction solve when
    # its constraint coverage allows: the greedy scan's P sequential steps
    # dominate solve latency there, while small batches keep the scan's
    # exact one-at-a-time reference semantics.
    AUCTION_MIN_PODS = 1024

    def _route(
        self,
        snap: schema.Snapshot,
        features: assign_ops.FeatureFlags,
        topo_split: Tuple[int, int],
        n_groups: int,
    ) -> str:
        route = self.mode
        if route == "auto":
            route = "greedy"
            if auction_ops.auction_features_ok(features):
                ok = True
                if features.interpod:
                    # the repair's [P, T] / [Z, T] tables must stay
                    # on-chip — this guard binds even for gang batches
                    # (greedy keeps gang all-or-nothing via its own
                    # post-pass)
                    t_dim = snap.terms.valid.shape[0]
                    if t_dim * max(snap.pods.req.shape[0], topo_split[1]) > 2**25:
                        ok = False
                has_gangs = n_groups > 0
                big = snap.pods.req.shape[0] >= self.AUCTION_MIN_PODS
                if ok and (has_gangs or big):
                    route = "auction"
        if route == "greedy" and (
            self.use_wavefront
            and snap.pods.req.shape[0] >= self.WAVEFRONT_MIN_PODS
            and not features.slices
        ):
            # same semantics as the scan (ops.assign parity suite), P/W
            # sequential steps instead of P; mesh mode routes here too —
            # the sharded wavefront is scan-identical across shards.
            # Slice carve-out batches stay on the classic scan: every
            # shaped pod writes the free mask every other shaped pod's
            # corner evaluation reads, so wave-start evaluation cannot
            # hold (auction_features_ok excludes them for the same
            # reason — sequential-by-construction anchor semantics).
            route = "wavefront"
        return route

    def _sharded_ok(self, snap: schema.Snapshot, route: str = "greedy") -> bool:
        """True when this batch solves on the mesh.  Node axis: any
        route, but the padded node bucket must split evenly across the
        mesh — a bucket smaller than the mesh (tiny cluster under a
        wide mesh) falls back to the single chip and counts a
        sharded_solve_fallback.  Pod axis: wavefront only (the one
        family with a pod-sharded twin); its member axis pads itself to
        the mesh, so there is no divisibility check, and non-wavefront
        routes run single-chip by design rather than as a fallback."""
        if self.mesh is None:
            return False
        if self.solve_shard_axis == "pod":
            return route == "wavefront"
        if snap.cluster.allocatable.shape[0] % self._mesh_size == 0:
            return True
        self.sharded_fallbacks += 1
        return False

    @staticmethod
    def _shapes_of(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
        )

    @staticmethod
    def _shapes_with_pod_dim(
        shapes: schema.Snapshot, p_new: int
    ) -> schema.Snapshot:
        """Rewrite the pod axis of a Snapshot shape tree to p_new (class/
        constraint-row dims are workload-shaped and stay put)."""

        def redim(sds, axis=0):
            shape = list(sds.shape)
            shape[axis] = p_new
            return jax.ShapeDtypeStruct(tuple(shape), sds.dtype)

        pods = shapes.pods._replace(
            valid=redim(shapes.pods.valid),
            req=redim(shapes.pods.req),
            nonzero_req=redim(shapes.pods.nonzero_req),
            name_id=redim(shapes.pods.name_id),
            sel_idx=redim(shapes.pods.sel_idx),
            tol_bits=redim(shapes.pods.tol_bits, axis=1),
            tol_all=redim(shapes.pods.tol_all, axis=1),
            port_bits=redim(shapes.pods.port_bits),
            pref_idx=redim(shapes.pods.pref_idx),
            pref_weight=redim(shapes.pods.pref_weight),
            class_id=redim(shapes.pods.class_id),
            priority=redim(shapes.pods.priority),
            group_id=redim(shapes.pods.group_id),
            pod_shape=redim(shapes.pods.pod_shape),
        )
        return shapes._replace(
            pods=pods,
            spread=shapes.spread._replace(
                pod_matches=redim(shapes.spread.pod_matches),
                pod_idx=redim(shapes.spread.pod_idx),
            ),
            terms=shapes.terms._replace(
                matches_incoming=redim(shapes.terms.matches_incoming),
                aff_idx=redim(shapes.terms.aff_idx),
                anti_idx=redim(shapes.terms.anti_idx),
                self_match_all=redim(shapes.terms.self_match_all),
            ),
            prefpod=shapes.prefpod._replace(
                matches_incoming=redim(shapes.prefpod.matches_incoming),
                pod_idx=redim(shapes.prefpod.pod_idx),
                pod_weight=redim(shapes.prefpod.pod_weight),
            ),
            images=shapes.images._replace(
                pod_ids=redim(shapes.images.pod_ids),
                n_containers=redim(shapes.images.n_containers),
            ),
        )

    def _prewarm_neighbors(  # graftlint: disable=purity -- speculative compile bookkeeping; the pool mutex is uncontended and compiles run off-thread
        self, snap, route, topo_z, features, n_groups, wave_shape=None,
        sharded: bool = False, statics=None,
    ) -> None:
        """On a first-seen executable key, speculatively compile the keys
        the workload will hit next (SolverPrewarmPool docstring).  The
        key carries the mesh size: sharded and single-chip solves of the
        same bucket are DIFFERENT executables (shard_map is part of the
        program), and a mesh-mode scheduler prewarms the sharded twin.
        Warm-started solves (statics from the PartialsCache) are their
        own executable family: the key carries the statics shapes and
        the compiles target the `.jitted_warm` twin."""
        pool = self.prewarm_pool
        if pool is None or route == "auction":
            return
        from ..utils.vocab import pad_dim

        p_dim = snap.pods.req.shape[0]
        n_dim = snap.cluster.allocatable.shape[0]
        mesh_key = self._mesh_size if sharded else 0
        statics_key = (
            None
            if statics is None
            else tuple(
                (tuple(a.shape), str(a.dtype)) for a in statics
            )
        )
        key = (
            route, mesh_key, n_dim, p_dim, topo_z, features, n_groups,
            wave_shape, statics_key,
        )
        if not pool.mark_seen(key):
            return
        shapes = self._shapes_of(snap)
        statics_shapes = (
            None if statics is None else self._shapes_of(statics)
        )
        if sharded:
            solver = (
                self._wavefront_sharded if route == "wavefront"
                else self._greedy_sharded
            )
        else:
            solver = (
                self._wavefront if route == "wavefront" else self._greedy
            )
        fn = solver.jitted if statics is None else solver.jitted_warm

        def offer(p_variant, feats):
            wshape = wave_shape
            if route == "wavefront":
                if p_variant != p_dim or wshape is None:
                    wshape = (
                        pad_dim(max(-(-p_variant // self.wave_cap), 1), 8),
                        self.wave_cap,
                    )
                args_shapes = (
                    self._shapes_with_pod_dim(shapes, p_variant)
                    if p_variant != p_dim else shapes,
                    jax.ShapeDtypeStruct(wshape, np.int32),
                )
            else:
                args_shapes = (
                    self._shapes_with_pod_dim(shapes, p_variant)
                    if p_variant != p_dim else shapes,
                )
            if statics_shapes is not None:
                # the warm twin takes the statics triple right after the
                # array args; the class axis tracks the batch's class
                # set, not its pod bucket, so neighbor variants reuse it
                args_shapes = args_shapes + (statics_shapes,)
            nkey = (
                route, mesh_key, n_dim, p_variant, topo_z, feats, n_groups,
                wshape, statics_key,
            )

            def compile_fn(args_shapes=args_shapes, feats=feats):
                fn.lower(*args_shapes, topo_z, feats, n_groups).compile()

            pool.offer(nkey, f"{route}/p={p_variant}", compile_fn)

        # the bucket ladder: churn batches walk adjacent pod buckets
        offer(p_dim * 2, features)
        if p_dim // 2 >= self.builder.limits.min_pods:
            offer(p_dim // 2, features)
        # the first bind flips the bound_* gates — a NEW executable the
        # second batch of a constraint workload would compile mid-cycle
        flipped = features._replace(
            bound_spread=features.spread,
            bound_terms=features.interpod,
            bound_pref=features.interpod_pref,
        )
        if flipped != features:
            offer(p_dim, flipped)

    def solve(
        self, snap: schema.Snapshot, topo_z: Optional[int] = None
    ) -> assign_ops.SolveResult:
        """Raw greedy device solve on a prebuilt snapshot.

        topo_z is auto-derived when not given; passing a value smaller
        than required aliases topology domains together and silently
        corrupts spread/inter-pod state, so it is validated (when those
        families are active — it is unused otherwise)."""
        features = assign_ops.features_of(
            snap, slice_policy=self.carveout_policy
        )
        if assign_ops.needs_topo(features):
            required = assign_ops.required_topo_z(snap)
            if topo_z is None:
                topo_z = required
            elif topo_z < required:
                raise ValueError(
                    f"topo_z={topo_z} < required_topo_z={required}: would "
                    "alias topology values (see ops.assign.required_topo_z)"
                )
        return self._greedy(snap, topo_z, features)

    @hot_path
    def _dispatch(
        self, snap: schema.Snapshot, meta: Optional[schema.SnapshotMeta] = None
    ) -> Result:
        meta = meta or schema.SnapshotMeta(0, 0, [], [], self.builder.limits)
        epochs.audit_dispatch(meta)
        features = meta.features or assign_ops.features_of(
            snap, slice_policy=self.carveout_policy
        )
        topo_split = meta.topo_split or assign_ops.required_topo_z_split(snap)
        n_groups = (
            meta.n_groups
            if meta.n_groups is not None
            else schema.num_groups(snap)
        )
        route = meta.route or self._route(snap, features, topo_split, n_groups)
        sharded = self._sharded_ok(snap, route)
        if route == "auction":
            solver = self._auction_sharded if sharded else self._auction
            self._prewarm_neighbors(snap, route, None, features, n_groups)
            return solver(
                snap, features=features, topo_z=topo_split,
                n_groups=n_groups, tie_k=meta.tie_k,
            )
        topo_z = (
            max(topo_split) if assign_ops.needs_topo(features) else 1
        )
        if route == "wavefront":
            plan = meta.wave_plan
            if plan is None:
                # stateless/one-shot path: snap is still host-resident,
                # so the numpy partition walk is cheap here
                plan = assign_ops.plan_waves(
                    snap, features=features, wave_cap=self.wave_cap
                )
            self._prewarm_neighbors(
                snap, route, topo_z, features, n_groups,
                wave_shape=plan.members.shape, sharded=sharded,
                statics=meta.statics,
            )
            solver = self._wavefront_sharded if sharded else self._wavefront
            return solver(
                snap, wave_members=plan.members, topo_z=topo_z,
                features=features, n_groups=n_groups, statics=meta.statics,
            )
        self._prewarm_neighbors(
            snap, route, topo_z, features, n_groups, sharded=sharded,
            statics=meta.statics,
        )
        solver = self._greedy_sharded if sharded else self._greedy
        return solver(
            snap, topo_z, features, n_groups=n_groups, statics=meta.statics
        )

    def encode_pending(
        self,
        pending: Sequence[api.Pod],
        num_pods_hint: int = 0,
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> Tuple[schema.Snapshot, schema.SnapshotMeta]:
        """Encode pending pods + live cluster state into a device-resident
        snapshot.  `lock` (the scheduler cache's mutex) is held across the
        encode AND the device transfer: build_from_state returns views
        aliasing live arrays that informer threads mutate, and both sides
        intern into the shared vocabularies — the reference holds the cache
        mutex for UpdateSnapshot (cache.go:185) for the same reason.
        The transfer MUST NOT alias live state: build_from_state returns
        cluster tensors as views of the ClusterState arrays, and on the
        CPU backend jax.device_put can zero-copy a numpy buffer — a later
        cache mutation would then leak into an already-"materialized"
        snapshot (observed: preemption's verify restore undoing its own
        victim removal mid-solve).  The cluster leaves are host-copied
        first (pod/constraint tables are freshly allocated every build,
        so only the cluster aliases); device_put then transfers without
        per-leaf device dispatches (jnp.array's convert path costs ~20ms
        PER LEAF over the axon tunnel — 49 leaves ≈ 1s per encode).

        reservations: (node_name, pod) pairs whose requests overlay the
        named node's usage in THIS snapshot only — nominated preemptors
        waiting to land (the filters-with-nominated-pods analogue,
        runtime/framework.go:962).  The overlay is applied to the device
        copy; live state is untouched."""
        with lock if lock is not None else contextlib.nullcontext():
            t_enc = time.perf_counter()
            snap, meta = self.builder.build_from_state(
                self.state, pending, num_pods_hint=num_pods_hint
            )
            dt_enc = time.perf_counter() - t_enc
            if pending and dt_enc > 0.0:
                self.last_encode_rows_per_s = len(pending) / dt_enc
            rows, reqs, nzs = [], [], []
            for node_name, pod in reservations:
                row = self.state._rows.get(node_name)
                if row is None:
                    continue  # nominated node left the cluster
                req, nz, _ = self.builder.pod_usage(pod, self.state._r)
                rows.append(row)
                reqs.append(req)
                nzs.append(nz)
            # derive routing statics while the arrays are host-resident —
            # probing them post-transfer costs one tunnel round-trip each
            no_bound = not self.state._pods
            meta.features = assign_ops.features_of(
                snap, no_bound_pods=no_bound,
                slice_policy=self.carveout_policy,
            )
            meta.topo_split = assign_ops.required_topo_z_split(snap)
            meta.n_groups = schema.num_groups(snap)
            meta.tie_k = auction_ops.default_tie_k(snap)
            # route now, while the pod tables are host numpy: the
            # wavefront partition walk reads them, and probing a
            # device-resident snapshot costs a tunnel round-trip per
            # array
            meta.route = self._route(
                snap, meta.features, meta.topo_split, meta.n_groups
            )
            if meta.route == "wavefront":
                meta.wave_plan = assign_ops.plan_waves(
                    snap, features=meta.features, wave_cap=self.wave_cap
                )
            # The cluster half (~98% of the bytes at scale) stays
            # device-resident across steps; only dirty rows transfer
            # (models.mirror).  The pod/constraint tables are freshly
            # allocated per batch, so device_put cannot alias live state.
            # Under a mesh the mirror is NamedSharding-resident in the
            # exact layout the sharded jits' shard_map specs expect, and
            # the pod-table transfers replicate over the mesh (_put) —
            # per-batch host→device traffic stays O(changed rows) in
            # both layouts.
            if self.use_mirror:
                dev_cluster = self._mirror.sync()
                epochs.audit_mirror(self._mirror, self.state)
                if (
                    self._partials is not None
                    and meta.route in ("greedy", "wavefront")
                ):
                    # warm-start statics for the greedy-family routes:
                    # re-evaluate only the rows dirtied since the last
                    # sync (plus first-seen classes) against the SAME
                    # resident tensors the solve consumes.  The cache is
                    # an optimization layer: any failure inside it
                    # (including injected solve.partials faults) falls
                    # back to the cold in-program class_statics path and
                    # invalidates the residents.
                    try:
                        meta.statics = self._partials.sync(
                            dev_cluster, snap, meta,
                            cluster_epoch=self._mirror.epoch(),
                        )
                    except Exception:  # noqa: BLE001 — cold solve instead
                        self._partials.invalidate()  # graftlint: disable=coherence -- partials-only fault: the mirror synced cleanly above and is not a suspect
                        logging.getLogger(__name__).exception(
                            "partials sync failed; cold solve for this "
                            "batch"
                        )
                    if meta.statics is not None:
                        # a MAX_SLOTS decline (statics None) leaves the
                        # store legitimately behind the cache — audit
                        # only what this solve actually consumes
                        epochs.audit_partials(self._partials, self.state)
                        meta.coherence_stamp = (
                            self._mirror.epoch(), self._partials.epoch()
                        )
                snap = snap._replace(cluster=dev_cluster)
                snap = _device_fill_shortcut(
                    snap, self._fill_cache, no_bound_pods=no_bound,
                    features=meta.features, put=self._put,
                )
                snap = _packed_device_put(
                    snap, self._unpack_cache, put=self._put
                )
            else:
                # DeviceClusterMirror gate off: full host copy +
                # transfer every step (the pre-mirror behavior — the
                # rollback knob the gate exists for).  Mesh mode keeps
                # the copies host-side and lets shard_map own placement.
                snap = snap._replace(
                    cluster=jax.tree.map(np.array, snap.cluster)
                )
                snap = jax.device_put(snap) if self.mesh is None else snap
        if rows:
            idx = jnp.asarray(np.array(rows, dtype=np.int32))
            cluster = snap.cluster._replace(
                requested=snap.cluster.requested.at[idx].add(
                    jnp.asarray(np.stack(reqs))
                ),
                nonzero_requested=snap.cluster.nonzero_requested.at[idx].add(
                    jnp.asarray(np.stack(nzs))
                ),
            )
            snap = snap._replace(cluster=cluster)
        return snap, meta

    @hot_path
    def solve_encoded_async(
        self, snap: schema.Snapshot, meta: schema.SnapshotMeta
    ) -> DeviceSolve:
        """Dispatch a prebuilt snapshot; the result stays a device future
        (DeviceSolve) and the readback happens on first names()/reasons()
        access — callers overlap it with host work."""
        act = faults.fire("batch.solve", pods=meta.num_pods)
        if (
            meta.features is not None
            and getattr(meta.features, "slices", False)
            and (meta.n_groups or 0) > 0
        ):
            # the gang carve-out dispatch point (chaos seeds 600-604):
            # fail-grade schedules kill the solve here and ride the same
            # retry/breaker containment as batch.solve faults
            faults.fire("solve.carveout", gangs=meta.n_groups)
        slot = self.arbiter
        if slot is not None:
            # multi-lane admission: at most `depth` device programs in
            # flight across every profile lane sharing this device
            slot.acquire()  # graftlint: disable=purity -- lane admission gate BEFORE dispatch, never between dispatch and readback; single-lane configs pass arbiter=None and skip it
        try:
            result = self._dispatch(snap, meta)
        except BaseException:
            if slot is not None:
                slot.release()
            raise
        if act == faults.CORRUPT and getattr(result, "scores", None) is not None:
            # injected device corruption: poison the score tensor so the
            # decode-side health check (SolveUnhealthy) trips
            result = result._replace(
                scores=jnp.full_like(result.scores, jnp.nan)
            )
        self.last_result = result
        ds = DeviceSolve(result, meta)
        ds._slot = slot
        return ds

    def solve_encoded(
        self, snap: schema.Snapshot, meta: schema.SnapshotMeta
    ) -> List[Optional[str]]:
        """Dispatch a prebuilt snapshot and decode node names (blocking)."""
        return self.solve_encoded_async(snap, meta).names()

    def schedule_pending_async(
        self,
        pending: Sequence[api.Pod],
        num_pods_hint: int = 0,
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> Optional[DeviceSolve]:
        """Encode + dispatch one batch without blocking on the device.
        Returns None for an empty batch.  The caller finishes the step
        with finalize_pending() once it wants the names — anything it
        does in between (queue pop window, wave staging) overlaps the
        device solve and the readback."""
        if not pending:
            return None
        if not self.breaker.allow_device():
            # breaker open: the device path is sick; solve on the host
            # (throughput stays > 0 while the cooldown runs)
            return self._host_fallback(
                pending, lock=lock, reservations=reservations
            )
        t0 = time.perf_counter()
        snap, meta = self.encode_pending(
            pending, num_pods_hint=num_pods_hint, lock=lock,
            reservations=reservations,
        )
        t1 = time.perf_counter()
        try:
            ds = self.solve_encoded_async(snap, meta)
        except Exception:  # noqa: BLE001 — device dispatch/compile fault
            logging.getLogger(__name__).exception(
                "device solve dispatch failed; retrying once"
            )
            try:
                ds = self.solve_encoded_async(snap, meta)
            except Exception:  # noqa: BLE001
                self.breaker.record_failure()
                # resident partials AND the resident mirror are fault
                # suspects here, exactly as on finalize_pending's heal
                # wire: a dispatch-time fault can be a poisoned resident
                # surfacing at trace time, and the host fallback below
                # doesn't read either — dropping both also frees their
                # HBM while the breaker cools down (graftcoh finding:
                # this site invalidated only the partials)
                with lock if lock is not None else contextlib.nullcontext():
                    if self._partials is not None:
                        self._partials.invalidate()
                    if self.use_mirror:
                        self._mirror.invalidate()
                logging.getLogger(__name__).exception(
                    "device solve retry failed; breaker open, host fallback"
                )
                return self._host_fallback(
                    pending, lock=lock, reservations=reservations
                )
        ds.encode_s = t1 - t0
        # trace/compile + dispatch-enqueue wall: on a first-of-a-bucket
        # batch this IS the XLA compile (jit blocks until the executable
        # exists); steady-state it is ~0 — the split the bench uses to
        # separate compile churn from real solve regressions
        ds.dispatch_s = ds.dispatched_at - t1
        return ds

    def finalize_pending(
        self,
        pending: Sequence[api.Pod],
        ds: Optional[DeviceSolve],
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> List[Optional[str]]:
        """Decode a dispatched batch (one coalesced readback), record the
        encode/solve/decode wall split, and run the gang admission retry
        if the batch needs it.

        Device faults surfacing at decode time (XLA runtime errors in
        device_get, the SolveUnhealthy non-finite check) retry the solve
        once; a second failure trips the circuit breaker and this batch
        — like every batch until the cooldown's half-open probe — solves
        on the host fallback instead."""
        if ds is None:
            return []
        try:
            names = ds.names()
            if not isinstance(ds, HostSolve):
                self.breaker.record_success()
        except Exception:  # noqa: BLE001 — device readback fault
            logging.getLogger(__name__).exception(
                "device solve readback failed; retrying once"
            )
            try:
                # resident partials AND the resident mirror are fault
                # suspects (a poisoned store/grow surfaces exactly here,
                # as SolveUnhealthy NaN scores): drop both so the
                # retry's encode performs a full recompute / full
                # (RESHARDED) re-upload — the parity gate's recovery
                # wire (solve.partials and mirror.grow CORRUPT grades)
                with lock if lock is not None else contextlib.nullcontext():
                    if self._partials is not None:
                        self._partials.invalidate()
                    if self.use_mirror:
                        self._mirror.invalidate()
                snap, meta = self.encode_pending(
                    pending, lock=lock, reservations=reservations
                )
                ds = self.solve_encoded_async(snap, meta)
                names = ds.names()
                self.breaker.record_success()
            except Exception:  # noqa: BLE001
                self.breaker.record_failure()
                logging.getLogger(__name__).exception(
                    "device solve retry failed; breaker open, host fallback"
                )
                ds = self._host_fallback(
                    pending, lock=lock, reservations=reservations
                )
                names = ds.names()
        # the EFFECTIVE solve for this batch (retry or fallback may have
        # replaced the caller's handle): telemetry readers (wave counts,
        # reason tensors) must touch this one, not the sick original
        self.last_solve = ds
        self.last_timings = {
            "encode_s": getattr(ds, "encode_s", 0.0),
            "compile_s": getattr(ds, "dispatch_s", 0.0),
            "solve_s": ds.deferred_s + ds.decode_wait_s,
            "decode_wait_s": ds.decode_wait_s,
            "decode_overlap_s": ds.deferred_s,
        }
        return self._gang_admission_retry(
            pending, names,
            # the full batch's padded bucket as the hint: without it every
            # binary-search subset size landed in a fresh pad bucket and
            # recompiled on the hot path
            lambda subset: self.schedule_pending_no_retry(
                subset, lock=lock, reservations=reservations,
                num_pods_hint=len(pending),
            ),
        )

    def schedule_pending(
        self,
        pending: Sequence[api.Pod],
        num_pods_hint: int = 0,
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> List[Optional[str]]:
        """One batched scheduling step against the incremental state.
        Returns one node name (or None) per pending pod.  Placements are
        NOT auto-assumed — the host scheduler assumes/binds explicitly."""
        ds = self.schedule_pending_async(
            pending, num_pods_hint=num_pods_hint, lock=lock,
            reservations=reservations,
        )
        return self.finalize_pending(
            pending, ds, lock=lock, reservations=reservations
        )

    def schedule_pending_no_retry(
        self, pending, lock=None, reservations=(), num_pods_hint: int = 0
    ) -> List[Optional[str]]:
        if not self.breaker.allow_device():
            return self._host_fallback(
                pending, lock=lock, reservations=reservations
            ).names()
        snap, meta = self.encode_pending(
            pending, lock=lock, reservations=reservations,
            num_pods_hint=num_pods_hint,
        )
        return self.solve_encoded(snap, meta)

    # -- degraded mode (the circuit breaker's fallback) --------------------

    def _host_fallback(
        self,
        pending: Sequence[api.Pod],
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> HostSolve:
        """Solve one batch on the host: the per-pod exact-evaluation path
        (testing.oracle.Oracle — the independent reference-semantics
        reimplementation the parity suite validates the kernels against)
        over the retained node/pod objects, with the device post-pass's
        gang all-or-nothing mirrored host-side.

        On healthy snapshots with default plugin weights the oracle IS
        scan-parity-identical (tests/test_assign_parity.py), so a tripped
        breaker degrades throughput, not placement quality.  Nominated
        reservations are accounted as bound pods on their nominated
        nodes — a slight over-reservation (ports/labels count too) that
        errs schedulable-pods-safe."""
        from ..testing.oracle import Oracle

        t0 = time.perf_counter()
        with lock if lock is not None else contextlib.nullcontext():
            state = self.state
            nodes = [
                state._node_objs[name]
                for name in state._rows
                if name in state._node_objs
            ]
            oracle = Oracle(
                nodes, fit_strategy=self.score_config.fit_strategy,
                slice_policy=self.carveout_policy,
            )
            by_name = {s.node.meta.name: s for s in oracle.states}
            for key, pod in state._pods.items():
                ns = by_name.get(
                    state._pod_node.get(key) or pod.spec.node_name
                )
                if ns is not None:
                    ns.add_pod(pod)
            for node_name, pod in reservations:
                ns = by_name.get(node_name)
                if ns is not None:
                    ns.add_pod(pod)
            names = oracle.schedule(list(pending))
        # gang all-or-nothing post-pass (ops.assign _gang_release's host
        # mirror): an incomplete gang releases every member
        groups: Dict[str, List[int]] = {}
        for i, p in enumerate(pending):
            g = p.spec.scheduling_group
            if g:
                groups.setdefault(g, []).append(i)
        for idx in groups.values():
            if any(names[i] is None for i in idx):
                for i in idx:
                    names[i] = None
        self.breaker.record_fallback()
        self.last_result = None  # no reason tensor aligns with these names
        hs = HostSolve(names)
        hs.encode_s = time.perf_counter() - t0
        return hs

    def _gang_admission_retry(
        self,
        pending: Sequence[api.Pod],
        names: List[Optional[str]],
        solve_subset,
    ) -> List[Optional[str]]:
        """Gang scarcity packing: when gangs are present and NONE placed
        completely (each went partial and all-or-nothing released all of
        them), admit gangs by priority until capacity runs out.

        The joint solve has no gang-knapsack stage — under scarcity,
        members of every gang interleave onto the same nodes and every
        gang comes back incomplete.  The live scheduler eventually
        self-heals through staggered backoff retries; one-shot callers
        (proto service, extender, bench bursts) would return zero.  The
        fix exploits monotonicity — if the k highest-priority gangs
        don't fit, k+1 don't either — so a binary search over the
        priority-ordered gang prefix finds the maximal admissible set in
        O(log G) extra solves, only on the everything-parked path."""
        groups: Dict[str, List[int]] = {}
        for i, p in enumerate(pending):
            g = p.spec.scheduling_group
            if g:
                groups.setdefault(g, []).append(i)
        if not groups:
            return names
        complete = [
            g for g, idx in groups.items()
            if all(names[i] is not None for i in idx)
        ]
        if complete:
            return names  # scarcity handled: some gang(s) landed
        # `names` belongs to the FULL solve; subset attempts below will
        # overwrite last_result, so keep the aligned one to restore on
        # the no-prefix-fits path (callers read reasons positionally)
        full_result = self.last_result
        # admission order: priority desc, then smaller gangs first
        order = sorted(
            groups,
            key=lambda g: (
                -max(pending[i].spec.priority for i in groups[g]),
                len(groups[g]),
                g,
            ),
        )
        nongang = [
            i for i, p in enumerate(pending) if not p.spec.scheduling_group
        ]

        def attempt(k: int) -> Optional[List[Optional[str]]]:
            idx = list(nongang)
            for g in order[:k]:
                idx.extend(groups[g])
            idx.sort()
            sub = [pending[i] for i in idx]
            sub_names = solve_subset(sub)
            admitted = {
                i for g in order[:k] for i in groups[g]
            }
            pos = {orig: j for j, orig in enumerate(idx)}
            if any(sub_names[pos[i]] is None for i in admitted):
                return None  # an admitted gang still doesn't fit
            out: List[Optional[str]] = [None] * len(pending)
            for orig, j in pos.items():
                out[orig] = sub_names[j]
            return out

        lo, hi, best = 0, len(order), None
        while lo < hi:
            mid = (lo + hi + 1) // 2
            got = attempt(mid)
            if got is not None:
                best, lo = got, mid
            else:
                hi = mid - 1
        if best is None:
            self.last_result = full_result  # re-align reasons with names
            return names
        # last_result belongs to the final SUBSET solve — its reasons no
        # longer align with the merged name list; unplaced pods here are
        # unadmitted gang members (REASON_GANG by construction)
        self.last_result = None
        return best

    # -- stateless (one-shot) ---------------------------------------------

    def snapshot(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
        num_pods_hint: int = 0,
    ) -> Tuple[schema.Snapshot, schema.SnapshotMeta]:
        return self.builder.build(
            nodes, pending, bound_pods=bound, num_pods_hint=num_pods_hint
        )

    def schedule(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
    ) -> List[Optional[str]]:
        if not pending:
            return []

        def solve(pods):
            # pad every gang-retry subset into the full batch's bucket so
            # the binary search reuses one executable instead of
            # compiling one per subset size
            snap, meta = self.snapshot(
                nodes, pods, bound, num_pods_hint=len(pending)
            )
            # derive the routing statics host-side while the snapshot is
            # host-resident (the stateless twin of encode_pending's
            # derivation) so the dispatch path never re-probes device
            # arrays, then decode through DeviceSolve: ONE coalesced
            # device_get instead of a bare np.asarray readback per
            # gang-retry subset solve (a graftlint purity finding —
            # each bare readback paid a blocking round-trip)
            meta.features = assign_ops.features_of(
                snap, slice_policy=self.carveout_policy
            )
            meta.topo_split = assign_ops.required_topo_z_split(snap)
            meta.n_groups = schema.num_groups(snap)
            meta.tie_k = auction_ops.default_tie_k(snap)
            meta.route = self._route(
                snap, meta.features, meta.topo_split, meta.n_groups
            )
            if meta.route == "wavefront":
                meta.wave_plan = assign_ops.plan_waves(
                    snap, features=meta.features, wave_cap=self.wave_cap
                )
            return self.solve_encoded_async(snap, meta).names()

        return self._gang_admission_retry(pending, solve(pending), solve)
