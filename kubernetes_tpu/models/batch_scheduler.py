"""TPUBatchScheduler — the flagship model: snapshot in, assignments out.

Wraps the ops kernels into the one-dispatch scheduling step the rest of
the framework (host scheduler, extender endpoint, benchmarks) calls.  The
north-star replacement for the reference's per-pod scheduling cycle
(pkg/scheduler/schedule_one.go:66): one compiled program filters, scores,
and assigns an entire pending batch with assume-bookkeeping carried on
device.

Two solver paths, routed automatically:
  * greedy scan (ops.assign) — exact one-pod-at-a-time reference
    semantics; handles every constraint family, including gang
    all-or-nothing via its post-pass (ops.assign n_groups).
  * auction (ops.auction) — joint parallel solve for large bursts and
    gang groups; static+resource families only.

Gangs therefore keep all-or-nothing semantics on BOTH routes: a gang
carrying spread/interpod/port constraints routes to greedy and its
incomplete placements are released by the post-pass.

Cluster state is incremental (ops.schema.ClusterState): node and pod
changes touch one tensor row, and per-batch encode cost is O(pending),
the cache.go:185-260 UpdateSnapshot property.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as api
from ..ops import assign as assign_ops
from ..ops import auction as auction_ops
from ..ops import schema
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig

Result = Union[assign_ops.SolveResult, auction_ops.AuctionResult]


class TPUBatchScheduler:
    """Owns the incremental cluster state (persistent vocabularies) and
    the jitted solvers.

    Stateless usage (one-shot):
        sched = TPUBatchScheduler()
        placements = sched.schedule(nodes, pending_pods, bound_pods)

    Incremental usage (the host scheduler's path):
        sched.add_node(n) / sched.remove_node(name)
        sched.assume(pod, node_name) / sched.forget(pod)
        placements = sched.schedule_pending(pending_pods)
    """

    def __init__(
        self,
        score_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        limits: Optional[schema.SnapshotLimits] = None,
        mode: str = "auto",  # auto | greedy | auction
    ):
        self.builder = schema.SnapshotBuilder(limits)
        self.state = schema.ClusterState(self.builder)
        self.score_config = score_config
        self.mode = mode
        self._greedy = assign_ops.greedy_assign_jit(score_config)
        self._auction = auction_ops.auction_assign_jit(score_config)
        self.last_result: Optional[Result] = None

    # -- incremental cluster state ---------------------------------------

    def add_node(self, node: api.Node) -> None:
        self.state.add_node(node)

    def update_node(self, node: api.Node) -> None:
        self.state.update_node(node)

    def remove_node(self, name: str) -> None:
        self.state.remove_node(name)

    def assume(self, pod: api.Pod, node_name: str) -> None:
        """Account a placement immediately (cache.go AssumePod)."""
        self.state.add_pod(pod, node_name)

    def forget(self, pod: api.Pod) -> None:
        """Undo an assume / remove a bound pod (ForgetPod/RemovePod)."""
        self.state.remove_pod(pod)

    # -- scheduling -------------------------------------------------------

    def _route(
        self, snap: schema.Snapshot, features: assign_ops.FeatureFlags
    ) -> str:
        if self.mode != "auto":
            return self.mode
        has_gangs = auction_ops.num_groups(snap) > 0
        if has_gangs and auction_ops.auction_features_ok(features):
            return "auction"
        return "greedy"

    def solve(
        self, snap: schema.Snapshot, topo_z: Optional[int] = None
    ) -> assign_ops.SolveResult:
        """Raw greedy device solve on a prebuilt snapshot.

        topo_z is auto-derived when not given; passing a value smaller
        than required aliases topology domains together and silently
        corrupts spread/inter-pod state, so it is validated (when those
        families are active — it is unused otherwise)."""
        features = assign_ops.features_of(snap)
        if features.spread or features.interpod:
            required = assign_ops.required_topo_z(snap)
            if topo_z is None:
                topo_z = required
            elif topo_z < required:
                raise ValueError(
                    f"topo_z={topo_z} < required_topo_z={required}: would "
                    "alias topology values (see ops.assign.required_topo_z)"
                )
        return self._greedy(snap, topo_z, features)

    def _dispatch(self, snap: schema.Snapshot) -> Result:
        features = assign_ops.features_of(snap)
        route = self._route(snap, features)
        if route == "auction":
            return self._auction(snap, features=features)
        topo_z = (
            assign_ops.required_topo_z(snap)
            if (features.spread or features.interpod)
            else 1
        )
        return self._greedy(snap, topo_z, features)

    def encode_pending(
        self,
        pending: Sequence[api.Pod],
        num_pods_hint: int = 0,
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> Tuple[schema.Snapshot, schema.SnapshotMeta]:
        """Encode pending pods + live cluster state into a device-resident
        snapshot.  `lock` (the scheduler cache's mutex) is held across the
        encode AND the device transfer: build_from_state returns views
        aliasing live arrays that informer threads mutate, and both sides
        intern into the shared vocabularies — the reference holds the cache
        mutex for UpdateSnapshot (cache.go:185) for the same reason.
        The transfer MUST copy: build_from_state returns views aliasing the
        live arrays, and on the CPU backend jax.device_put can zero-copy
        alias a numpy buffer — a later cache mutation would then leak into
        an already-"materialized" snapshot (observed: preemption's verify
        restore undoing its own victim removal mid-solve).  jnp.array
        guarantees a copy on every backend; on accelerators it is the same
        host→device transfer device_put does.

        reservations: (node_name, pod) pairs whose requests overlay the
        named node's usage in THIS snapshot only — nominated preemptors
        waiting to land (the filters-with-nominated-pods analogue,
        runtime/framework.go:962).  The overlay is applied to the device
        copy; live state is untouched."""
        with lock if lock is not None else contextlib.nullcontext():
            snap, meta = self.builder.build_from_state(
                self.state, pending, num_pods_hint=num_pods_hint
            )
            rows, reqs, nzs = [], [], []
            for node_name, pod in reservations:
                row = self.state._rows.get(node_name)
                if row is None:
                    continue  # nominated node left the cluster
                req, nz, _ = self.builder.pod_usage(pod, self.state._r)
                rows.append(row)
                reqs.append(req)
                nzs.append(nz)
            snap = jax.tree.map(jnp.array, snap)
        if rows:
            idx = jnp.asarray(np.array(rows, dtype=np.int32))
            cluster = snap.cluster._replace(
                requested=snap.cluster.requested.at[idx].add(
                    jnp.asarray(np.stack(reqs))
                ),
                nonzero_requested=snap.cluster.nonzero_requested.at[idx].add(
                    jnp.asarray(np.stack(nzs))
                ),
            )
            snap = snap._replace(cluster=cluster)
        return snap, meta

    def solve_encoded(
        self, snap: schema.Snapshot, meta: schema.SnapshotMeta
    ) -> List[Optional[str]]:
        """Dispatch a prebuilt snapshot and decode node names."""
        result = self._dispatch(snap)
        self.last_result = result
        idx = np.asarray(result.assignment)[: meta.num_pods]
        return [meta.node_name(int(i)) for i in idx]

    def schedule_pending(
        self,
        pending: Sequence[api.Pod],
        num_pods_hint: int = 0,
        lock=None,
        reservations: Sequence[Tuple[str, api.Pod]] = (),
    ) -> List[Optional[str]]:
        """One batched scheduling step against the incremental state.
        Returns one node name (or None) per pending pod.  Placements are
        NOT auto-assumed — the host scheduler assumes/binds explicitly."""
        if not pending:
            return []
        snap, meta = self.encode_pending(
            pending, num_pods_hint=num_pods_hint, lock=lock,
            reservations=reservations,
        )
        return self.solve_encoded(snap, meta)

    # -- stateless (one-shot) ---------------------------------------------

    def snapshot(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
    ) -> Tuple[schema.Snapshot, schema.SnapshotMeta]:
        return self.builder.build(nodes, pending, bound_pods=bound)

    def schedule(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
    ) -> List[Optional[str]]:
        if not pending:
            return []
        snap, meta = self.snapshot(nodes, pending, bound)
        result = self._dispatch(snap)
        self.last_result = result
        idx = np.asarray(result.assignment)[: meta.num_pods]
        return [meta.node_name(int(i)) for i in idx]
