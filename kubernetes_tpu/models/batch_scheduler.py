"""TPUBatchScheduler — the flagship model: snapshot in, assignments out.

Wraps the ops kernels into the one-dispatch scheduling step the rest of
the framework (host scheduler, extender endpoint, benchmarks) calls.  The
north-star replacement for the reference's per-pod scheduling cycle
(pkg/scheduler/schedule_one.go:66): one compiled program filters, scores,
and greedily assigns an entire pending batch with assume-bookkeeping
carried on device.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..api import types as api
from ..ops import assign as assign_ops
from ..ops import schema
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig


class TPUBatchScheduler:
    """Owns a SnapshotBuilder (persistent vocabularies) and a jitted solver.

    Usage:
        sched = TPUBatchScheduler()
        placements = sched.schedule(nodes, pending_pods, bound_pods)
        # placements: list[node-name or None], one per pending pod
    """

    def __init__(
        self,
        score_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        limits: Optional[schema.SnapshotLimits] = None,
    ):
        self.builder = schema.SnapshotBuilder(limits)
        self.score_config = score_config
        self._solver = assign_ops.greedy_assign_jit(score_config)
        self.last_result: Optional[assign_ops.SolveResult] = None

    def snapshot(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
    ) -> Tuple[schema.Snapshot, schema.SnapshotMeta]:
        return self.builder.build(nodes, pending, bound_pods=bound)

    def schedule(
        self,
        nodes: Sequence[api.Node],
        pending: Sequence[api.Pod],
        bound: Sequence[api.Pod] = (),
    ) -> List[Optional[str]]:
        if not pending:
            return []
        snap, meta = self.snapshot(nodes, pending, bound)
        result = self._solver(snap, meta.topo_z)
        self.last_result = result
        idx = np.asarray(result.assignment)[: meta.num_pods]
        return [meta.node_name(int(i)) for i in idx]

    def solve(
        self, snap: schema.Snapshot, topo_z: Optional[int] = None
    ) -> assign_ops.SolveResult:
        """Raw device-side solve on a prebuilt snapshot.

        topo_z is auto-derived (required_topo_z) when not given; passing a
        value smaller than required aliases topology domains together and
        silently corrupts spread/inter-pod state, so it is validated."""
        required = assign_ops.required_topo_z(snap)
        if topo_z is None:
            topo_z = required
        elif topo_z < required:
            raise ValueError(
                f"topo_z={topo_z} < required_topo_z={required}: would alias "
                "topology values together (see ops.assign.required_topo_z)"
            )
        return self._solver(snap, topo_z)
