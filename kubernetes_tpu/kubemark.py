"""Hollow nodes: control-plane scale simulation without real kubelets.

Reference: pkg/kubemark/hollow_kubelet.go:63-87 — a real kubelet loop
against a no-op runtime, used to exercise 5k-node control planes.  Ours
registers Node objects, heartbeats them through the API (MODIFIED events
— the NodeUpdate churn a real cluster produces), and plays the kubelet
status half: bound pods transition to Running, so Jobs and controllers
see lifecycle progress.

Two layers:

  HollowCluster  the hollow kubelet fleet.  Heartbeats are BATCHED —
                 each tick commits one ``Store.update_wave`` over its
                 node slice (one lock acquisition, one coalesced journal
                 append, one watch fan-out handoff on the Node shard)
                 instead of O(batch) single-object writes, and the tick
                 is jittered so a 100k-node fleet doesn't monopolize the
                 Node shard in phase-locked bursts.
  NodeGroupScaler  the autoscaler-in-the-loop half (bench
                 ``c12_autoscale_churn``): a named node group scaled
                 up/down through the API (or replayed as a frozen
                 trace), with a cluster-autoscaler-shaped reconcile
                 policy — the sustained node add/remove stream the
                 elastic node axis exists to absorb.
  FleetHarness   the first-class fleet driver (bench ``c8_store_100k``):
                 registers up to 100k hollow nodes, runs a SUSTAINED
                 pod-lifecycle soak (create → bind via per-shard
                 update_wave sub-waves committed concurrently → hollow
                 kubelets run them → delete) across many namespaces so
                 the waves exercise the sharded store, and reports
                 SLO-style p50/p90/p99 lifecycle latency plus
                 lost/double-bound counts.

This drives the FULL store/watch/journal path at fleet scale — the
thing the solver bench can't see (VERDICT missing #10; ROADMAP's
"heavy traffic from millions of users" axis)."""

from __future__ import annotations

import random
import threading
import time
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from .api import store as st
from .api import types as api
from .testing.wrappers import GI, MI, make_node, make_pod


def percentiles(samples: List[float]) -> Dict[str, float]:
    """SLO-style latency summary: p50/p90/p99 by nearest-rank over the
    sample list (empty list reports zeros)."""
    if not samples:
        return {"p50": 0.0, "p90": 0.0, "p99": 0.0}
    s = sorted(samples)
    n = len(s)

    def rank(q: float) -> float:
        return s[min(n - 1, max(0, int(q * n + 0.5) - 1))]

    return {"p50": rank(0.50), "p90": rank(0.90), "p99": rank(0.99)}


class HollowCluster:
    def __init__(
        self,
        store: st.Store,
        n_nodes: int,
        zones: int = 8,
        cpu_milli: int = 32000,
        mem: int = 64 * GI,
        pods_cap: int = 110,
        heartbeat_interval: float = 10.0,
        run_pods: bool = True,
        # fraction of the tick period each sleep is jittered by (±):
        # de-phases heartbeat waves so the fleet never lands on the Node
        # shard in lockstep with the binder's sub-waves
        heartbeat_jitter: float = 0.2,
    ):
        self.store = store
        self.n_nodes = n_nodes
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_jitter = heartbeat_jitter
        self.run_pods = run_pods
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.node_names = [f"hollow-{i}" for i in range(n_nodes)]
        # observability: wave-committed heartbeat batches (tests assert
        # the loop batches instead of issuing per-node writes)
        self.heartbeat_waves = 0
        self.heartbeats = 0
        self._specs = [
            make_node(name)
            .capacity(cpu_milli=cpu_milli, mem=mem, pods=pods_cap)
            .zone(f"zone-{i % zones}")
            .obj()
            for i, name in enumerate(self.node_names)
        ]

    def register(self) -> None:
        """Create every Node through the API (the kubelet registration)."""
        for node in self._specs:
            try:
                self.store.create(node)
            except st.AlreadyExists:
                pass

    def start(self) -> "HollowCluster":
        self.register()
        t = threading.Thread(
            target=self._heartbeat_loop, name="hollow-heartbeat", daemon=True
        )
        t.start()
        self._threads.append(t)
        if self.run_pods:
            t = threading.Thread(
                target=self._pod_runner, name="hollow-pod-runner", daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    # -- loops -------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        """Round-robin status heartbeats (nodeStatusUpdateFrequency),
        BATCHED: each jittered tick commits its node slice through ONE
        ``update_wave`` — one lock acquisition, one coalesced journal
        append and one fan-out handoff on the Node shard, instead of
        O(batch) single-object writes — so the harness itself never
        monopolizes the shard it shares with real Node traffic."""
        i = 0
        per_tick = max(1, self.n_nodes // 10)
        tick = self.heartbeat_interval / 10
        rng = random.Random(0x5EED ^ self.n_nodes)
        j = self.heartbeat_jitter
        while not self._stop.wait(tick * (1.0 + rng.uniform(-j, j))):
            batch = [
                self.node_names[(i + k) % self.n_nodes]
                for k in range(min(per_tick, self.n_nodes))
            ]
            i = (i + per_tick) % self.n_nodes
            now = str(time.time())

            def beat(node) -> None:
                node.meta.annotations["hollow/heartbeat"] = now

            try:
                applied, _ = self.store.update_wave(
                    "Node", [(name, "", beat) for name in batch]
                )
            except Exception:  # noqa: BLE001 — heartbeat best-effort
                continue
            self.heartbeat_waves += 1
            self.heartbeats += len(applied)

    def _pod_runner(self) -> None:
        """The kubelet status half: bound Pending pods become Running
        (status written through the API, like status manager PATCHes).
        A watch the store EXPIRED for falling behind (coalescing
        overflow sets .stopped too) is re-established with a catch-up
        list — the reflector contract; the store never destructively
        terminates a slow watcher."""
        w = self.store.watch("Pod")
        try:
            while not self._stop.is_set():
                if w.stopped:
                    w.stop()
                    pods, rv = self.store.list("Pod")
                    for pod in pods:
                        self._maybe_run(pod)  # catch up on missed binds
                    # resume FROM the list's rv: binds landing between
                    # the snapshot and the new watch must not vanish
                    w = self.store.watch("Pod", from_rv=rv)
                    continue
                ev = w.get(timeout=0.2)
                if ev is None:
                    continue
                pod = ev.obj
                if ev.type in (st.ADDED, st.MODIFIED):
                    self._maybe_run(pod)
        finally:
            w.stop()

    def _maybe_run(self, pod) -> None:
        if (
            pod.spec.node_name
            and pod.spec.node_name.startswith("hollow-")
            and pod.status.phase == "Pending"
        ):
            try:
                fresh = self.store.get(
                    "Pod", pod.meta.name, pod.meta.namespace
                )
                if fresh.status.phase == "Pending" and fresh.spec.node_name:
                    fresh.status.phase = "Running"
                    self.store.update(fresh, force=True)
            except st.NotFound:
                pass


class NodeGroupScaler:
    """Autoscaler-in-the-loop node-group driver — the cluster-autoscaler
    half kubemark didn't model.  Owns a named group of hollow nodes and
    scales it toward a target: `scale_to` creates the missing members
    (highest index first to appear, lowest removed last) and deletes the
    surplus, returning the (added nodes, removed names) so a
    frozen-trace harness can replay the exact churn against a solver
    pair; with a Store attached the membership changes also commit
    through the API (create/delete → informers → scheduler cache), the
    live-loop shape bench c12 drives.

    `reconcile` is the bundled scale policy (the CA loop's core):
    scale UP by ceil(pending / pods_per_node) when pods are pending,
    scale DOWN one `step` at a time once idle capacity exceeds a full
    step plus `idle_headroom` nodes — asymmetric on purpose, like the
    reference autoscaler's eager-up / conservative-down posture (the
    ClusterState's bucket-shrink dwell provides the second layer of
    hysteresis underneath)."""

    def __init__(
        self,
        store: Optional[st.Store] = None,
        group: str = "autoscale",
        cpu_milli: int = 32000,
        mem: int = 64 * GI,
        pods_cap: int = 110,
        zones: int = 8,
        max_nodes: int = 1 << 20,
        taints: Optional[List[tuple]] = None,
    ):
        self.store = store
        self.group = group
        self.cpu_milli = cpu_milli
        self.mem = mem
        self.pods_cap = pods_cap
        self.zones = zones
        self.max_nodes = max_nodes
        self.taints = list(taints or [])
        self._size = 0
        self._next_id = 0
        self._members: List[str] = []  # creation order; drain from the tail
        # observability (bench c12 reports them)
        self.scale_ups = 0
        self.scale_downs = 0
        self.nodes_added = 0
        self.nodes_removed = 0

    def size(self) -> int:
        return self._size

    def _make_node(self, i: int):
        w = (
            make_node(f"{self.group}-{i}")
            .capacity(
                cpu_milli=self.cpu_milli, mem=self.mem, pods=self.pods_cap
            )
            .zone(f"zone-{i % self.zones}")
        )
        for key, value, effect in self.taints:
            w = w.taint(key, value, effect)
        return w.obj()

    def scale_to(self, target: int):
        """Drive the group to `target` members.  Returns
        (added_node_objects, removed_node_names); store-backed groups
        also commit the changes through the API."""
        target = max(0, min(int(target), self.max_nodes))
        added, removed = [], []
        while self._size < target:
            node = self._make_node(self._next_id)
            self._next_id += 1
            if self.store is not None:
                try:
                    self.store.create(node)
                except st.AlreadyExists:
                    pass
            self._members.append(node.meta.name)
            added.append(node)
            self._size += 1
        while self._size > target:
            name = self._members.pop()  # newest first: oldest nodes pin
            if self.store is not None:
                try:
                    self.store.delete("Node", name)
                except st.NotFound:
                    pass
            removed.append(name)
            self._size -= 1
        if added:
            self.scale_ups += 1
            self.nodes_added += len(added)
        if removed:
            self.scale_downs += 1
            self.nodes_removed += len(removed)
        return added, removed

    def reconcile(
        self,
        pending: int,
        pods_per_node: int,
        idle_nodes: int = 0,
        step: int = 1,
        idle_headroom: int = 0,
        up_step_cap: int = 0,
    ):
        """One autoscaler pass: returns scale_to()'s (added, removed)
        for the policy's chosen target (no-op → ([], [])).
        `up_step_cap` (0 = unbounded) bounds one pass's scale-up so a
        tight reconcile loop ramps instead of bursting — bursts dirty
        more rows than the mirror's delta/grow path can absorb and
        force full re-uploads (the over-fraction safety path)."""
        per = max(1, int(pods_per_node))
        if pending > 0:
            up = (pending + per - 1) // per
            if up_step_cap > 0:
                up = min(up, up_step_cap)
            return self.scale_to(min(self._size + up, self.max_nodes))
        if idle_nodes > max(0, idle_headroom) + max(1, step):
            return self.scale_to(max(0, self._size - max(1, step)))
        return [], []


class _LifecycleAudit:
    """Watches the Pod stream and records, per pod key: the node(s) it
    was ever bound to (double-bind detection) and the instant it was
    first observed Running (lifecycle-latency half).  Poll-style
    consumer: an Expired stream relists and resumes, so audit coverage
    survives overload."""

    def __init__(self, store: st.Store):
        self.store = store
        self.bound_nodes: Dict[str, set] = defaultdict(set)
        self.running_at: Dict[str, float] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="fleet-audit", daemon=True
        )
        self._thread.start()

    def _note(self, pod) -> None:
        key = f"{pod.meta.namespace}/{pod.meta.name}"
        with self._mu:
            if pod.spec.node_name:
                self.bound_nodes[key].add(pod.spec.node_name)
            if pod.status.phase == "Running" and key not in self.running_at:
                self.running_at[key] = time.perf_counter()

    def _run(self) -> None:
        w = self.store.watch("Pod")
        try:
            while not self._stop.is_set():
                if w.stopped:
                    w.stop()
                    pods, rv = self.store.list("Pod")
                    for pod in pods:
                        self._note(pod)
                    w = self.store.watch("Pod", from_rv=rv)
                    continue
                ev = w.get(timeout=0.2)
                if ev is None:
                    continue
                if ev.type in (st.ADDED, st.MODIFIED):
                    self._note(ev.obj)
        finally:
            w.stop()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def double_bound(self) -> Dict[str, set]:
        with self._mu:
            return {
                k: set(v) for k, v in self.bound_nodes.items() if len(v) > 1
            }

    def first_running(self, key: str) -> Optional[float]:
        with self._mu:
            return self.running_at.get(key)


class FleetHarness:
    """The first-class hollow-node fleet driver: a HollowCluster plus a
    sustained pod-lifecycle soak with SLO-style reporting.

    ``soak`` runs rounds of: create `round_pods` pods spread across
    `namespaces` (so they hash across store shards), bind each
    namespace's slice through its own ``update_wave`` sub-wave — the
    sub-waves commit CONCURRENTLY, the binder-overlap shape the sharded
    store exists for — wait for the hollow kubelets to run every pod
    (recording per-pod create→Running latency), then delete the round.
    The audit watcher independently verifies no pod is ever bound to
    two nodes and no created pod is lost."""

    def __init__(
        self,
        store: st.Store,
        n_nodes: int,
        namespaces: int = 8,
        heartbeat_interval: float = 30.0,
        bind_concurrency: int = 4,
        zones: int = 16,
    ):
        self.store = store
        self.namespaces = [f"fleet-{i}" for i in range(namespaces)]
        self.hollow = HollowCluster(
            store, n_nodes,
            zones=zones,
            heartbeat_interval=heartbeat_interval,
            run_pods=True,
        )
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, bind_concurrency),
            thread_name_prefix="fleet-bind",
        )
        self.audit: Optional[_LifecycleAudit] = None

    def start(self) -> "FleetHarness":
        self.audit = _LifecycleAudit(self.store)
        self.hollow.start()
        return self

    def stop(self) -> None:
        self.hollow.stop()
        if self.audit is not None:
            self.audit.stop()
        self._pool.shutdown(wait=False)

    # -- the sustained lifecycle soak --------------------------------------

    def _bind_round(self, keys: List[tuple]) -> int:
        """Bind one round's pods round-robin onto hollow nodes: one
        update_wave sub-wave per namespace (each a single-shard atomic
        transaction), committed concurrently on the pool."""
        n_nodes = self.hollow.n_nodes
        by_ns: Dict[str, List[tuple]] = defaultdict(list)
        for idx, (name, ns) in enumerate(keys):
            by_ns[ns].append((name, f"hollow-{(idx * 131) % n_nodes}"))

        def bind_ns(ns, entries):
            def mutator(node_name):
                def mutate(pod) -> None:
                    if pod.spec.node_name and pod.spec.node_name != node_name:
                        raise st.Conflict(
                            f"pod already bound to {pod.spec.node_name}"
                        )
                    pod.spec.node_name = node_name
                return mutate

            applied, errors = self.store.update_wave(
                "Pod",
                [(name, ns, mutator(node)) for name, node in entries],
            )
            return len(applied)

        futures = [
            self._pool.submit(bind_ns, ns, entries)
            for ns, entries in by_ns.items()
        ]
        return sum(f.result() for f in futures)

    def soak(
        self,
        total_pods: int,
        round_pods: int = 1024,
        cpu_milli: int = 50,
        round_timeout: float = 60.0,
    ) -> Dict[str, object]:
        """Run the sustained lifecycle soak; returns the SLO report."""
        assert self.audit is not None, "start() the harness first"
        latencies: List[float] = []
        lost: List[str] = []
        created = 0
        rounds = 0
        bind_s = 0.0
        t0 = time.perf_counter()
        while created < total_pods:
            n = min(round_pods, total_pods - created)
            keys = []
            t_create = time.perf_counter()
            for k in range(n):
                i = created + k
                ns = self.namespaces[i % len(self.namespaces)]
                pod = (
                    make_pod(f"soak-{i}")
                    .req(cpu_milli=cpu_milli, mem=8 * MI)
                    .obj()
                )
                pod.meta.namespace = ns
                self.store.create(pod)
                keys.append((f"soak-{i}", ns))
            created += n
            rounds += 1
            t_bind = time.perf_counter()
            self._bind_round(keys)
            bind_s += time.perf_counter() - t_bind
            # wait for the hollow kubelets: every pod of the round must
            # reach Running inside the round budget or count as lost
            deadline = time.monotonic() + round_timeout
            pending = {f"{ns}/{name}" for name, ns in keys}
            while pending and time.monotonic() < deadline:
                done = {
                    k for k in pending
                    if self.audit.first_running(k) is not None
                }
                pending -= done
                if pending:
                    time.sleep(0.01)
            for name, ns in keys:
                key = f"{ns}/{name}"
                at = self.audit.first_running(key)
                if at is None:
                    lost.append(key)
                else:
                    latencies.append(at - t_create)
            # the delete half of the lifecycle: the round leaves the
            # store (sustained churn, not unbounded growth)
            for name, ns in keys:
                try:
                    self.store.delete("Pod", name, ns)
                except st.NotFound:
                    pass
        wall = time.perf_counter() - t0
        pct = percentiles(latencies)
        return {
            "nodes": self.hollow.n_nodes,
            "pods": created,
            "rounds": rounds,
            "soak_wall_s": round(wall, 4),
            "lifecycle_pods_per_s": round(created / wall, 1) if wall else 0.0,
            "lifecycle_p50_ms": round(pct["p50"] * 1000, 2),
            "lifecycle_p90_ms": round(pct["p90"] * 1000, 2),
            "lifecycle_p99_ms": round(pct["p99"] * 1000, 2),
            "lost_pods": len(lost),
            "double_bound_pods": len(self.audit.double_bound()),
            # wall share each round spent inside the concurrent
            # per-shard bind sub-waves (the commit half of the step)
            "bind_s_total": round(bind_s, 4),
            "commit_share_per_step": round(bind_s / wall, 4) if wall else 0.0,
            "heartbeat_waves": self.hollow.heartbeat_waves,
            "heartbeats": self.hollow.heartbeats,
        }

    # -- the serving-plane phase (bench c13_serving_fleet) ----------------

    def serve(
        self,
        replicas: int = 2,
        informers: int = 1000,
        soak_pods: int = 2048,
        round_pods: int = 512,
        sample: int = 32,
        cpu_milli: int = 50,
        kill_replica: bool = True,
        sync_timeout: float = 60.0,
        round_timeout: float = 60.0,
        recovery_budget_s: float = 30.0,
    ) -> Dict[str, object]:
        """Fleet-scale serving soak: `informers` multiplexed HTTP watch
        streams over a `replicas`-wide :class:`APIServerReplicaSet`,
        pods created THROUGH the HTTP path (round-robin across
        replicas) and bound via the store's wave path, with a mid-soak
        replica kill + restart.  Reports p99 watch-delivery latency
        (create-call → event delivery on the sampled informers),
        failover/recovery health (no wedged watcher, recovery within
        budget) and the serving-plane gauges.

        The latency sample covers the first `sample` informers: the
        mux delivers every event to every informer, but recording
        per-event timestamps across thousands of caches would measure
        the recorder, not the plane."""
        assert self.audit is not None, "start() the harness first"
        from .api.server import APIServerReplicaSet
        from .client.rest import RestClient
        from .client.watchmux import HttpWatchMux

        plane = APIServerReplicaSet(self.store, replicas=replicas)
        commit_at: Dict[str, float] = {}
        latencies: List[float] = []

        def observer(typ, obj, rv, recv_ts):
            if typ != "ADDED":
                return
            t0 = commit_at.get(f"{obj.meta.namespace}/{obj.meta.name}")
            if t0 is not None:
                latencies.append(recv_ts - t0)

        mux = HttpWatchMux(plane.urls())
        infs = [
            mux.add_informer(
                "Pod", on_event=observer if i < sample else None
            )
            for i in range(informers)
        ]
        mux.start()
        recovery_ms: Optional[float] = None
        wedged = 0
        created = 0
        rounds = 0
        try:
            deadline = time.monotonic() + sync_timeout
            while time.monotonic() < deadline and not all(
                i.synced for i in infs
            ):
                time.sleep(0.02)
            unsynced = sum(1 for i in infs if not i.synced)
            clients = [RestClient(u) for u in plane.urls()]
            kill_at = soak_pods // 2
            watched = infs[: max(1, sample)]
            t0 = time.perf_counter()
            while created < soak_pods:
                n = min(round_pods, soak_pods - created)
                keys = []
                for k in range(n):
                    i = created + k
                    ns = self.namespaces[i % len(self.namespaces)]
                    name = f"serve-{i}"
                    pod = (
                        make_pod(name)
                        .req(cpu_milli=cpu_milli, mem=8 * MI)
                        .obj()
                    )
                    pod.meta.namespace = ns
                    commit_at[f"{ns}/{name}"] = time.monotonic()
                    clients[i % len(clients)].create(pod)
                    keys.append((name, ns))
                created += n
                rounds += 1
                self._bind_round(keys)
                # the round's events must reach the sampled informers
                # before the next round floods in (bounded, not exact:
                # stragglers show up in the latency tail / lost count)
                rdl = time.monotonic() + round_timeout
                want = {f"{ns}/{name}" for name, ns in keys}
                while time.monotonic() < rdl and any(
                    not want <= set(w.cache) for w in watched
                ):
                    time.sleep(0.01)
                if kill_replica and recovery_ms is None and (
                    created >= kill_at
                ):
                    # mid-soak replica death: every stream on the dead
                    # replica must fail over and converge on a marker
                    # pod created after the kill, within budget
                    t_kill = time.monotonic()
                    plane.kill(0)
                    clients = [RestClient(u) for u in plane.urls()]
                    marker = (
                        make_pod("serve-marker")
                        .req(cpu_milli=cpu_milli, mem=8 * MI)
                        .obj()
                    )
                    marker.meta.namespace = self.namespaces[0]
                    mkey = f"{self.namespaces[0]}/serve-marker"
                    commit_at[mkey] = time.monotonic()
                    clients[0].create(marker)
                    rdl = time.monotonic() + recovery_budget_s
                    while time.monotonic() < rdl and any(
                        mkey not in i.cache for i in infs
                    ):
                        time.sleep(0.02)
                    wedged = sum(1 for i in infs if mkey not in i.cache)
                    recovery_ms = (time.monotonic() - t_kill) * 1000
                    plane.restart(0)
                    mux.set_urls(plane.urls())
                    clients = [RestClient(u) for u in plane.urls()]
            wall = time.perf_counter() - t0
            # lost = created pods a sampled informer never delivered
            lost = sum(
                1 for key in commit_at if key not in watched[0].cache
            )
            pct = percentiles(latencies)
            report: Dict[str, object] = {
                "replicas": replicas,
                "informers": informers,
                "serve_pods": created,
                "serve_rounds": rounds,
                "serve_wall_s": round(wall, 4),
                "watch_events_delivered": sum(
                    i.events_delivered for i in infs
                ),
                "watch_p50_ms": round(pct["p50"] * 1000, 2),
                "watch_p90_ms": round(pct["p90"] * 1000, 2),
                "watch_p99_ms": round(pct["p99"] * 1000, 2),
                "rv_violations": len(mux.violations()),
                "informer_failovers": sum(i.failovers for i in infs),
                "informer_relists": sum(i.relists for i in infs),
                "unsynced_informers": unsynced,
                "recovery_ms": (
                    round(recovery_ms, 1) if recovery_ms is not None
                    else None
                ),
                "wedged_watchers": wedged,
                "lost_watch_pods": lost,
                "double_bound_pods": len(self.audit.double_bound()),
            }
            report.update(plane.serving_stats())
            return report
        finally:
            mux.stop()
            plane.stop()
            # the serving round's pods leave the store (the soak halves
            # share the harness; growth here would skew a later phase)
            for key in list(commit_at):
                ns, _, name = key.partition("/")
                try:
                    self.store.delete("Pod", name, ns)
                except st.NotFound:
                    pass
