"""Hollow nodes: control-plane scale simulation without real kubelets.

Reference: pkg/kubemark/hollow_kubelet.go:63-87 — a real kubelet loop
against a no-op runtime, used to exercise 5k-node control planes.  Ours
registers Node objects, heartbeats them through the API (MODIFIED events
— the NodeUpdate churn a real cluster produces), and plays the kubelet
status half: bound pods transition to Running, so Jobs and controllers
see lifecycle progress.

This drives the FULL store/informer/queue path — the thing the solver
bench can't see (VERDICT missing #10)."""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from .api import store as st
from .api import types as api
from .testing.wrappers import GI, make_node


class HollowCluster:
    def __init__(
        self,
        store: st.Store,
        n_nodes: int,
        zones: int = 8,
        cpu_milli: int = 32000,
        mem: int = 64 * GI,
        pods_cap: int = 110,
        heartbeat_interval: float = 10.0,
        run_pods: bool = True,
    ):
        self.store = store
        self.n_nodes = n_nodes
        self.heartbeat_interval = heartbeat_interval
        self.run_pods = run_pods
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.node_names = [f"hollow-{i}" for i in range(n_nodes)]
        self._specs = [
            make_node(name)
            .capacity(cpu_milli=cpu_milli, mem=mem, pods=pods_cap)
            .zone(f"zone-{i % zones}")
            .obj()
            for i, name in enumerate(self.node_names)
        ]

    def register(self) -> None:
        """Create every Node through the API (the kubelet registration)."""
        for node in self._specs:
            try:
                self.store.create(node)
            except st.AlreadyExists:
                pass

    def start(self) -> "HollowCluster":
        self.register()
        t = threading.Thread(
            target=self._heartbeat_loop, name="hollow-heartbeat", daemon=True
        )
        t.start()
        self._threads.append(t)
        if self.run_pods:
            t = threading.Thread(
                target=self._pod_runner, name="hollow-pod-runner", daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    # -- loops -------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        """Round-robin status heartbeats (nodeStatusUpdateFrequency):
        each tick re-writes one batch of Node objects so the control
        plane sees steady NodeUpdate churn like a real cluster."""
        i = 0
        per_tick = max(1, self.n_nodes // 10)
        tick = self.heartbeat_interval / 10
        while not self._stop.wait(tick):
            for _ in range(per_tick):
                name = self.node_names[i % self.n_nodes]
                i += 1
                try:
                    node = self.store.get("Node", name, namespace="")
                    node.meta.annotations["hollow/heartbeat"] = str(time.time())
                    self.store.update(node, force=True)
                except st.NotFound:
                    pass

    def _pod_runner(self) -> None:
        """The kubelet status half: bound Pending pods become Running
        (status written through the API, like status manager PATCHes).
        A watch the store EXPIRED for falling behind (coalescing
        overflow sets .stopped too) is re-established with a catch-up
        list — the reflector contract; the store never destructively
        terminates a slow watcher."""
        w = self.store.watch("Pod")
        try:
            while not self._stop.is_set():
                if w.stopped:
                    w.stop()
                    pods, rv = self.store.list("Pod")
                    for pod in pods:
                        self._maybe_run(pod)  # catch up on missed binds
                    # resume FROM the list's rv: binds landing between
                    # the snapshot and the new watch must not vanish
                    w = self.store.watch("Pod", from_rv=rv)
                    continue
                ev = w.get(timeout=0.2)
                if ev is None:
                    continue
                pod = ev.obj
                if ev.type in (st.ADDED, st.MODIFIED):
                    self._maybe_run(pod)
        finally:
            w.stop()

    def _maybe_run(self, pod) -> None:
        if (
            pod.spec.node_name
            and pod.spec.node_name.startswith("hollow-")
            and pod.status.phase == "Pending"
        ):
            try:
                fresh = self.store.get(
                    "Pod", pod.meta.name, pod.meta.namespace
                )
                if fresh.status.phase == "Pending" and fresh.spec.node_name:
                    fresh.status.phase = "Running"
                    self.store.update(fresh, force=True)
            except st.NotFound:
                pass
