"""Cluster: the all-in-one composition (the hyperkube / `kind` role).

Reference: the reference ships kube-apiserver, kube-scheduler,
kube-controller-manager, and kubelets as separate binaries a deployment
tool assembles; the single-process analogue is this one object — store
(+ optional journal), admission chain, API server (+ optional
authn/authz/APF), scheduler, controller manager, node agents, and an
optional service proxy — started and stopped together.  Everything it
wires is the same public surface tests and embedders use piecemeal.

    from kubernetes_tpu.cluster import Cluster

    cluster = Cluster(n_agents=3).start()
    client = cluster.client()           # RestClient against the server
    client.create(deployment)           # agents run the pods
    cluster.stop()
"""

from __future__ import annotations

from typing import List, Optional

from .agent import NodeAgent
from .api import admission as adm
from .api import store as st
from .api.server import APIServer
from .client.rest import RestClient
from .controllers import ControllerManager
from .proxy import ServiceProxy
from .scheduler import Scheduler


class Cluster:
    def __init__(
        self,
        n_agents: int = 0,
        journal_path: Optional[str] = None,
        authn=None,
        authz=None,
        apf=None,
        scheduler_config=None,
        admission_chain=None,
        with_proxy: bool = False,
        agent_cpu_milli: int = 32000,
        agent_mem: int = 64 * (1 << 30),
    ):
        self.store = st.Store(
            journal_path=journal_path,
            admission=(
                admission_chain
                if admission_chain is not None
                else adm.default_chain()
            ),
        )
        self.server = APIServer(
            self.store, authn=authn, authz=authz, apf=apf
        )
        self.scheduler = Scheduler(self.store, config=scheduler_config)
        self.manager = ControllerManager(self.store)
        self.agents: List[NodeAgent] = [
            NodeAgent(
                self.store,
                f"node-{i}",
                register=True,
                cpu_milli=agent_cpu_milli,
                mem=agent_mem,
            )
            for i in range(n_agents)
        ]
        self.proxy = ServiceProxy(self.store) if with_proxy else None

    @property
    def url(self) -> str:
        return self.server.url

    def client(self, token: Optional[str] = None) -> RestClient:
        return RestClient(self.url, token=token)

    def start(self) -> "Cluster":
        self.server.start()
        for agent in self.agents:
            agent.start()
        self.manager.start()
        self.scheduler.start()
        if self.proxy is not None:
            self.proxy.start()
        return self

    def stop(self) -> None:
        if self.proxy is not None:
            self.proxy.stop()
        self.scheduler.stop()
        self.manager.stop()
        for agent in self.agents:
            agent.stop()
        self.server.stop()
