"""Deterministic, seeded fault injection for the solve→assume→bind
pipeline.

The registry is the chaos suite's only lever: named fault points are
threaded through the hot path (journal append/fsync, the wave
transaction, the checkpoint writer, watch fan-out and the consumer side
of watch streams, the list/relist path, the device solve, the binder
commit, lease renewal)
and each point consults the armed registry through one module-level
indirection.  Disarmed — the production state — the check
is a single global load and an early return, so the hot path pays
nothing measurable (BENCH_STRICT budgets hold with the points in
place).

Schedules are bounded and seeded: a `FaultRegistry(seed=N)` draws every
probabilistic decision from its own `random.Random(N)`, so a failing
chaos seed replays byte-identically.  Supported schedule kinds:

  fail(point, n)        raise (fail-once / fail-N); custom exception type
  crash(point, n)       raise FaultCrash — a BaseException that escapes
                        `except Exception` containment and kills the
                        worker thread (binder-supervision coverage)
  delay(point, s, n)    sleep `s` seconds (latency injection)
  torn_write(point)     the caller writes a PREFIX of its payload and
                        then fails (journal torn-tail coverage)
  drop(point, n)        the caller discards its payload (watch.offer →
                        simulated slow watcher)
  corrupt(point, n)     the caller poisons its result (batch.solve →
                        NaN score tensor)

Sites that need caller-interpreted behaviour (torn/drop/corrupt) read
fire()'s return value; exception-kind schedules raise from inside
fire() so most sites need no control flow at all.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional

from ..analysis import ledger as _ledger

# Every fault point the hot path exposes.  fail()/crash()/... validate
# against this set so a typo'd point name fails the test loudly instead
# of silently never firing.
KNOWN_POINTS = frozenset({
    "store.journal.append",
    "store.journal.fsync",
    "store.update_wave",
    # per-shard twins of the journal/wave points: fired with the shard
    # index in ctx so a schedule lands on the FIRST shard that reaches
    # the point — the crash-one-shard chaos family (surviving shards
    # must stay consistent while the crashed one recovers)
    "store.shard.journal.append",
    "store.shard.update_wave",
    "store.checkpoint",
    "store.list",
    "watch.offer",
    "watch.consume",
    "batch.solve",
    # the batched PostFilter dry-run (one [P, N, K] dispatch per pass);
    # corrupt-grade schedules poison the decoded result so the health
    # check trips and the pass falls back to the per-pod parity path
    "batch.preemption",
    "binder.commit_wave",
    # a batch dispatched SPECULATIVELY — encode/solve over an earlier
    # wave's assumed placements while that wave is still committing;
    # fail-grade schedules kill the dispatch (the cycle containment
    # requeues exactly the speculative batch)
    "solve.speculate",
    # a streamed per-store-shard sub-wave handed to the commit pool as
    # its slice of the wave finished staging (before the rest staged)
    "binder.stream_subwave",
    # a gang carve-out batch dispatched to the device (slice family
    # armed, gangs present) — fail-grade schedules kill the solve and
    # ride the batch.solve retry/breaker containment; the carve-out
    # chaos family (seeds 600-604) asserts no partially occupied
    # carve-out survives quiesce
    "solve.carveout",
    # the incremental-solve partials sync (models/partials.py): CORRUPT
    # poisons the resident partials with NaN score rows so the decode
    # health check trips and the retry path falls back to a full
    # recompute / breaker fallback (the parity gate's runtime wire);
    # fail-grade schedules make the batch solve cold instead — the
    # partials chaos family (seeds 700-704)
    "solve.partials",
    # the elastic node axis's in-place resident resize (models/mirror.py
    # _resize_resident, a pad-bucket crossing absorbed without a full
    # re-upload): fail-grade schedules decline the resize — the mirror
    # takes the full (RESHARDED) re-upload safety path; CORRUPT poisons
    # the carried rows so the decode health check trips and the retry's
    # invalidation heals via full resync — the node-churn chaos family
    # (seeds 800-804)
    "mirror.grow",
    "leader.renew",
    # -- serving-plane points (api/server.py, api/flowcontrol.py) -------
    # every authorized HTTP request, fired before dispatch: fail-grade
    # schedules surface as 4xx/5xx to the client (retry containment),
    # delay-grade as server-side latency — the serving chaos family
    # (seeds 900-909)
    "server.request",
    # one chunked frame written to a watch stream: delay-grade models a
    # stalled TCP consumer (full socket buffer), fail-grade a mid-frame
    # client disconnect, torn-grade a partial frame write then error —
    # the per-watcher write deadline must expire the watch, never pin
    # the handler thread
    "server.watch.write",
    # APF admission (flowcontrol.APFGate.acquire): delay-grade stalls
    # admission (queue-wait coverage), fail-grade rejects the request
    # at the gate (surfaced as a 4xx by the handler's containment)
    "apf.admit",
    # one framed journal wave line (store._append_journal_wave after
    # framing.encode_frame): CORRUPT poisons the encoded frame bytes so
    # replay must drop it as a torn wave — exercised against BOTH the
    # native _hostplane CRC path and the pure-Python fallback (parity)
    "journal.frame",
})

# caller-interpreted actions returned by fire()
DROP = "drop"
CORRUPT = "corrupt"


class FaultInjected(RuntimeError):
    """The default injected failure."""


class FaultCrash(BaseException):
    """Escapes `except Exception` containment: the injected analogue of
    a worker thread dying outright (stack overflow, interpreter-level
    fault) — what binder supervision exists to recover from."""


@dataclass
class TornWrite:
    """Returned by fire(): write only `frac` of the payload, then fail."""

    frac: float = 0.5


@dataclass
class _Schedule:
    mode: str                 # fail | crash | delay | torn | drop | corrupt
    remaining: int            # fires left; -1 = unbounded
    exc: type = FaultInjected
    seconds: float = 0.0
    probability: float = 1.0
    frac: float = 0.5


class FaultRegistry:
    """One chaos run's fault plan: schedules per point, consumed in
    registration order, every probabilistic draw from the run's seed."""

    GUARDED_FIELDS = {
        "_schedules": "_lock",
        "_rng": "_lock",
        "fired": "_lock",
        "log": "_lock",
        "last_ctx": "_lock",
    }
    # schedule registration precedes arm(): the builder-style fail()/
    # crash()/... calls run single-threaded before any hot-path thread
    # can reach fire()
    LOCKED_METHODS = frozenset({"_add"})

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = Random(seed)
        self._lock = threading.Lock()
        self._schedules: Dict[str, List[_Schedule]] = {}
        # observability for the suite's coverage assertions
        self.fired: Dict[str, int] = {}
        self.log: List[tuple] = []  # (point, mode)
        # fire-site context of the LAST schedule that fired per point
        # (e.g. {"shard": 2} from the store's per-shard points) — the
        # crash-one-shard chaos family reads which shard it killed
        self.last_ctx: Dict[str, dict] = {}

    # -- schedule registration -------------------------------------------

    def _add(self, point: str, sched: _Schedule) -> "FaultRegistry":
        if point not in KNOWN_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {sorted(KNOWN_POINTS)}"
            )
        self._schedules.setdefault(point, []).append(sched)
        return self

    def fail(
        self,
        point: str,
        n: int = 1,
        exc: type = FaultInjected,
        probability: float = 1.0,
    ) -> "FaultRegistry":
        return self._add(
            point, _Schedule("fail", n, exc=exc, probability=probability)
        )

    def crash(
        self, point: str, n: int = 1, probability: float = 1.0
    ) -> "FaultRegistry":
        return self._add(
            point, _Schedule("crash", n, probability=probability)
        )

    def delay(
        self, point: str, seconds: float, n: int = 1, probability: float = 1.0
    ) -> "FaultRegistry":
        return self._add(
            point,
            _Schedule("delay", n, seconds=seconds, probability=probability),
        )

    def torn_write(
        self, point: str, frac: float = 0.5, n: int = 1
    ) -> "FaultRegistry":
        return self._add(point, _Schedule("torn", n, frac=frac))

    def drop(
        self, point: str, n: int = 1, probability: float = 1.0
    ) -> "FaultRegistry":
        return self._add(point, _Schedule("drop", n, probability=probability))

    def corrupt(
        self, point: str, n: int = 1, probability: float = 1.0
    ) -> "FaultRegistry":
        return self._add(
            point, _Schedule("corrupt", n, probability=probability)
        )

    def pending(self) -> Dict[str, int]:
        """Point → fires still scheduled (0 once a bounded plan drained;
        the chaos suite's bounded-quiesce precondition)."""
        with self._lock:
            return {
                point: sum(
                    s.remaining for s in scheds if s.remaining > 0
                )
                for point, scheds in self._schedules.items()
            }

    # -- the hot-path side ------------------------------------------------

    def fire(self, point: str, **ctx):
        delay_s = 0.0
        action = None
        exc: Optional[BaseException] = None
        with self._lock:
            for sched in self._schedules.get(point, ()):
                if sched.remaining == 0:
                    continue
                if (
                    sched.probability < 1.0
                    and self._rng.random() >= sched.probability
                ):
                    continue
                if sched.remaining > 0:
                    sched.remaining -= 1
                self.fired[point] = self.fired.get(point, 0) + 1
                self.log.append((point, sched.mode))
                self.last_ctx[point] = dict(ctx)
                if sched.mode == "delay":
                    delay_s = sched.seconds
                    continue  # latency composes with a later failure
                if sched.mode == "fail":
                    exc = sched.exc(f"injected fault at {point}")
                elif sched.mode == "crash":
                    exc = FaultCrash(f"injected crash at {point}")
                elif sched.mode == "torn":
                    action = TornWrite(sched.frac)
                elif sched.mode == "drop":
                    action = DROP
                elif sched.mode == "corrupt":
                    action = CORRUPT
                break  # at most one non-delay schedule fires per call
        if delay_s > 0.0:
            time.sleep(delay_s)
        if exc is not None:
            raise exc
        return action


# -- module-level arming ----------------------------------------------------

_registry: Optional[FaultRegistry] = None


def arm(registry: FaultRegistry) -> FaultRegistry:
    global _registry
    if _registry is not None:
        # re-arm over a live registry: the previous arming's obligation
        # is retired by being overwritten, not leaked
        _ledger.discharge("fault", 0)
    _registry = registry
    _ledger.acquire("fault", 0)
    return registry


def disarm() -> None:
    global _registry
    if _registry is not None:
        _ledger.discharge("fault", 0)
    _registry = None


@contextlib.contextmanager
def armed(registry: FaultRegistry):
    arm(registry)
    try:
        yield registry
    finally:
        disarm()


def fire(point: str, **ctx):
    """The hot-path entry: a single global load when disarmed."""
    reg = _registry
    if reg is None:
        return None
    return reg.fire(point, **ctx)


# -- crash-restart harness ---------------------------------------------------
#
# The kill-restart chaos suite simulates process death WITHOUT fd
# hackery on the live store: a SIGKILL's disk image is exactly "the
# filesystem's bytes right now, minus whatever still sits in userspace
# buffers" — and copying the journal/snapshot files through the
# filesystem reproduces that by construction (a copy reads what the OS
# has, never what the dying process buffered).  The restarted store
# opens the image; the original store object is torn down ungracefully
# (Scheduler.kill(), no Store.close()) and abandoned.


def crash_disk_image(journal_path: str, dest_dir: str) -> str:
    """Capture the post-SIGKILL on-disk state of a journaled store:
    copy the journal(s) and checkpoint snapshot(s) (if present) into
    `dest_dir` as they exist on the filesystem RIGHT NOW — the 1-shard
    layout (``<path>`` + ``<path>.snap``) and the sharded layout
    (``<path>.s<i>`` + ``<path>.s<i>.snap``) both.  Returns the copied
    journal base path — hand it to ``Store(journal_path=...)`` to
    'restart' the killed store (the shard count is inferred from the
    copied layout).  Call while the victim is still live (or already
    abandoned); the copy never touches its file handles."""
    import glob
    import os
    import shutil

    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, os.path.basename(journal_path))
    copied = False
    for src in [journal_path, journal_path + ".snap"] + sorted(
        glob.glob(glob.escape(journal_path) + ".s*")
    ):
        if os.path.exists(src):
            suffix = src[len(journal_path):]
            shutil.copyfile(src, dest + suffix)
            copied = copied or not suffix.endswith(".snap")
    if not copied:
        open(dest, "w").close()
    return dest


def remove_snapshots(journal_path: str) -> int:
    """Delete every checkpoint snapshot of a store's on-disk layout
    (1-shard and sharded alike) — the full-journal-replay ORACLE mode
    the chaos suite compares snapshot+suffix recovery against.  Returns
    the number of snapshots removed."""
    import glob
    import os

    n = 0
    for p in [journal_path + ".snap"] + glob.glob(
        glob.escape(journal_path) + ".s*.snap"
    ):
        if os.path.exists(p):
            os.remove(p)
            n += 1
    return n
