"""Pure-Python scheduling oracle — an independent re-implementation of the
reference's per-pod Filter/Score cycle used to validate the TPU kernels.

Deliberately written the slow, obvious way (per-node Python loops over the
api object model, no tensors, no shared code with ops/) so that a bug in
the snapshot encoder or a kernel cannot cancel itself out in tests.
Semantics follow the same reference code paths the kernels cite:

  filter: noderesources/fit.go:421, nodename, tainttoleration,
          nodeports (wildcard-IP simplification, same as the kernel),
          nodeaffinity required terms
  score:  least_allocated.go:30, balanced_allocation.go:138,
          nodeaffinity preferred + DefaultNormalizeScore,
          tainttoleration PreferNoSchedule count + reversed normalize
  loop:   one pod at a time with assume between picks
          (schedule_one.go:66-133), first-index tie-break.

Resource quantities are converted to the same device units the schema uses
(schema.DEVICE_UNIT_DIVISOR) so score floors land on identical integers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..api import types as api
from ..ops.schema import DEVICE_UNIT_DIVISOR

MAX_SCORE = 100


def _units(requests: Dict[str, int]) -> Dict[str, float]:
    return {k: v / DEVICE_UNIT_DIVISOR.get(k, 1) for k, v in requests.items()}


@dataclass
class _NodeState:
    node: api.Node
    allocatable: Dict[str, float]
    requested: Dict[str, float] = field(default_factory=dict)
    nonzero_requested: Dict[str, float] = field(default_factory=dict)
    used_ports: Set[Tuple[str, int]] = field(default_factory=set)
    pods: List[api.Pod] = field(default_factory=list)

    def add_pod(self, pod: api.Pod) -> None:
        self.pods.append(pod)
        req = _units(pod.resource_requests())
        req[api.PODS] = req.get(api.PODS, 0) + 1
        for k, v in req.items():
            self.requested[k] = self.requested.get(k, 0) + v
        nz = dict(req)
        nz_cpu, nz_mem = pod.nonzero_requests()
        nz[api.CPU] = nz_cpu
        nz[api.MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
        for k, v in nz.items():
            self.nonzero_requested[k] = self.nonzero_requested.get(k, 0) + v
        for proto, _ip, port in pod.host_ports():
            self.used_ports.add((proto, port))


class Oracle:
    """Schedules pods one at a time with reference semantics."""

    def __init__(
        self,
        nodes: Sequence[api.Node],
        bound_pods: Sequence[api.Pod] = (),
        fit_strategy: str = "LeastAllocated",
        slice_policy: str = "prefer",
    ):
        self.states: List[_NodeState] = [
            _NodeState(node=n, allocatable=_units(n.status.allocatable)) for n in nodes
        ]
        self.fit_strategy = fit_strategy
        # TPU slice carve-outs (ops/slices.py semantics contract):
        # per-node slice info from labels, per-gang anchored carve-outs
        self.slice_policy = slice_policy
        self._slice_infos = [self._parse_slice(st) for st in self.states]
        self._has_slices = any(i is not None for i in self._slice_infos)
        self._gang_carve: Dict[str, Tuple[str, Tuple[int, int, int]]] = {}
        by_name = {s.node.meta.name: s for s in self.states}
        for p in bound_pods:
            st = by_name.get(p.spec.node_name)
            if st is not None:
                st.add_pod(p)

    # -- TPU slice carve-outs (ops/slices.py parity twin) -----------------
    #
    # The slow, obvious reimplementation of the carve-out semantics
    # contract: python dict grids instead of value-space tensors.  Only
    # the score WEIGHTS are shared (ops.slices constants) — they define
    # the semantics, not the implementation.

    @staticmethod
    def _parse_slice(st: _NodeState):
        labels = st.node.meta.labels
        name = labels.get(api.LABEL_TPU_SLICE)
        if not name:
            return None
        dims = api.parse_topology(labels.get(api.LABEL_TPU_TOPOLOGY))
        coords = api.parse_coords(labels.get(api.LABEL_TPU_COORDS))
        if dims is None or coords is None:
            return None
        if any(c >= d for c, d in zip(coords, dims)):
            return None
        return name, coords, dims

    @staticmethod
    def _node_free(st: _NodeState) -> bool:
        return st.requested.get(api.PODS, 0) == 0

    def _slice_grids(self):
        """(cells, dims, free_nodes): per-slice coordinate→free map (a
        coordinate shared by several nodes/cores is free only when all
        are), declared extents, and free NODE counts (the best-fit
        leftover signal)."""
        cells: Dict[str, Dict[tuple, bool]] = {}
        dims_of: Dict[str, tuple] = {}
        free_nodes: Dict[str, int] = {}
        for st, info in zip(self.states, self._slice_infos):
            if info is None:
                continue
            name, coords, dims = info
            free = self._node_free(st)
            d = cells.setdefault(name, {})
            d[coords] = d.get(coords, True) and free
            prev = dims_of.get(name, (0, 0, 0))
            dims_of[name] = tuple(max(a, b) for a, b in zip(prev, dims))
            free_nodes[name] = free_nodes.get(name, 0) + (1 if free else 0)
        return cells, dims_of, free_nodes

    def _corner_ok(self, cells, dims_of, info, shape) -> bool:
        name, (x, y, z), _dims = info
        dx, dy, dz = dims_of[name]
        a, b, c = shape
        if x + a > dx or y + b > dy or z + c > dz:
            return False
        grid = cells[name]
        for i in range(x, x + a):
            for j in range(y, y + b):
                for k in range(z, z + c):
                    if not grid.get((i, j, k), False):
                        return False
        return True

    def _carveout_ctx(self, pod: api.Pod):
        """Per-cycle carve-out context: (shape, anchored carve-out or
        None, grids) — None when the family is off for this pod."""
        if self.slice_policy == "off" or not self._has_slices:
            return None
        shape = api.parse_topology(pod.spec.tpu_topology)
        if shape is None:
            return None
        group = pod.spec.scheduling_group
        carve = self._gang_carve.get(group) if group else None
        cells, dims_of, free_nodes = self._slice_grids()
        return {
            "shape": shape,
            "carve": carve,
            "cells": cells,
            "dims_of": dims_of,
            "free_nodes": free_nodes,
        }

    def _carveout_ok(self, st_idx: int, sctx) -> bool:
        """require-mode filter: anchors need a free-box corner, anchored
        members the carved cuboid."""
        info = self._slice_infos[st_idx]
        if sctx["carve"] is not None:
            sname, lo = sctx["carve"]
            if info is None or info[0] != sname:
                return False
            if not self._node_free(self.states[st_idx]):
                return False  # one member per device
            coords, shape = info[1], sctx["shape"]
            return all(
                l <= c < l + s for c, l, s in zip(coords, lo, shape)
            )
        if info is None or not self._node_free(self.states[st_idx]):
            return False
        return self._corner_ok(
            sctx["cells"], sctx["dims_of"], info, sctx["shape"]
        )

    def _carveout_bonus(self, st_idx: int, sctx) -> float:
        from ..ops.slices import (
            BONUS_CARVE, BONUS_SLICE, W_CORNER, W_HOP, W_LEFTOVER,
        )

        info = self._slice_infos[st_idx]
        shape = sctx["shape"]
        if sctx["carve"] is not None:
            if info is None or not self._node_free(self.states[st_idx]):
                return 0.0  # one member per device: occupied earns nothing
            sname, lo = sctx["carve"]
            name, coords, _dims = info
            if name != sname:
                return 0.0
            hop = sum(abs(c - l) for c, l in zip(coords, lo))
            if all(l <= c < l + s for c, l, s in zip(coords, lo, shape)):
                return BONUS_CARVE + BONUS_SLICE - W_HOP * hop
            return BONUS_SLICE - W_HOP * hop
        if (
            info is None
            or not self._node_free(self.states[st_idx])
            or not self._corner_ok(sctx["cells"], sctx["dims_of"], info, shape)
        ):
            return 0.0
        vol = shape[0] * shape[1] * shape[2]
        leftover = max(sctx["free_nodes"].get(info[0], 0) - vol, 0)
        coordsum = sum(info[1])
        return BONUS_CARVE - W_LEFTOVER * leftover - W_CORNER * coordsum

    def _record_carve(self, pod: api.Pod, st_idx: int, sctx) -> None:
        """Anchor the gang's carve-out at the first member's landing
        coordinates (only when the node is slice-labelled — an
        off-slice prefer-mode landing leaves the gang unanchored,
        matching the kernel's -1 sentinel write)."""
        group = pod.spec.scheduling_group
        if not group or sctx["carve"] is not None:
            return
        info = self._slice_infos[st_idx]
        if info is not None:
            self._gang_carve[group] = (info[0], info[1])

    # -- topology spread (filtering.go) ----------------------------------

    def _spread_eligible(self, pod: api.Pod, st: _NodeState) -> bool:
        """Node counted for the pod's spread constraints: passes the pod's
        node selector/affinity and has every constraint's topology key."""
        sel = pod.required_node_selector()
        if sel is not None and not sel.matches(st.node.meta.labels):
            return False
        return all(
            c.topology_key in st.node.meta.labels
            for c in pod.spec.topology_spread_constraints
        )

    def _spread_counts(self, pod: api.Pod, c: api.TopologySpreadConstraint):
        """(counts per topology value over eligible nodes, min count)."""
        sel = c.label_selector or api.LabelSelector()
        counts: Dict[str, int] = {}
        for st in self.states:
            if not self._spread_eligible(pod, st):
                continue
            val = st.node.meta.labels.get(c.topology_key)
            if val is None:
                continue
            counts.setdefault(val, 0)
            counts[val] += sum(
                1
                for q in st.pods
                if q.meta.namespace == pod.meta.namespace
                and sel.matches(q.meta.labels)
            )
        return counts, (min(counts.values()) if counts else 0)

    # -- inter-pod affinity (interpodaffinity/filtering.go) --------------

    @staticmethod
    def _term_matches(term: api.PodAffinityTerm, owner_ns: str, q: api.Pod) -> bool:
        namespaces = term.namespaces or [owner_ns]
        if q.meta.namespace not in namespaces:
            return False
        sel = term.label_selector or api.LabelSelector()
        return sel.matches(q.meta.labels)

    def _pod_context(self, pod: api.Pod) -> dict:
        """Node-independent per-cycle state, computed once per pod — the
        oracle's PreFilter.  Keeps _feasible O(1)-ish per node so parity
        tests stay O(N * pods) instead of O(N^2 * pods)."""
        ctx: dict = {}

        # spread: counts + min per hard constraint, self-match flags
        hard = [
            c
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "DoNotSchedule"
        ]
        ctx["spread"] = []
        for c in hard:
            counts, min_match = self._spread_counts(pod, c)
            sel = c.label_selector or api.LabelSelector()
            self_match = 1 if sel.matches(pod.meta.labels) else 0
            ctx["spread"].append((c, counts, min_match, self_match))

        # existing pods' anti-affinity terms that match this pod:
        # (topologyKey, value) pairs that block it
        blockers = set()
        for other in self.states:
            for q in other.pods:
                qaff = q.spec.affinity
                for t in (
                    qaff.pod_anti_affinity.required
                    if qaff and qaff.pod_anti_affinity
                    else []
                ):
                    if not self._term_matches(t, q.meta.namespace, pod):
                        continue
                    qv = other.node.meta.labels.get(t.topology_key)
                    if qv is not None:
                        blockers.add((t.topology_key, qv))
        ctx["blockers"] = blockers

        # per own-term: topology values with a matching existing pod
        aff = pod.spec.affinity
        aff_terms = aff.pod_affinity.required if aff and aff.pod_affinity else []
        anti_terms = aff.pod_anti_affinity.required if aff and aff.pod_anti_affinity else []

        def values_with_match(t: api.PodAffinityTerm) -> Set[str]:
            vals = set()
            for other in self.states:
                ov = other.node.meta.labels.get(t.topology_key)
                if ov is None:
                    continue
                if any(
                    self._term_matches(t, pod.meta.namespace, q) for q in other.pods
                ):
                    vals.add(ov)
            return vals

        ctx["aff_terms"] = [(t, values_with_match(t)) for t in aff_terms]
        ctx["anti_terms"] = [(t, values_with_match(t)) for t in anti_terms]
        ctx["self_match"] = bool(aff_terms) and all(
            self._term_matches(t, pod.meta.namespace, pod) for t in aff_terms
        )
        return ctx

    def _spread_ok(self, pod: api.Pod, st: _NodeState, ctx: dict) -> bool:
        for c, counts, min_match, self_match in ctx["spread"]:
            val = st.node.meta.labels.get(c.topology_key)
            if val is None:
                return False
            if counts.get(val, 0) + self_match - min_match > c.max_skew:
                return False
        return True

    def _interpod_ok(self, pod: api.Pod, st: _NodeState, ctx: dict) -> bool:
        labels = st.node.meta.labels
        # 1. existing pods' anti-affinity vs the incoming pod
        for key, val in ctx["blockers"]:
            if labels.get(key) == val:
                return False
        # 2. incoming pod's anti-affinity
        for t, vals in ctx["anti_terms"]:
            v = labels.get(t.topology_key)
            if v is not None and v in vals:
                return False
        # 3. incoming pod's affinity (with first-pod escape)
        if ctx["aff_terms"]:
            if any(t.topology_key not in labels for t, _ in ctx["aff_terms"]):
                return False
            all_here = all(
                labels[t.topology_key] in vals for t, vals in ctx["aff_terms"]
            )
            if not all_here:
                none_anywhere = all(not vals for _, vals in ctx["aff_terms"])
                if not (none_anywhere and ctx["self_match"]):
                    return False
        return True

    # -- filter ----------------------------------------------------------

    def _feasible(self, pod: api.Pod, st: _NodeState, ctx: dict) -> bool:
        req = _units(pod.resource_requests())
        req[api.PODS] = req.get(api.PODS, 0) + 1
        for k, v in req.items():
            if v == 0:
                continue
            if st.requested.get(k, 0) + v > st.allocatable.get(k, 0):
                return False
        if not self._static_ok(pod, st):
            return False
        for proto, _ip, port in pod.host_ports():
            if (proto, port) in st.used_ports:
                return False
        if not self._spread_ok(pod, st, ctx):
            return False
        if not self._interpod_ok(pod, st, ctx):
            return False
        return True

    # -- score -----------------------------------------------------------

    def _fit_score(self, pod: api.Pod, st: _NodeState) -> int:
        nz_cpu, nz_mem = pod.nonzero_requests()
        pod_nz = {api.CPU: nz_cpu, api.MEMORY: nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]}
        total = wsum = 0
        for res in (api.CPU, api.MEMORY):
            cap = st.allocatable.get(res, 0)
            if cap <= 0:
                continue
            q = st.nonzero_requested.get(res, 0) + pod_nz[res]
            if self.fit_strategy == "MostAllocated":
                s = math.floor(q * MAX_SCORE / cap) if q <= cap else 0
            else:
                s = math.floor((cap - q) * MAX_SCORE / cap) if q <= cap else 0
            total += s
            wsum += 1
        return math.floor(total / wsum) if wsum else 0

    def _balanced_score(self, pod: api.Pod, st: _NodeState) -> int:
        req = _units(pod.resource_requests())
        fracs = []
        for res in (api.CPU, api.MEMORY):
            cap = st.allocatable.get(res, 0)
            if cap <= 0:
                continue
            f = (st.requested.get(res, 0) + req.get(res, 0)) / cap
            fracs.append(min(f, 1.0))
        if len(fracs) < 2:
            std = 0.0
        else:
            mean = sum(fracs) / len(fracs)
            std = math.sqrt(sum((f - mean) ** 2 for f in fracs) / len(fracs))
        return math.floor((1 - std) * MAX_SCORE)

    @staticmethod
    def _affinity_raw(pod: api.Pod, st: _NodeState) -> int:
        return sum(
            t.weight
            for t in pod.preferred_node_affinity()
            if t.preference.matches(st.node.meta.labels)
        )

    @staticmethod
    def _taint_raw(pod: api.Pod, st: _NodeState) -> int:
        return sum(
            1
            for t in st.node.effective_taints()
            if t.effect == api.PREFER_NO_SCHEDULE
            and not api.tolerations_tolerate_taint(pod.spec.tolerations, t)
        )

    @staticmethod
    def _normalize(raws: List[int], reverse: bool = False) -> List[int]:
        m = max(raws) if raws else 0
        if m == 0:
            return [MAX_SCORE if reverse else 0 for _ in raws]
        out = [math.floor(MAX_SCORE * r / m) for r in raws]
        if reverse:
            out = [MAX_SCORE - s for s in out]
        return out

    def _spread_scores(self, pod: api.Pod, feasible: List[Tuple[int, _NodeState]]) -> List[int]:
        """PodTopologySpread soft-constraint scores, normalized
        (scoring.go Score + NormalizeScore)."""
        soft = [
            c
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "ScheduleAnyway"
        ]
        if not soft:
            return [0] * len(feasible)
        ignored = [
            any(c.topology_key not in st.node.meta.labels for c in soft)
            for _, st in feasible
        ]
        raws: List[Optional[int]] = []
        counts = {id(c): self._spread_counts(pod, c)[0] for c in soft}
        # Distinct values over *eligible* nodes, matching the kernel's
        # prep-time sizes (the reference uses the per-cycle feasible set;
        # see ops/topology.py spread_score for why this is equivalent in
        # the single-constraint case).
        sizes = {
            id(c): len(
                {
                    st.node.meta.labels[c.topology_key]
                    for st in self.states
                    if self._spread_eligible(pod, st)
                    and c.topology_key in st.node.meta.labels
                }
            )
            for c in soft
        }
        for (_, st), ign in zip(feasible, ignored):
            if ign:
                raws.append(None)
                continue
            s = 0.0
            for c in soft:
                val = st.node.meta.labels[c.topology_key]
                cnt = counts[id(c)].get(val, 0)
                s += cnt * math.log(sizes[id(c)] + 2) + (c.max_skew - 1)
            raws.append(round(s))
        valid = [r for r in raws if r is not None]
        mx, mn = (max(valid), min(valid)) if valid else (0, 0)
        out = []
        for r in raws:
            if r is None:
                out.append(0)
            elif mx <= 0:
                out.append(MAX_SCORE)
            else:
                out.append(math.floor(MAX_SCORE * (mx + mn - r) / mx))
        return out

    # -- cycle -----------------------------------------------------------

    def schedule_one(self, pod: api.Pod) -> Optional[str]:
        ctx = self._pod_context(pod)
        sctx = self._carveout_ctx(pod)
        feasible = [
            (i, st)
            for i, st in enumerate(self.states)
            if self._feasible(pod, st, ctx)
            and (
                sctx is None
                or self.slice_policy != "require"
                or self._carveout_ok(i, sctx)
            )
        ]
        if not feasible:
            return None
        aff = self._normalize([self._affinity_raw(pod, st) for _, st in feasible])
        taint = self._normalize([self._taint_raw(pod, st) for _, st in feasible], reverse=True)
        spread = self._spread_scores(pod, feasible)
        best_i, best_score = None, None
        for j, (i, st) in enumerate(feasible):
            score = (
                1 * self._fit_score(pod, st)
                + 1 * self._balanced_score(pod, st)
                + 2 * aff[j]
                + 3 * taint[j]
                + 2 * spread[j]
            )
            if sctx is not None:
                score += self._carveout_bonus(i, sctx)
            if best_score is None or score > best_score:
                best_i, best_score = i, score
        st = self.states[best_i]
        st.add_pod(pod)
        if sctx is not None:
            self._record_carve(pod, best_i, sctx)
        return st.node.meta.name

    def schedule(self, pods: Sequence[api.Pod]) -> List[Optional[str]]:
        return [self.schedule_one(p) for p in pods]

    # -- preemption (scheduler/preemption.py policy mirror) ---------------

    def _static_ok(self, pod: api.Pod, st: _NodeState) -> bool:
        """Non-resource, placement-independent filters only — the slice
        the preemption dry-run keeps (eviction can't change these)."""
        if pod.spec.node_name and pod.spec.node_name != st.node.meta.name:
            return False
        for taint in st.node.effective_taints():
            if taint.effect in (api.NO_SCHEDULE, api.NO_EXECUTE):
                if not api.tolerations_tolerate_taint(pod.spec.tolerations, taint):
                    return False
        sel = pod.required_node_selector()
        if sel is not None and not sel.matches(st.node.meta.labels):
            return False
        return True

    def preempt(self, pod: api.Pod):
        """Victim-selection oracle mirroring the documented policy of
        kubernetes_tpu.scheduler.preemption: per node, evict the minimal
        lowest-priority-first prefix that admits the pod (resource math
        only, over static-feasible nodes); across nodes, pick
        lexicographically by (highest victim priority, priority sum,
        victim count, node index).  Returns (node_name, [victim pods]) or
        None."""
        candidates = []
        pod_req = _units(pod.resource_requests())
        pod_req[api.PODS] = pod_req.get(api.PODS, 0) + 1
        for idx, st in enumerate(self.states):
            if not self._static_ok(pod, st):
                continue
            victims = sorted(
                (q for q in st.pods if q.spec.priority < pod.spec.priority),
                key=lambda q: (q.spec.priority, f"{q.meta.namespace}/{q.meta.name}"),
            )
            if not victims:
                continue
            freed: Dict[str, float] = {}
            chosen = None
            for k in range(len(victims) + 1):
                fits = all(
                    v <= 0
                    or st.requested.get(res, 0) - freed.get(res, 0) + v
                    <= st.allocatable.get(res, 0)
                    for res, v in pod_req.items()
                )
                if fits:
                    chosen = k
                    break
                if k < len(victims):
                    vreq = _units(victims[k].resource_requests())
                    vreq[api.PODS] = vreq.get(api.PODS, 0) + 1
                    for res, v in vreq.items():
                        freed[res] = freed.get(res, 0) + v
            if chosen is None or chosen == 0:
                continue
            evicted = victims[:chosen]
            candidates.append(
                (
                    max(q.spec.priority for q in evicted),
                    sum(q.spec.priority for q in evicted),
                    len(evicted),
                    idx,
                    st.node.meta.name,
                    evicted,
                )
            )
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[:4])
        _, _, _, _, name, evicted = candidates[0]
        return name, evicted
