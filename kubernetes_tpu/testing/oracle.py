"""Pure-Python scheduling oracle — an independent re-implementation of the
reference's per-pod Filter/Score cycle used to validate the TPU kernels.

Deliberately written the slow, obvious way (per-node Python loops over the
api object model, no tensors, no shared code with ops/) so that a bug in
the snapshot encoder or a kernel cannot cancel itself out in tests.
Semantics follow the same reference code paths the kernels cite:

  filter: noderesources/fit.go:421, nodename, tainttoleration,
          nodeports (wildcard-IP simplification, same as the kernel),
          nodeaffinity required terms
  score:  least_allocated.go:30, balanced_allocation.go:138,
          nodeaffinity preferred + DefaultNormalizeScore,
          tainttoleration PreferNoSchedule count + reversed normalize
  loop:   one pod at a time with assume between picks
          (schedule_one.go:66-133), first-index tie-break.

Resource quantities are converted to the same device units the schema uses
(schema.DEVICE_UNIT_DIVISOR) so score floors land on identical integers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..api import types as api
from ..ops.schema import DEVICE_UNIT_DIVISOR

MAX_SCORE = 100


def _units(requests: Dict[str, int]) -> Dict[str, float]:
    return {k: v / DEVICE_UNIT_DIVISOR.get(k, 1) for k, v in requests.items()}


@dataclass
class _NodeState:
    node: api.Node
    allocatable: Dict[str, float]
    requested: Dict[str, float] = field(default_factory=dict)
    nonzero_requested: Dict[str, float] = field(default_factory=dict)
    used_ports: Set[Tuple[str, int]] = field(default_factory=set)

    def add_pod(self, pod: api.Pod) -> None:
        req = _units(pod.resource_requests())
        req[api.PODS] = req.get(api.PODS, 0) + 1
        for k, v in req.items():
            self.requested[k] = self.requested.get(k, 0) + v
        nz = dict(req)
        nz_cpu, nz_mem = pod.nonzero_requests()
        nz[api.CPU] = nz_cpu
        nz[api.MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
        for k, v in nz.items():
            self.nonzero_requested[k] = self.nonzero_requested.get(k, 0) + v
        for proto, _ip, port in pod.host_ports():
            self.used_ports.add((proto, port))


class Oracle:
    """Schedules pods one at a time with reference semantics."""

    def __init__(
        self,
        nodes: Sequence[api.Node],
        bound_pods: Sequence[api.Pod] = (),
        fit_strategy: str = "LeastAllocated",
    ):
        self.states: List[_NodeState] = [
            _NodeState(node=n, allocatable=_units(n.status.allocatable)) for n in nodes
        ]
        self.fit_strategy = fit_strategy
        by_name = {s.node.meta.name: s for s in self.states}
        for p in bound_pods:
            st = by_name.get(p.spec.node_name)
            if st is not None:
                st.add_pod(p)

    # -- filter ----------------------------------------------------------

    def _feasible(self, pod: api.Pod, st: _NodeState) -> bool:
        req = _units(pod.resource_requests())
        req[api.PODS] = req.get(api.PODS, 0) + 1
        for k, v in req.items():
            if v == 0:
                continue
            if st.requested.get(k, 0) + v > st.allocatable.get(k, 0):
                return False
        if pod.spec.node_name and pod.spec.node_name != st.node.meta.name:
            return False
        for taint in st.node.effective_taints():
            if taint.effect in (api.NO_SCHEDULE, api.NO_EXECUTE):
                if not api.tolerations_tolerate_taint(pod.spec.tolerations, taint):
                    return False
        for proto, _ip, port in pod.host_ports():
            if (proto, port) in st.used_ports:
                return False
        sel = pod.required_node_selector()
        if sel is not None and not sel.matches(st.node.meta.labels):
            return False
        return True

    # -- score -----------------------------------------------------------

    def _fit_score(self, pod: api.Pod, st: _NodeState) -> int:
        nz_cpu, nz_mem = pod.nonzero_requests()
        pod_nz = {api.CPU: nz_cpu, api.MEMORY: nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]}
        total = wsum = 0
        for res in (api.CPU, api.MEMORY):
            cap = st.allocatable.get(res, 0)
            if cap <= 0:
                continue
            q = st.nonzero_requested.get(res, 0) + pod_nz[res]
            if self.fit_strategy == "MostAllocated":
                s = math.floor(q * MAX_SCORE / cap) if q <= cap else 0
            else:
                s = math.floor((cap - q) * MAX_SCORE / cap) if q <= cap else 0
            total += s
            wsum += 1
        return math.floor(total / wsum) if wsum else 0

    def _balanced_score(self, pod: api.Pod, st: _NodeState) -> int:
        req = _units(pod.resource_requests())
        fracs = []
        for res in (api.CPU, api.MEMORY):
            cap = st.allocatable.get(res, 0)
            if cap <= 0:
                continue
            f = (st.requested.get(res, 0) + req.get(res, 0)) / cap
            fracs.append(min(f, 1.0))
        if len(fracs) < 2:
            std = 0.0
        else:
            mean = sum(fracs) / len(fracs)
            std = math.sqrt(sum((f - mean) ** 2 for f in fracs) / len(fracs))
        return math.floor((1 - std) * MAX_SCORE)

    @staticmethod
    def _affinity_raw(pod: api.Pod, st: _NodeState) -> int:
        return sum(
            t.weight
            for t in pod.preferred_node_affinity()
            if t.preference.matches(st.node.meta.labels)
        )

    @staticmethod
    def _taint_raw(pod: api.Pod, st: _NodeState) -> int:
        return sum(
            1
            for t in st.node.effective_taints()
            if t.effect == api.PREFER_NO_SCHEDULE
            and not api.tolerations_tolerate_taint(pod.spec.tolerations, t)
        )

    @staticmethod
    def _normalize(raws: List[int], reverse: bool = False) -> List[int]:
        m = max(raws) if raws else 0
        if m == 0:
            return [MAX_SCORE if reverse else 0 for _ in raws]
        out = [math.floor(MAX_SCORE * r / m) for r in raws]
        if reverse:
            out = [MAX_SCORE - s for s in out]
        return out

    # -- cycle -----------------------------------------------------------

    def schedule_one(self, pod: api.Pod) -> Optional[str]:
        feasible = [(i, st) for i, st in enumerate(self.states) if self._feasible(pod, st)]
        if not feasible:
            return None
        aff = self._normalize([self._affinity_raw(pod, st) for _, st in feasible])
        taint = self._normalize([self._taint_raw(pod, st) for _, st in feasible], reverse=True)
        best_i, best_score = None, None
        for j, (i, st) in enumerate(feasible):
            score = (
                1 * self._fit_score(pod, st)
                + 1 * self._balanced_score(pod, st)
                + 2 * aff[j]
                + 3 * taint[j]
            )
            if best_score is None or score > best_score:
                best_i, best_score = i, score
        st = self.states[best_i]
        st.add_pod(pod)
        return st.node.meta.name

    def schedule(self, pods: Sequence[api.Pod]) -> List[Optional[str]]:
        return [self.schedule_one(p) for p in pods]
