"""Test kit: object builders (wrappers) and the pure-Python scheduling oracle."""
