"""Builder-style test object constructors.

The equivalent of the reference's st.MakePod()/MakeNode() wrappers
(pkg/scheduler/testing/wrappers.go) — fluent builders so tests and
benchmarks construct clusters in one expression.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..api import types as api

MI = 1 << 20
GI = 1 << 30


class PodWrapper:
    def __init__(self, name: str, namespace: str = "default"):
        self.pod = api.Pod(meta=api.ObjectMeta(name=name, namespace=namespace))
        self.pod.spec.containers.append(api.Container(name="c0"))

    def obj(self) -> api.Pod:
        return self.pod

    def pvc(self, claim_name: str) -> "PodWrapper":
        """Mount a PVC-backed volume (core/v1 Volume.persistentVolumeClaim)."""
        self.pod.spec.volumes.append(
            api.Volume(
                name=f"vol-{len(self.pod.spec.volumes)}",
                persistent_volume_claim=claim_name,
            )
        )
        return self

    def req(self, cpu_milli: int = 0, mem: int = 0, **scalars: int) -> "PodWrapper":
        r = self.pod.spec.containers[0].requests
        if cpu_milli:
            r[api.CPU] = cpu_milli
        if mem:
            r[api.MEMORY] = mem
        r.update(scalars)
        return self

    def labels(self, **kv: str) -> "PodWrapper":
        self.pod.meta.labels.update({k.replace("_", "-"): v for k, v in kv.items()})
        return self

    def label(self, key: str, value: str) -> "PodWrapper":
        self.pod.meta.labels[key] = value
        return self

    def node_name(self, name: str) -> "PodWrapper":
        self.pod.spec.node_name = name
        return self

    def node_selector(self, **kv: str) -> "PodWrapper":
        self.pod.spec.node_selector.update(kv)
        return self

    def node_selector_kv(self, key: str, value: str) -> "PodWrapper":
        self.pod.spec.node_selector[key] = value
        return self

    def priority(self, p: int) -> "PodWrapper":
        self.pod.spec.priority = p
        return self

    def group(self, name: str, size: Optional[int] = None) -> "PodWrapper":
        """Gang/coscheduling group (PodSpec.scheduling_group); size is the
        declared member count (scheduling_group_size, PodGroup minMember)."""
        self.pod.spec.scheduling_group = name
        self.pod.spec.scheduling_group_size = size
        return self

    def toleration(
        self, key: str = "", op: str = api.OP_EXISTS, value: str = "", effect: str = ""
    ) -> "PodWrapper":
        self.pod.spec.tolerations.append(
            api.Toleration(key=key, op=op, value=value, effect=effect)
        )
        return self

    def image(self, name: str) -> "PodWrapper":
        self.pod.spec.containers[0].image = name
        return self

    def host_port(self, port: int, protocol: str = "TCP") -> "PodWrapper":
        self.pod.spec.containers[0].ports.append(
            api.ContainerPort(container_port=port, host_port=port, protocol=protocol)
        )
        return self

    def _affinity(self) -> api.Affinity:
        if self.pod.spec.affinity is None:
            self.pod.spec.affinity = api.Affinity()
        return self.pod.spec.affinity

    def _node_affinity(self) -> api.NodeAffinity:
        aff = self._affinity()
        if aff.node_affinity is None:
            aff.node_affinity = api.NodeAffinity()
        return aff.node_affinity

    def required_affinity(
        self, key: str, op: str = api.OP_IN, values: Sequence[str] = ()
    ) -> "PodWrapper":
        """Adds one requirement as its own term (new term ORs)."""
        na = self._node_affinity()
        if na.required is None:
            na.required = api.NodeSelector()
        na.required.terms.append(
            api.NodeSelectorTerm(
                match_expressions=[api.Requirement(key, op, list(values))]
            )
        )
        return self

    def preferred_affinity(
        self, weight: int, key: str, op: str = api.OP_IN, values: Sequence[str] = ()
    ) -> "PodWrapper":
        na = self._node_affinity()
        na.preferred.append(
            api.PreferredSchedulingTerm(
                weight=weight,
                preference=api.NodeSelectorTerm(
                    match_expressions=[api.Requirement(key, op, list(values))]
                ),
            )
        )
        return self

    def spread(
        self,
        max_skew: int = 1,
        topology_key: str = api.LABEL_ZONE,
        when_unsatisfiable: str = "DoNotSchedule",
        selector: Optional[Dict[str, str]] = None,
    ) -> "PodWrapper":
        self.pod.spec.topology_spread_constraints.append(
            api.TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=api.LabelSelector(match_labels=selector or {}),
            )
        )
        return self

    def pod_anti_affinity(
        self, selector: Dict[str, str], topology_key: str = api.LABEL_HOSTNAME
    ) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_anti_affinity is None:
            aff.pod_anti_affinity = api.PodAntiAffinity()
        aff.pod_anti_affinity.required.append(
            api.PodAffinityTerm(
                label_selector=api.LabelSelector(match_labels=selector),
                topology_key=topology_key,
            )
        )
        return self

    def pod_affinity(
        self, selector: Dict[str, str], topology_key: str = api.LABEL_HOSTNAME
    ) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_affinity is None:
            aff.pod_affinity = api.PodAffinity()
        aff.pod_affinity.required.append(
            api.PodAffinityTerm(
                label_selector=api.LabelSelector(match_labels=selector),
                topology_key=topology_key,
            )
        )
        return self


class NodeWrapper:
    def __init__(self, name: str):
        self.node = api.Node(meta=api.ObjectMeta(name=name, namespace=""))
        self.node.meta.labels[api.LABEL_HOSTNAME] = name
        self.capacity(cpu_milli=32000, mem=64 * GI, pods=110)

    def obj(self) -> api.Node:
        return self.node

    def capacity(
        self, cpu_milli: int = 0, mem: int = 0, pods: int = 0, **scalars: int
    ) -> "NodeWrapper":
        a = self.node.status.allocatable
        if cpu_milli:
            a[api.CPU] = cpu_milli
        if mem:
            a[api.MEMORY] = mem
        if pods:
            a[api.PODS] = pods
        a.update(scalars)
        self.node.status.capacity = dict(a)
        return self

    def label(self, key: str, value: str) -> "NodeWrapper":
        self.node.meta.labels[key] = value
        return self

    def zone(self, z: str) -> "NodeWrapper":
        return self.label(api.LABEL_ZONE, z)

    def taint(self, key: str, value: str = "", effect: str = api.NO_SCHEDULE) -> "NodeWrapper":
        self.node.spec.taints.append(api.Taint(key, value, effect))
        return self

    def image(self, name: str, size_bytes: int = 500 * 1024 * 1024) -> "NodeWrapper":
        self.node.status.images.append(
            api.ContainerImage(names=[name], size_bytes=size_bytes)
        )
        return self

    def unschedulable(self, flag: bool = True) -> "NodeWrapper":
        self.node.spec.unschedulable = flag
        return self


def make_pv(
    name: str,
    storage: int,
    storage_class: str = "",
    zone: Optional[str] = None,
    driver: str = "",
    access_modes: Sequence[str] = ("ReadWriteOnce",),
) -> api.PersistentVolume:
    affinity = None
    if zone is not None:
        affinity = api.NodeSelector(
            terms=[
                api.NodeSelectorTerm(
                    match_expressions=[
                        api.Requirement(api.LABEL_ZONE, api.OP_IN, [zone])
                    ]
                )
            ]
        )
    return api.PersistentVolume(
        meta=api.ObjectMeta(name=name),
        spec=api.PersistentVolumeSpec(
            capacity={api.STORAGE: storage},
            access_modes=list(access_modes),
            storage_class_name=storage_class,
            node_affinity=affinity,
            driver=driver,
        ),
    )


def make_pvc(
    name: str,
    storage: int,
    storage_class: str = "",
    namespace: str = "default",
    access_modes: Sequence[str] = ("ReadWriteOnce",),
) -> api.PersistentVolumeClaim:
    return api.PersistentVolumeClaim(
        meta=api.ObjectMeta(name=name, namespace=namespace),
        spec=api.PersistentVolumeClaimSpec(
            access_modes=list(access_modes),
            storage_class_name=storage_class,
            resources={api.STORAGE: storage},
        ),
    )


def make_storage_class(
    name: str,
    provisioner: str = "",
    mode: str = api.VOLUME_BINDING_WAIT,
    zones: Optional[Sequence[str]] = None,
) -> api.StorageClass:
    topo = None
    if zones is not None:
        topo = api.NodeSelector(
            terms=[
                api.NodeSelectorTerm(
                    match_expressions=[
                        api.Requirement(api.LABEL_ZONE, api.OP_IN, [z])
                    ]
                )
                for z in zones
            ]
        )
    return api.StorageClass(
        meta=api.ObjectMeta(name=name),
        provisioner=provisioner,
        volume_binding_mode=mode,
        allowed_topologies=topo,
    )


def make_pod(name: str, namespace: str = "default") -> PodWrapper:
    return PodWrapper(name, namespace)


def make_node(name: str) -> NodeWrapper:
    return NodeWrapper(name)


def make_nodes(
    n: int, prefix: str = "node", cpu_milli: int = 0, mem: int = 0, pods: int = 0
) -> List[api.Node]:
    out = []
    for i in range(n):
        nw = make_node(f"{prefix}-{i}")
        if cpu_milli or mem or pods:
            nw.capacity(cpu_milli=cpu_milli, mem=mem, pods=pods)
        out.append(nw.obj())
    return out
