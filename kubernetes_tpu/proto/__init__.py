"""The dense-snapshot proto boundary (SURVEY §2.6's Go↔JAX shim).

snapshot.proto is the contract; snapshot_pb2 is committed generated
code, regenerated on import if protoc is available and the .proto is
newer (so editing the contract never ships stale gencode)."""

from __future__ import annotations

import os
import subprocess

_here = os.path.dirname(__file__)
_proto = os.path.join(_here, "snapshot.proto")
_gen = os.path.join(_here, "snapshot_pb2.py")

if (
    os.path.exists(_proto)
    and (
        not os.path.exists(_gen)
        or os.path.getmtime(_proto) > os.path.getmtime(_gen)
    )
):
    try:  # best effort; the committed gencode is the fallback
        subprocess.run(
            ["protoc", f"--python_out={_here}", "snapshot.proto"],
            cwd=_here, check=True, capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        pass

from . import snapshot_pb2  # noqa: E402,F401
