"""Authentication + authorization for the API server.

The reference wires authn/authz into the generic apiserver's handler
chain (DefaultBuildHandlerChain, apiserver/pkg/server/config.go:983-1028:
authorization at :987, authentication at :1014) with pluggable token
authenticators and RBAC/webhook authorizers.  Ours is the minimal
useful pair:

  * TokenAuthenticator — static bearer-token -> subject map (the
    --token-auth-file pattern, apiserver/pkg/authentication/token);
  * RuleAuthorizer — an ordered allow-list evaluated per
    (subject, verb, kind), "*" wildcards (the ABAC policy-file shape,
    apiserver/plugin/pkg/authorizer/abac reduced to allow rules).

Semantics: with no authenticator every request is anonymous; with one,
a missing/unknown bearer token is 401.  With no authorizer everything
is allowed; with one, any non-matching request is 403.  Reads and
writes use the reference verb set (get/list/watch/create/update/patch/
delete).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Subject:
    name: str
    groups: Tuple[str, ...] = ()


ANONYMOUS = Subject("system:anonymous", ("system:unauthenticated",))


class TokenAuthenticator:
    def __init__(self, tokens: Dict[str, Subject]):
        self._tokens = dict(tokens)

    def authenticate(self, authorization: Optional[str]) -> Optional[Subject]:
        """Subject for an Authorization header value, or None (401)."""
        if not authorization or not authorization.startswith("Bearer "):
            return None
        return self._tokens.get(authorization[len("Bearer "):].strip())


@dataclass
class Rule:
    """Allow rule: subject name OR group must match, plus verb + kind."""

    subjects: Sequence[str] = ("*",)   # names or group names
    verbs: Sequence[str] = ("*",)
    kinds: Sequence[str] = ("*",)

    def matches(self, subject: Subject, verb: str, kind: str) -> bool:
        who = {subject.name, *subject.groups}
        return (
            ("*" in self.subjects or who.intersection(self.subjects))
            and ("*" in self.verbs or verb in self.verbs)
            and ("*" in self.kinds or kind in self.kinds)
        )


READ_VERBS = ("get", "list", "watch")


class RuleAuthorizer:
    def __init__(self, rules: Sequence[Rule]):
        self.rules = list(rules)

    def allowed(
        self, subject: Subject, verb: str, kind: str, namespace: str = ""
    ) -> bool:
        # flat ABAC-style rules have no namespace dimension; every grant
        # is cluster-wide (use RBACAuthorizer for namespace scoping)
        return any(r.matches(subject, verb, kind) for r in self.rules)


class RBACAuthorizer:
    """Role/RoleBinding evaluation (plugin/pkg/auth/authorizer/rbac/
    rbac.go:75 VisitRulesFor):

      ClusterRoleBinding -> ClusterRole   grants everywhere
      RoleBinding        -> Role          grants in the binding's ns
      RoleBinding        -> ClusterRole   grants the cluster role's
                                          rules IN that namespace only

    Bindings and roles are read from the store with a short TTL cache
    (the reference keeps them in informers); the namespace dimension
    makes multi-tenant grants expressible at last."""

    def __init__(self, store, ttl: float = 0.5, clock=None):
        import time as _t

        self.store = store
        self.ttl = ttl
        self._clock = clock or _t.monotonic
        self._cache = None
        self._cached_at = -1e9

    def _snapshot(self):
        now = self._clock()
        if self._cache is not None and now - self._cached_at < self.ttl:
            return self._cache
        roles = {
            (r.meta.namespace, r.meta.name): r
            for r in self.store.list("Role")[0]
        }
        cluster_roles = {
            r.meta.name: r for r in self.store.list("ClusterRole")[0]
        }
        bindings = self.store.list("RoleBinding")[0]
        cluster_bindings = self.store.list("ClusterRoleBinding")[0]
        self._cache = (roles, cluster_roles, bindings, cluster_bindings)
        self._cached_at = now
        return self._cache

    @staticmethod
    def _subject_matches(subjects, subject: Subject) -> bool:
        for s in subjects:
            if s.kind == "User" and s.name == subject.name:
                return True
            if s.kind == "Group" and s.name in subject.groups:
                return True
        return False

    @staticmethod
    def _rules_allow(rules, verb: str, kind: str) -> bool:
        for rule in rules:
            if ("*" in rule.verbs or verb in rule.verbs) and (
                "*" in rule.resources or kind in rule.resources
            ):
                return True
        return False

    def allowed(
        self, subject: Subject, verb: str, kind: str, namespace: str = ""
    ) -> bool:
        roles, cluster_roles, bindings, cluster_bindings = self._snapshot()
        for b in cluster_bindings:
            if not self._subject_matches(b.subjects, subject):
                continue
            role = cluster_roles.get(b.role_ref.name)
            if role is not None and self._rules_allow(role.rules, verb, kind):
                return True
        for b in bindings:
            if namespace and b.meta.namespace != namespace:
                continue
            if not namespace:
                # cluster-scoped request (e.g. list across namespaces):
                # only cluster bindings can grant it
                continue
            if not self._subject_matches(b.subjects, subject):
                continue
            if b.role_ref.kind == "ClusterRole":
                role_rules = cluster_roles.get(b.role_ref.name)
                rules = role_rules.rules if role_rules else []
            else:
                role = roles.get((b.meta.namespace, b.role_ref.name))
                rules = role.rules if role else []
            if self._rules_allow(rules, verb, kind):
                return True
        return False
