"""Authentication + authorization for the API server.

The reference wires authn/authz into the generic apiserver's handler
chain (DefaultBuildHandlerChain, apiserver/pkg/server/config.go:983-1028:
authorization at :987, authentication at :1014) with pluggable token
authenticators and RBAC/webhook authorizers.  Ours is the minimal
useful pair:

  * TokenAuthenticator — static bearer-token -> subject map (the
    --token-auth-file pattern, apiserver/pkg/authentication/token);
  * RuleAuthorizer — an ordered allow-list evaluated per
    (subject, verb, kind), "*" wildcards (the ABAC policy-file shape,
    apiserver/plugin/pkg/authorizer/abac reduced to allow rules).

Semantics: with no authenticator every request is anonymous; with one,
a missing/unknown bearer token is 401.  With no authorizer everything
is allowed; with one, any non-matching request is 403.  Reads and
writes use the reference verb set (get/list/watch/create/update/patch/
delete).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Subject:
    name: str
    groups: Tuple[str, ...] = ()


ANONYMOUS = Subject("system:anonymous", ("system:unauthenticated",))


class TokenAuthenticator:
    def __init__(self, tokens: Dict[str, Subject]):
        self._tokens = dict(tokens)

    def authenticate(self, authorization: Optional[str]) -> Optional[Subject]:
        """Subject for an Authorization header value, or None (401)."""
        if not authorization or not authorization.startswith("Bearer "):
            return None
        return self._tokens.get(authorization[len("Bearer "):].strip())


@dataclass
class Rule:
    """Allow rule: subject name OR group must match, plus verb + kind."""

    subjects: Sequence[str] = ("*",)   # names or group names
    verbs: Sequence[str] = ("*",)
    kinds: Sequence[str] = ("*",)

    def matches(self, subject: Subject, verb: str, kind: str) -> bool:
        who = {subject.name, *subject.groups}
        return (
            ("*" in self.subjects or who.intersection(self.subjects))
            and ("*" in self.verbs or verb in self.verbs)
            and ("*" in self.kinds or kind in self.kinds)
        )


READ_VERBS = ("get", "list", "watch")


class RuleAuthorizer:
    def __init__(self, rules: Sequence[Rule]):
        self.rules = list(rules)

    def allowed(self, subject: Subject, verb: str, kind: str) -> bool:
        return any(r.matches(subject, verb, kind) for r in self.rules)
