"""The API server: the store's REST + watch surface.

Reference shape (reduced): the generic apiserver's REST endpoints +
watch streams (staging/src/k8s.io/apiserver endpoints/handlers,
watch.go) over the storage layer.  One process-boundary protocol so
out-of-process clients — the CLI, remote controllers, a kube shim — use
the same store the in-process components do:

  GET    /api/v1/{kind}                      list (+ ?namespace=)
  GET    /api/v1/{kind}/{ns}/{name}          get
  POST   /api/v1/{kind}                      create (wire-coded body)
  PUT    /api/v1/{kind}/{ns}/{name}          update (optimistic rv;
                                             ?force=1 overrides)
  DELETE /api/v1/{kind}/{ns}/{name}          delete
  GET    /api/v1/watch/{kind}?from_rv=N      newline-delimited JSON
                                             event stream (chunked)

Objects travel as api.wire documents (type-tagged dataclass JSON) —
the codec the journal already uses.  Errors map to the reference's
status codes: 404 NotFound, 409 AlreadyExists/Conflict, 410 Expired.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import store as st
from . import wire


class _Handler(BaseHTTPRequestHandler):
    store: st.Store  # bound by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    # -- helpers -----------------------------------------------------------

    def _reply(self, obj, code: int = 200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, exc: Exception) -> None:
        code = (
            404 if isinstance(exc, st.NotFound)
            else 409 if isinstance(exc, (st.AlreadyExists, st.Conflict))
            else 410 if isinstance(exc, st.Expired)
            else 400
        )
        self._reply({"error": str(exc), "reason": type(exc).__name__}, code)

    def _parts(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        return parts, parse_qs(parsed.query)

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:
        parts, q = self._parts()
        try:
            if len(parts) >= 3 and parts[:2] == ["api", "v1"]:
                if parts[2] == "watch" and len(parts) == 4:
                    return self._watch(parts[3], q)
                if len(parts) == 3:
                    namespace = q.get("namespace", [None])[0]
                    items, rv = self.store.list(parts[2], namespace=namespace)
                    return self._reply(
                        {
                            "items": [wire.to_wire(o) for o in items],
                            "resourceVersion": rv,
                        }
                    )
                if len(parts) == 5:
                    ns = "" if parts[3] == "-" else parts[3]
                    obj = self.store.get(parts[2], parts[4], ns)
                    return self._reply(wire.to_wire(obj))
            if parts == ["healthz"] or parts == ["readyz"]:
                return self._reply({"ok": True})
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_POST(self) -> None:
        parts, _ = self._parts()
        try:
            if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                obj = wire.from_wire(self._body())
                created = self.store.create(obj)
                return self._reply(wire.to_wire(created), 201)
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_PUT(self) -> None:
        parts, q = self._parts()
        try:
            if len(parts) == 5 and parts[:2] == ["api", "v1"]:
                obj = wire.from_wire(self._body())
                force = q.get("force", ["0"])[0] == "1"
                updated = self.store.update(obj, force=force)
                return self._reply(wire.to_wire(updated))
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_DELETE(self) -> None:
        parts, _ = self._parts()
        try:
            if len(parts) == 5 and parts[:2] == ["api", "v1"]:
                ns = "" if parts[3] == "-" else parts[3]
                self.store.delete(parts[2], parts[4], ns)
                return self._reply({"deleted": True})
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _watch(self, kind: str, q) -> None:
        """Newline-delimited JSON watch stream (endpoints/handlers/
        watch.go's chunked frames).  Ends when the client disconnects or
        the store terminates the watch."""
        from_rv = q.get("from_rv", [None])[0]
        w = self.store.watch(kind, int(from_rv) if from_rv else None)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def frame(payload: bytes) -> None:
            self.wfile.write(f"{len(payload):x}\r\n".encode())
            self.wfile.write(payload + b"\r\n")
            self.wfile.flush()

        try:
            while True:
                ev = w.get(timeout=1.0)
                if w.stopped:
                    break
                if ev is None:
                    # idle keepalive (the watch-bookmark pattern): the
                    # write is how a dead client surfaces — without it an
                    # idle watch leaks its thread + store registration
                    frame(
                        (json.dumps({"type": "BOOKMARK",
                                     "rv": self.store.resource_version})
                         + "\n").encode()
                    )
                    continue
                doc = {
                    "type": ev.type,
                    "kind": ev.kind,
                    "rv": ev.rv,
                    "object": wire.to_wire(ev.obj),
                }
                frame((json.dumps(doc) + "\n").encode())
        except Exception:
            # after headers are sent there is no sane error response —
            # any write/socket failure (BrokenPipe, ConnectionAborted,
            # arbitrary OSError) just tears the stream down; letting it
            # escape would make do_GET write a fresh status line into the
            # middle of a chunked body
            pass
        finally:
            w.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass


class APIServer:
    """Threaded HTTP server exposing one Store."""

    def __init__(self, store: st.Store, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"store": store})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
