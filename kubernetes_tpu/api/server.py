"""The API server: the store's REST + watch surface.

Reference shape (reduced): the generic apiserver's REST endpoints +
watch streams (staging/src/k8s.io/apiserver endpoints/handlers,
watch.go) over the storage layer.  One process-boundary protocol so
out-of-process clients — the CLI, remote controllers, a kube shim — use
the same store the in-process components do:

  GET    /api/v1/{kind}                      list (+ ?namespace= and
                                             ?labelSelector= / ?fieldSelector=)
  GET    /api/v1/{kind}/{ns}/{name}          get
  POST   /api/v1/{kind}                      create (wire-coded body)
  PUT    /api/v1/{kind}/{ns}/{name}          update (optimistic rv;
                                             ?force=1 overrides)
  PUT    /api/v1/{kind}/{ns}/{name}/status   status subresource: only
                                             .status from the body lands
  PATCH  /api/v1/{kind}/{ns}/{name}[/status] RFC 7386 JSON merge patch
  DELETE /api/v1/{kind}/{ns}/{name}          delete
  GET    /api/v1/watch/{kind}?from_rv=N      newline-delimited JSON
                                             event stream (chunked)

Objects travel as api.wire documents (type-tagged dataclass JSON) —
the codec the journal already uses.  Errors map to the reference's
status codes: 401/403 authn/authz, 404 NotFound, 409 AlreadyExists/
Conflict, 410 Expired.  Authentication/authorization are optional
constructor hooks (api.auth): bearer tokens -> subjects, allow-list
rules per (subject, verb, kind) — the DefaultBuildHandlerChain slice
(apiserver/pkg/server/config.go:983-1028).
"""

from __future__ import annotations

import json
import socket
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from . import auth as authmod
from . import store as st
from . import wire
from ..testing import faults


def parse_label_selector(expr: str):
    """`a=b,c!=d,e` -> predicate over an object's labels (the
    labels.Parse equality subset + bare-key Exists)."""
    clauses = []
    for raw in expr.split(","):
        raw = raw.strip()
        if not raw:
            continue
        if "!=" in raw:
            k, v = raw.split("!=", 1)
            clauses.append(("!=", k.strip(), v.strip()))
        elif "==" in raw:
            k, v = raw.split("==", 1)
            clauses.append(("=", k.strip(), v.strip()))
        elif "=" in raw:
            k, v = raw.split("=", 1)
            clauses.append(("=", k.strip(), v.strip()))
        else:
            clauses.append(("exists", raw, ""))

    def pred(obj) -> bool:
        labels = obj.meta.labels
        for op, k, v in clauses:
            if op == "=" and labels.get(k) != v:
                return False
            if op == "!=" and labels.get(k) == v:
                return False
            if op == "exists" and k not in labels:
                return False
        return True

    return pred


# fieldSelector paths the reference supports for pods (plus the metadata
# pair every kind has) — dotted wire-field paths resolved on the object
_FIELD_GETTERS = {
    "metadata.name": lambda o: o.meta.name,
    "metadata.namespace": lambda o: o.meta.namespace,
    "spec.nodeName": lambda o: getattr(o.spec, "node_name", ""),
    "status.phase": lambda o: getattr(o.status, "phase", ""),
}


def parse_field_selector(expr: str):
    clauses = []
    for raw in expr.split(","):
        raw = raw.strip()
        if not raw:
            continue
        if "!=" in raw:
            k, v = raw.split("!=", 1)
            op = "!="
        else:
            k, v = raw.split("=", 1)
            op = "="
        getter = _FIELD_GETTERS.get(k.strip())
        if getter is None:
            raise ValueError(f"unsupported fieldSelector {k.strip()!r}")
        clauses.append((op, getter, v.strip()))

    def pred(obj) -> bool:
        for op, getter, v in clauses:
            try:
                actual = str(getter(obj))
            except AttributeError:
                return False
            if op == "=" and actual != v:
                return False
            if op == "!=" and actual == v:
                return False
        return True

    return pred


def merge_patch(base, patch):
    """RFC 7386 JSON merge patch over wire documents: dicts merge
    recursively, null deletes, everything else replaces (the reference's
    application/merge-patch+json handler)."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(base, dict):
        base = {}
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


class _ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + serving-plane accounting: watch-frame
    writes that tripped the per-watcher deadline, the handler threads
    currently inside a request (the chaos suite asserts none stays
    pinned by a dead client), and the open connections — so a replica
    kill can sever live streams the way a process death would."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._stats_lock = threading.Lock()
        self.watch_write_stalls_total = 0
        self._active_handlers = 0
        self._conns: set = set()

    def _note_stall(self) -> None:
        with self._stats_lock:
            self.watch_write_stalls_total += 1

    def _handler_enter(self) -> None:
        with self._stats_lock:
            self._active_handlers += 1

    def _handler_exit(self) -> None:
        with self._stats_lock:
            self._active_handlers -= 1

    def active_handlers(self) -> int:
        """Handler threads currently inside a request (watch streams
        included).  0 at quiesce = no thread pinned by a dead client."""
        with self._stats_lock:
            return self._active_handlers

    # connection tracking: process_request runs on the accept loop,
    # shutdown_request on the worker thread's way out
    def process_request(self, request, client_address):
        with self._stats_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._stats_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        """Sever every live connection (replica kill): in-flight handler
        threads see their socket die mid-write and tear down through the
        normal stream-teardown path."""
        with self._stats_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class _Handler(BaseHTTPRequestHandler):
    store: st.Store  # bound by APIServer
    authn = None     # Optional[auth.TokenAuthenticator]
    authz = None     # Optional[auth.RuleAuthorizer | auth.RBACAuthorizer]
    apf = None       # Optional[flowcontrol.APFGate]
    # a watch frame write blocked past this deadline (stalled TCP
    # consumer: the client stopped reading and the kernel send buffer
    # filled) expires the watch instead of pinning the handler thread
    watch_write_deadline = 10.0
    # test knob: shrink the kernel send buffer so a stalled client's
    # backpressure surfaces after KBs of buffered frames, not MBs
    watch_sndbuf: Optional[int] = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    # -- helpers -----------------------------------------------------------

    def _authorize(self, verb: str, kind: str, namespace: str = "") -> bool:
        """authn -> flow-control -> authz gate; replies 401/429/403 and
        returns False on rejection.  healthz stays open (the reference
        exempts health endpoints before the chain).  The APF seat, once
        acquired, is released by the do_* wrapper's finally — except for
        watches, which release it as soon as the stream is established
        (_watch) so long-lived streams can't pin seats."""
        faults.fire("server.request", verb=verb, kind=kind)
        subject = authmod.ANONYMOUS
        if self.authn is not None:
            subject = self.authn.authenticate(
                self.headers.get("Authorization")
            )
            if subject is None:
                self._reply({"error": "unauthorized",
                             "reason": "Unauthorized"}, 401)
                return False
        if self.apf is not None and self._apf_seat is None:
            seat = self.apf.acquire(subject, verb)
            if seat is None:
                # shed: Retry-After widens with the gate's adaptive
                # pressure so rejected clients back off harder the
                # deeper the overload (static gates report 1s)
                retry = max(
                    1, int(getattr(self.apf, "retry_after_s", lambda: 1.0)())
                )
                data = json.dumps(
                    {"error": "too many requests", "reason": "TooManyRequests"}
                ).encode()
                self.send_response(429)
                self.send_header("Retry-After", str(retry))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return False
            self._apf_seat = seat
        if self.authz is not None and not self.authz.allowed(
            subject, verb, kind, namespace
        ):
            self._reply(
                {"error": f"{subject.name} cannot {verb} {kind}"
                 + (f" in {namespace!r}" if namespace else ""),
                 "reason": "Forbidden"},
                403,
            )
            return False
        return True

    # every request handler runs inside this wrapper so an acquired APF
    # seat is always released, whatever path the verb takes
    def handle_one_request(self):  # noqa: N802 (stdlib name)
        self._apf_seat = None
        srv = self.server
        track = isinstance(srv, _ServingHTTPServer)
        if track:
            srv._handler_enter()
        try:
            super().handle_one_request()
        finally:
            if self._apf_seat is not None:
                self._apf_seat.release()
                self._apf_seat = None
            if track:
                srv._handler_exit()

    def _reply(self, obj, code: int = 200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, exc: Exception) -> None:
        code = (
            404 if isinstance(exc, st.NotFound)
            else 409 if isinstance(exc, (st.AlreadyExists, st.Conflict))
            else 410 if isinstance(exc, st.Expired)
            else 400
        )
        self._reply({"error": str(exc), "reason": type(exc).__name__}, code)

    def _parts(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        return parts, parse_qs(parsed.query)

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:
        parts, q = self._parts()
        try:
            if len(parts) >= 3 and parts[:2] == ["api", "v1"]:
                if parts[2] == "watch" and len(parts) == 4:
                    if not self._authorize("watch", parts[3]):
                        return
                    return self._watch(parts[3], q)
                if len(parts) == 3:
                    namespace = q.get("namespace", [None])[0]
                    if not self._authorize("list", parts[2], namespace or ""):
                        return
                    preds = []
                    if q.get("labelSelector"):
                        preds.append(
                            parse_label_selector(q["labelSelector"][0])
                        )
                    if q.get("fieldSelector"):
                        preds.append(
                            parse_field_selector(q["fieldSelector"][0])
                        )
                    selector = (
                        (lambda o: all(p(o) for p in preds)) if preds
                        else None
                    )
                    items, rv = self.store.list(
                        parts[2], namespace=namespace, selector=selector
                    )
                    return self._reply(
                        {
                            "items": [wire.to_wire(o) for o in items],
                            "resourceVersion": rv,
                        }
                    )
                if len(parts) == 5:
                    ns = "" if parts[3] == "-" else parts[3]
                    if not self._authorize("get", parts[2], ns):
                        return
                    obj = self.store.get(parts[2], parts[4], ns)
                    return self._reply(wire.to_wire(obj))
            if parts == ["api", "v1"]:
                # discovery (the APIResourceList kubectl uses to map
                # names) rides the full chain like any read — only
                # healthz/readyz are exempt
                if not self._authorize("get", "APIResourceList"):
                    return
                from . import kubeyaml

                kinds = sorted(
                    set(self.store.kinds()) | set(kubeyaml.CONVERTERS)
                )
                return self._reply({
                    "kind": "APIResourceList",
                    "groupVersion": "v1",
                    "resources": [
                        {
                            "kind": k,
                            "verbs": ["get", "list", "watch", "create",
                                      "update", "patch", "delete"],
                        }
                        for k in kinds
                    ],
                })
            if parts == ["healthz"] or parts == ["readyz"]:
                return self._reply({"ok": True})
            if parts == ["metrics"]:
                # metrics go through the full chain like any resource
                # (the reference grants system:monitoring via authz —
                # only healthz/readyz are exempt)
                if not self._authorize("get", "metrics"):
                    return
                body = self.apf.metrics() if self.apf is not None else ""
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_POST(self) -> None:
        parts, _ = self._parts()
        try:
            if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                obj = wire.from_wire(self._body())
                ns = getattr(obj.meta, "namespace", "") or ""
                if not self._authorize("create", parts[2], ns):
                    return
                created = self.store.create(obj)
                return self._reply(wire.to_wire(created), 201)
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_PUT(self) -> None:
        parts, q = self._parts()
        try:
            if (
                len(parts) == 6
                and parts[:2] == ["api", "v1"]
                and parts[5] == "status"
            ):
                # status subresource: only .status from the body lands —
                # spec edits through this path are dropped (the
                # StatusStrategy PrepareForUpdate contract,
                # registry/core/pod/strategy.go podStatusStrategy)
                ns = "" if parts[3] == "-" else parts[3]
                if not self._authorize("update", parts[2], ns):
                    return
                incoming = wire.from_wire(self._body())
                current = self.store.get(parts[2], parts[4], ns)
                current.status = incoming.status
                updated = self.store.update(current)
                return self._reply(wire.to_wire(updated))
            if len(parts) == 5 and parts[:2] == ["api", "v1"]:
                ns = "" if parts[3] == "-" else parts[3]
                if not self._authorize("update", parts[2], ns):
                    return
                obj = wire.from_wire(self._body())
                force = q.get("force", ["0"])[0] == "1"
                updated = self.store.update(obj, force=force)
                return self._reply(wire.to_wire(updated))
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_PATCH(self) -> None:
        """RFC 7386 merge patch on the object's wire document (or its
        status subresource) — endpoints/handlers/patch.go reduced to the
        merge-patch content type."""
        parts, _ = self._parts()
        try:
            is_status = (
                len(parts) == 6
                and parts[:2] == ["api", "v1"]
                and parts[5] == "status"
            )
            if (len(parts) == 5 or is_status) and parts[:2] == ["api", "v1"]:
                ns = "" if parts[3] == "-" else parts[3]
                if not self._authorize("patch", parts[2], ns):
                    return
                patch = self._body()
                if not isinstance(patch, dict):
                    return self._reply(
                        {"error": "merge patch body must be a JSON object",
                         "reason": "BadRequest"},
                        400,
                    )
                current = self.store.get(parts[2], parts[4], ns)
                doc = wire.to_wire(current)
                if is_status:
                    patch = {"status": patch.get("status", patch)}
                merged = merge_patch(doc, patch)
                obj = wire.from_wire(merged)
                # the patch applies to what was READ: keep its rv so a
                # concurrent writer surfaces as 409, not silent clobber
                obj.meta.resource_version = current.meta.resource_version
                updated = self.store.update(obj)
                return self._reply(wire.to_wire(updated))
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def do_DELETE(self) -> None:
        parts, _ = self._parts()
        try:
            if len(parts) == 5 and parts[:2] == ["api", "v1"]:
                ns = "" if parts[3] == "-" else parts[3]
                if not self._authorize("delete", parts[2], ns):
                    return
                self.store.delete(parts[2], parts[4], ns)
                return self._reply({"deleted": True})
            self._reply({"error": f"unknown path {self.path}"}, 404)
        except Exception as e:
            self._error(e)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _watch(self, kind: str, q) -> None:
        """Newline-delimited JSON watch stream (endpoints/handlers/
        watch.go's chunked frames).  Ends when the client disconnects or
        the store terminates the watch."""
        # The APF seat gates watch INITIALIZATION only (the reference's
        # apf_filter.go forgetWatch): a seat held for the stream's whole
        # lifetime would let N long-lived watches from one priority level
        # permanently exhaust its N seats and 429 every later request in
        # that class.  Release it here; handle_one_request's finally sees
        # None and won't double-release.
        if self._apf_seat is not None:
            self._apf_seat.release()
            self._apf_seat = None
        from_rv = q.get("from_rv", [None])[0]
        w = self.store.watch(kind, int(from_rv) if from_rv else None)
        if self.watch_sndbuf:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF, int(self.watch_sndbuf)
            )
        # the per-watcher write deadline: a send that cannot make
        # progress for this long (client stopped reading, kernel send
        # buffer full) raises socket.timeout instead of parking the
        # thread forever — the 1s bookmark keepalive guarantees a
        # stalled stream reaches a blocked write within ~1 frame
        self.connection.settimeout(self.watch_write_deadline)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def frame(payload: bytes) -> None:
            action = faults.fire(
                "server.watch.write", kind=kind, size=len(payload)
            )
            if isinstance(action, faults.TornWrite):
                # a PREFIX of the chunk, then die mid-frame: the client
                # sees a truncated chunk on a dropped connection
                part = payload[: max(1, int(len(payload) * action.frac))]
                self.wfile.write(f"{len(payload):x}\r\n".encode())
                self.wfile.write(part)
                self.wfile.flush()
                raise OSError("injected mid-frame disconnect")
            self.wfile.write(f"{len(payload):x}\r\n".encode())
            self.wfile.write(payload + b"\r\n")
            self.wfile.flush()

        try:
            while True:
                ev = w.get(timeout=1.0)
                if w.stopped:
                    break
                if ev is None:
                    # idle keepalive (the watch-bookmark pattern): the
                    # write is how a dead client surfaces — without it an
                    # idle watch leaks its thread + store registration
                    frame(
                        (json.dumps({"type": "BOOKMARK",
                                     "rv": self.store.resource_version})
                         + "\n").encode()
                    )
                    continue
                doc = {
                    "type": ev.type,
                    "kind": ev.kind,
                    "rv": ev.rv,
                    "object": wire.to_wire(ev.obj),
                }
                frame((json.dumps(doc) + "\n").encode())
        except socket.timeout:
            # stalled TCP consumer: the write deadline tripped.  Expire
            # the watch (bookmark rv recorded, consumer relists on
            # reconnect — counted in watch_expired_total) and free the
            # handler thread; a dead client must never pin it.
            srv = self.server
            if isinstance(srv, _ServingHTTPServer):
                srv._note_stall()
            with w._mu:
                w._expire_locked()
            self.store._retire_expired_watch(w, kind)
            self.close_connection = True
            # drop the socket NOW: the buffered writer must not block
            # another deadline's worth flushing into a full send buffer
            # (the finally's terminal chunk + stdlib close both write)
            try:
                self.connection.close()
            except OSError:
                pass
        except Exception:
            # after headers are sent there is no sane error response —
            # any write/socket failure (BrokenPipe, ConnectionAborted,
            # arbitrary OSError) just tears the stream down; letting it
            # escape would make do_GET write a fresh status line into the
            # middle of a chunked body
            pass
        finally:
            w.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass


class APIServer:
    """Threaded HTTP server exposing one Store.

    authn/authz: optional api.auth.TokenAuthenticator /
    api.auth.RuleAuthorizer — None keeps the surface open (the
    --anonymous-auth development posture)."""

    def __init__(
        self,
        store: st.Store,
        host: str = "127.0.0.1",
        port: int = 0,
        authn=None,
        authz=None,
        apf=None,  # flowcontrol.APFGate, or an APF config dict/YAML/path
        watch_write_deadline: float = 10.0,
        watch_sndbuf: Optional[int] = None,
    ):
        if apf is not None and not hasattr(apf, "acquire"):
            # config-shaped apf (dict / YAML string / file path): the
            # per-level seat knobs are deployment configuration, not
            # code — build the gate here (flowcontrol.APFGate.from_config)
            from . import flowcontrol

            apf = flowcontrol.APFGate.from_config(apf)
        self.apf = apf
        handler = type(
            "BoundHandler", (_Handler,),
            {
                "store": store, "authn": authn, "authz": authz, "apf": apf,
                "watch_write_deadline": watch_write_deadline,
                "watch_sndbuf": watch_sndbuf,
            },
        )
        self.httpd = _ServingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def watch_write_stalls_total(self) -> int:
        return self.httpd.watch_write_stalls_total

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class APIServerReplicaSet:
    """N read-replica :class:`APIServer` instances over ONE sharded
    Store — the fleet-scale serving plane behind the leader-elected
    scheduler.

    All replicas share the store, one APF gate and one
    :class:`flowcontrol.AdaptiveAPF` controller, so admission pressure
    and seat accounting are fleet-wide, not per-process.  The
    bounded-staleness contract falls out of the shared store: a list at
    rv R from ANY replica followed by ``watch?from_rv=R`` against any
    replica — including one that replaced a killed instance — replays
    from the shared event ring (or 410s into a relist) and converges on
    exact leader state; rv-gating and relist-on-Expired are exactly the
    single-server semantics.

    ``kill()`` severs a replica's live connections the way a process
    death would (client watch streams see dropped sockets and fail over
    to another replica); ``restart()`` brings a fresh instance up on a
    new port.  The scheduler feeds ``note_scheduler`` each cycle via the
    ``store.serving_plane`` weakref and mirrors ``serving_stats()`` into
    its Registry."""

    GUARDED_FIELDS = {
        "_servers": "_lock",
        "_stall_base": "_lock",
        "replica_failovers_total": "_lock",
    }

    def __init__(
        self,
        store: st.Store,
        replicas: int = 2,
        authn=None,
        authz=None,
        apf=None,
        watch_write_deadline: float = 10.0,
        watch_sndbuf: Optional[int] = None,
        depth_threshold: int = 256,
        recover_after: int = 3,
    ):
        from . import flowcontrol

        if apf is None:
            apf = flowcontrol.APFGate()
        elif not hasattr(apf, "acquire"):
            apf = flowcontrol.APFGate.from_config(apf)
        self.store = store
        self.apf = apf
        self.adaptive = flowcontrol.AdaptiveAPF(
            apf, depth_threshold=depth_threshold, recover_after=recover_after
        )
        self._authn = authn
        self._authz = authz
        self._deadline = watch_write_deadline
        self._sndbuf = watch_sndbuf
        self._lock = threading.Lock()
        self.replica_failovers_total = 0
        # stalls recorded by instances that have since been killed: the
        # fleet-wide counter must not reset when a replica dies
        self._stall_base = 0
        self._servers: List[Optional[APIServer]] = [
            self._spawn() for _ in range(replicas)
        ]
        # the scheduler's per-cycle mirror hook (weak: the replica set's
        # lifetime belongs to whoever built it, not to the store)
        store.serving_plane = weakref.ref(self)

    def _spawn(self) -> APIServer:
        return APIServer(
            self.store, authn=self._authn, authz=self._authz, apf=self.apf,
            watch_write_deadline=self._deadline, watch_sndbuf=self._sndbuf,
        ).start()

    def servers(self) -> List[APIServer]:
        with self._lock:
            return [s for s in self._servers if s is not None]

    def urls(self) -> List[str]:
        return [s.url for s in self.servers()]

    def kill(self, index: int) -> None:
        """Abrupt replica death: sever its live connections, stop the
        accept loop.  Clients discover the survivor set via urls()."""
        with self._lock:
            srv = self._servers[index]
            self._servers[index] = None
            if srv is None:
                return
            self._stall_base += srv.httpd.watch_write_stalls_total
            self.replica_failovers_total += 1
        srv.httpd.close_all_connections()
        srv.stop()

    def restart(self, index: int) -> APIServer:
        """A fresh instance in the killed slot (new port — restarted
        processes don't inherit sockets)."""
        srv = self._spawn()
        with self._lock:
            stale = [s for s in (self._servers[index],) if s is not None]
            self._servers[index] = srv
        for s in stale:
            s.httpd.close_all_connections()
            s.stop()
        return srv

    def stop(self) -> None:
        with self._lock:
            servers = [s for s in self._servers if s is not None]
            self._servers = [None] * len(self._servers)
        for srv in servers:
            srv.httpd.close_all_connections()
            srv.stop()

    def active_handlers(self) -> int:
        return sum(s.httpd.active_handlers() for s in self.servers())

    def note_scheduler(self, overload_level: int, store=None) -> int:
        """The scheduler's per-cycle feed: its overload level + the
        store's watch/dispatch depth → the adaptive APF ladder."""
        ws = (store or self.store).watch_stats()
        return self.adaptive.note(
            overload_level=overload_level,
            watch_depth=ws["watch_queue_depth"],
            dispatch_depth=ws.get("watch_dispatch_depth", 0),
        )

    def serving_stats(self) -> dict:
        """The four serving-plane gauges the scheduler mirrors
        (Registry names scheduler_apf_* / scheduler_server_* /
        scheduler_replica_*).  Stall counts are cumulative across killed
        instances."""
        with self._lock:
            stalls = self._stall_base + sum(
                s.httpd.watch_write_stalls_total
                for s in self._servers if s is not None
            )
            failovers = self.replica_failovers_total
        return {
            "apf_seats_current": self.apf.seats_current(),
            "apf_rejected_total": self.apf.rejected_total(),
            "server_watch_write_stalls_total": stalls,
            "replica_failovers_total": failovers,
        }
