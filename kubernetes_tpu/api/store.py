"""In-memory versioned object store with watch streams.

The control-plane data path of the reference collapses into one process:
etcd revisions + the apiserver's generic registry + the watch cache
(storage/etcd3/store.go:106, registry/generic/registry/store.go:414,
storage/cacher/cacher.go:337-514) become a single store with a monotonic
resourceVersion, per-kind keyspaces, and fan-out watch channels serving
events from a bounded ring buffer.

Semantics kept from the reference:
  * every successful write bumps one global resourceVersion (etcd
    revision semantics: one counter across kinds);
  * optimistic concurrency: update with a stale resource_version fails
    with Conflict (GuaranteedUpdate's retry trigger);
  * list returns (items, rv) so a watch can resume from that rv
    (reflector's ListAndWatch contract, reflector.go:340);
  * watch(from_rv) replays buffered events after from_rv, then streams;
    a from_rv older than the buffer raises Expired — the client relists
    (the 410 Gone path).

Threading: writes and watch dispatch hold one lock; delivery is
per-watcher bounded queues.  A slow watcher that overflows its queue is
stopped (the cacher's terminate-blocked-watcher behaviour,
cacher.go dispatchEvent) and must relist.
"""

from __future__ import annotations

import copy
import logging
import queue
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..testing import faults
from . import types as api

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """Stale resourceVersion on update/delete."""


class Expired(ValueError):
    """Watch start revision fell out of the event buffer (410 Gone)."""


@dataclass
class Event:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any           # deep copy at dispatch time
    rv: int


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}" if namespace else name


class Watch:
    """One watch stream: iterate to receive events; stop() to cancel.
    Iteration ends when the store stops the watch (overflow/close)."""

    _SENTINEL = object()

    def __init__(self, store: "Store", capacity: int):
        self._store = store
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self.stopped = False

    def stop(self) -> None:
        self._store._drop_watch(self)
        self._close()

    def _close(self) -> None:
        if not self.stopped:
            self.stopped = True
            try:
                self._q.put_nowait(self._SENTINEL)
            except queue.Full:
                # the overflow-kill path closes a FULL queue: evict one
                # buffered event to guarantee the sentinel lands — the
                # stream is already lossy (that's why it's being killed)
                # and a consumer blocked on get() with no sentinel would
                # hang its reflector FOREVER instead of relisting
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    self._q.put_nowait(self._SENTINEL)
                except queue.Full:
                    pass  # __next__'s stopped check is the backstop

    def _offer(self, ev: Event) -> bool:
        # hot path (per event per watcher): the disarmed check is one
        # module-attribute load, not a function call
        if faults._registry is not None and faults.fire("watch.offer") == faults.DROP:
            # injected slow watcher: the store treats a refused offer
            # exactly like a full queue — overflow-kill + relist
            return False
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            return False

    def __iter__(self) -> Iterator[Event]:
        return self

    def __next__(self) -> Event:
        while True:
            try:
                # bounded wait so a lost sentinel can never park the
                # consumer forever (belt to _close()'s braces)
                ev = self._q.get(timeout=0.5)
            except queue.Empty:
                if self.stopped:
                    raise StopIteration from None
                continue
            if ev is self._SENTINEL:
                raise StopIteration
            return ev

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """One event, or None on timeout / stream end."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return None if ev is self._SENTINEL else ev


class Store:
    """The single-process control-plane store (see module docstring).

    With `journal_path`, every committed write appends one JSON line
    (op, rv, type-tagged object — api.wire codec) and construction
    replays the file: the crash-only resume property whose reference
    counterpart is every component rebuilding from etcd on restart
    (storage/etcd3/store.go; SURVEY §5.4).  Replay re-applies writes
    without re-journaling and leaves the event buffer empty — watchers
    attach after recovery and relist, exactly like a reflector hitting a
    fresh apiserver."""

    # graftlint guarded-by declarations: object maps, version counters,
    # the event ring, watcher fan-out lists, and all journal state share
    # the store mutex (writes and watch dispatch hold one lock — module
    # docstring)
    GUARDED_FIELDS = {
        "_rv": "_lock",
        "_objects": "_lock",
        "_versions": "_lock",
        "_buffer": "_lock",
        "_watchers": "_lock",
        "_journal": "_lock",
        "_journal_records": "_lock",
        "_journal_dirty": "_lock",
        "_journal_flushed_at": "_lock",
        "watchers_terminated": "_lock",
        "terminated_kinds": "_lock",
        "journal_recovered_records": "_lock",
        "journal_tail_truncations": "_lock",
        "journal_write_errors": "_lock",
    }
    # reviewed lock-free: replay/compaction run from __init__ before the
    # store is shared; the rest document "caller holds the lock"
    LOCKED_METHODS = frozenset({
        "_replay_journal",
        "_compact_journal",
        "_flush_journal",
        "_journal_commit",
        "_append_journal",
        "_append_journal_wave",
        "_dispatch",
        "_dispatch_wave",
    })

    def __init__(
        self,
        buffer_size: int = 4096,
        # per-watcher queue matches the event buffer: a watcher that
        # can't hold buffer_size events couldn't relist-recover either,
        # and a 4k bind wave must not kill the scheduler's own informer
        watch_capacity: int = 4096,
        journal_path: Optional[str] = None,
        admission=None,
        journal_sync: str = "write",  # "write" | "interval"
    ):
        self._lock = threading.RLock()
        self._rv = 0
        self._objects: Dict[str, Dict[str, Any]] = {}   # kind -> key -> obj
        self._versions: Dict[str, Dict[str, int]] = {}  # kind -> key -> rv
        self._buffer: List[Event] = []                  # ring of recent events
        self._buffer_size = buffer_size
        self._watch_capacity = watch_capacity
        self._watchers: Dict[str, List[Watch]] = {}     # kind -> watches
        self.watchers_terminated = 0                    # slow-watcher kills
        self.terminated_kinds: List[str] = []           # ... by kind
        # optional api.admission.AdmissionChain: mutate-then-validate on
        # every create/update before the commit (the apiserver admission
        # chain's position in the write path, server/config.go:983)
        self._admission = admission
        if admission is not None and getattr(admission, "store", None) is None:
            admission.store = self  # plugin initializer (wants_store)
        self._journal = None
        self._journal_path = journal_path
        self._journal_records = 0
        self._journal_dirty = False
        self._journal_flushed_at = time.monotonic()
        # journal health/recovery counters (surfaced as
        # scheduler_journal_recovered_records by the perf collectors):
        #   recovered — corrupt records replay survived (skipped mid-file
        #       lines + truncated tails), i.e. every time the CRC path
        #       saved a restart;
        #   tail truncations — torn final appends cut back to the last
        #       good record;
        #   write errors — appends/flushes that failed and were contained
        #       (the store keeps serving; durability is degraded until
        #       appends succeed again).
        self.journal_recovered_records = 0
        self.journal_tail_truncations = 0
        self.journal_write_errors = 0
        # "write": flush per record — every acknowledged write is on
        # disk (etcd's ack-after-fsync contract; the replay test's
        # kill-anywhere guarantee).  "interval": group-commit with a
        # bounded <=_JOURNAL_FLUSH_S loss window for write-heavy
        # deployments (etcd batches proposals into one fsync the same
        # way; our window trades the ack barrier for throughput).
        self._journal_sync = journal_sync
        if journal_path:
            replayed = self._replay_journal(journal_path)
            live = sum(len(objs) for objs in self._objects.values())
            if replayed > max(1024, 4 * live):
                # compaction: rewrite history as one ADDED per live object
                # (the etcd-compaction analogue) — otherwise churny
                # writers (lease renewals every few seconds) grow the file
                # and replay time without bound
                self._compact_journal(journal_path)
            else:
                self._journal = open(journal_path, "a")
                self._journal_records = replayed
            if journal_sync == "interval":
                # bounds the crash window left by batched flushing: any
                # record older than _JOURNAL_FLUSH_S is on disk
                t = threading.Thread(
                    target=self._journal_flusher,
                    name="journal-flush",
                    daemon=True,
                )
                t.start()

    _JOURNAL_FLUSH_S = 0.05

    def _journal_flusher(self) -> None:
        while True:
            time.sleep(self._JOURNAL_FLUSH_S)
            with self._lock:
                if self._journal is None:
                    return
                if self._journal_dirty:
                    try:
                        self._journal.flush()
                    except ValueError:  # closed mid-compaction race
                        pass
                    self._journal_dirty = False
                    self._journal_flushed_at = time.monotonic()

    # -- journal (crash-only durability) -----------------------------------

    @staticmethod
    def _encode_record(rec: dict) -> str:
        """One journal line: the record JSON with a trailing crc32 over
        the crc-less serialization.  Replay re-serializes the parsed
        record (key order and value round-trips are stable under
        json.dumps) and compares — a partial page write or bit flip
        anywhere in the line fails the check even when the damage still
        parses as JSON."""
        import json

        s = json.dumps(rec)
        return '%s, "crc": %d}\n' % (s[:-1], zlib.crc32(s.encode()))

    @staticmethod
    def _record_crc_ok(rec: dict, crc) -> bool:
        import json

        if crc is None:
            return True  # pre-CRC journal line: accept (upgrade path)
        return zlib.crc32(json.dumps(rec).encode()) == crc

    def _replay_journal(self, path: str) -> int:
        import json
        import os

        from . import wire

        if not os.path.exists(path):
            return 0
        replayed = 0
        good_offset = 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            for raw in f:
                line = raw.decode(errors="replace").strip()
                if not line:
                    good_offset += len(raw)
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("journal record is not an object")
                    crc = rec.pop("crc", None)
                    if not self._record_crc_ok(rec, crc):
                        raise ValueError("journal record crc mismatch")
                    op, rv, kind = rec["op"], rec["rv"], rec["kind"]
                    key = rec["key"]
                    obj = (
                        None if op == DELETED else wire.from_wire(rec["obj"])
                    )
                except (json.JSONDecodeError, ValueError, KeyError, TypeError):
                    # undecodable, CRC-failing, OR structurally-corrupt
                    # record (a line that parses as JSON but lost its
                    # fields or its object payload aborts replay just as
                    # hard as a torn one)
                    self.journal_recovered_records += 1
                    if good_offset + len(raw) >= size:
                        # corrupt TAIL (the first corrupt record with
                        # nothing valid after it): the process died
                        # mid-append; the record was never acknowledged
                        # durable — stop replay and truncate so appends
                        # continue from the last good line
                        self.journal_tail_truncations += 1
                        with open(path, "r+b") as t:
                            t.truncate(good_offset)
                        break
                    # mid-file corruption (partial page write): records
                    # AFTER it were acknowledged durable — skip the bad
                    # line, keep replaying, do NOT truncate them away
                    logging.getLogger(__name__).error(
                        "journal %s: corrupt record at offset %d "
                        "(not tail); skipping it and keeping later "
                        "records", path, good_offset,
                    )
                    good_offset += len(raw)
                    continue
                objs = self._objects.setdefault(kind, {})
                vers = self._versions.setdefault(kind, {})
                if op == DELETED:
                    objs.pop(key, None)
                    vers.pop(key, None)
                else:
                    objs[key] = obj
                    vers[key] = rv
                self._rv = max(self._rv, rv)
                replayed += 1
                good_offset += len(raw)
        return replayed

    def _compact_journal(self, path: str) -> None:
        """Rewrite history as one ADDED per live object, crash-safely:
        write-temp, flush+fsync the temp, then atomic rename — a crash
        at ANY point leaves either the old journal or the complete new
        one, never a half-written mix (the etcd snapshot+WAL-rotation
        discipline)."""
        import os

        from . import wire

        tmp = path + ".compact"
        n = 0
        with open(tmp, "w") as f:
            for kind, objs in self._objects.items():
                for key, obj in objs.items():
                    rec = {
                        "op": ADDED,
                        "rv": self._versions[kind][key],
                        "kind": kind,
                        "key": key,
                        "obj": wire.to_wire(obj),
                    }
                    f.write(self._encode_record(rec))
                    n += 1
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory so the rename itself is durable
        try:
            dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without directory fsync
        self._journal = open(path, "a")
        self._journal_records = n

    def _flush_journal(self) -> None:
        # caller holds the lock
        faults.fire("store.journal.fsync")
        self._journal.flush()

    def _journal_commit(self, lines: List[str]) -> None:
        """Write+flush journal lines with failure containment: a torn or
        failed append degrades durability (counted, logged) but never
        fails the already-committed in-memory write — the store keeps
        serving (availability over the fsync ack, unlike etcd's
        fail-stop; replay's CRC path handles whatever landed)."""
        try:
            act = faults.fire("store.journal.append", records=len(lines))
            data = "".join(lines)
            if isinstance(act, faults.TornWrite):
                cut = max(1, int(len(data) * act.frac))
                self._journal.write(data[:cut].rstrip("\n"))
                self._journal.flush()
                raise faults.FaultInjected("torn journal append")
            self._journal.write(data)
            if self._journal_sync == "write":
                self._flush_journal()
            else:
                # group commit: one flush covers a burst of records (a
                # bind wave is thousands back-to-back); the flusher
                # thread bounds the window at _JOURNAL_FLUSH_S
                self._journal_dirty = True
                now = time.monotonic()
                if now - self._journal_flushed_at >= self._JOURNAL_FLUSH_S:
                    self._flush_journal()
                    self._journal_dirty = False
                    self._journal_flushed_at = now
        except Exception:  # noqa: BLE001 — durability degradation, not an API error
            self.journal_write_errors += 1
            logging.getLogger(__name__).exception(
                "journal append failed; continuing with degraded durability"
            )
            return
        self._journal_records += len(lines)
        live = sum(len(objs) for objs in self._objects.values())
        if self._journal_records > max(1024, 8 * max(live, 1)):
            try:
                self._journal.close()
                self._compact_journal(self._journal_path)
            except Exception:  # noqa: BLE001
                self.journal_write_errors += 1
                logging.getLogger(__name__).exception(
                    "journal compaction failed; reopening for append"
                )
                if self._journal is None or self._journal.closed:
                    self._journal = open(self._journal_path, "a")

    def _append_journal(self, op: str, kind: str, key: str, obj, rv: int) -> None:
        # caller holds the lock; called after the in-memory commit
        if self._journal is None:
            return
        from . import wire

        rec = {"op": op, "rv": rv, "kind": kind, "key": key}
        if op != DELETED:
            rec["obj"] = wire.to_wire(obj)
        self._journal_commit([self._encode_record(rec)])

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _meta(obj: Any) -> api.ObjectMeta:
        return obj.meta

    def _kind_of(self, obj: Any) -> str:
        kind = getattr(obj, "KIND", None)
        if not kind:
            raise TypeError(f"object {obj!r} has no KIND")
        return kind

    def _dispatch(self, ev: Event) -> None:
        # caller holds the lock
        self._buffer.append(ev)
        if len(self._buffer) > self._buffer_size:
            del self._buffer[: self._buffer_size // 4]
        dead: List[Watch] = []
        for w in self._watchers.get(ev.kind, ()):  # fan-out (cacher.go:514)
            if not w._offer(ev):
                dead.append(w)
        for w in dead:
            self._watchers[ev.kind].remove(w)
            w._close()
            # observability: churn benches assert no watcher was too
            # slow for the event rate (cacher terminations == data loss
            # for that consumer until it relists)
            self.watchers_terminated += 1
            self.terminated_kinds.append(ev.kind)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            admitted = False
            if self._admission is not None:
                # admit a server-side COPY: mutators must never edit the
                # caller's object (a rejected or conflicting write would
                # leave the caller's template silently modified — every other
                # store path deep-copies for exactly this isolation).
                # Admission runs UNDER the store lock: store-reading
                # plugins (quota validator, ClusterIP allocation) are
                # check-then-act otherwise — two concurrent creates could
                # both pass quota or allocate the same ClusterIP.  The
                # reference enforces these inside a storage transaction;
                # the lock is reentrant, so plugin reads are fine.
                obj = self._admission.admit(copy.deepcopy(obj), "CREATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                # resource scope normalization: cluster-scoped objects live
                # at namespace "" regardless of what the caller set (the
                # apiserver rejects these; normalizing keeps every
                # convenience-default caller working)
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            objs = self._objects.setdefault(kind, {})
            if key in objs:
                raise AlreadyExists(f"{kind} {key} exists")
            self._rv += 1
            if not admitted:  # the admitted copy is already unaliased
                obj = copy.deepcopy(obj)
            obj.meta.resource_version = self._rv
            if not obj.meta.creation_timestamp:
                obj.meta.creation_timestamp = time.time()
            objs[key] = obj
            self._versions.setdefault(kind, {})[key] = self._rv
            self._append_journal(ADDED, kind, key, obj, self._rv)
            self._dispatch(Event(ADDED, kind, copy.deepcopy(obj), self._rv))
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        with self._lock:
            try:
                return copy.deepcopy(self._objects[kind][key])
            except KeyError:
                raise NotFound(f"{kind} {key}") from None

    def update(
        self, obj: Any, *, force: bool = False, copy_result: bool = True
    ) -> Any:
        """Optimistic-concurrency update: obj.meta.resource_version must
        match the stored version unless force (the GuaranteedUpdate retry
        loop's compare step).  copy_result=False skips the defensive
        deep copy of the return value for hot-path callers that discard
        it (the scheduler's bind wave) — the returned object is then the
        STORED one and must not be mutated."""
        with self._lock:
            admitted = False
            if self._admission is not None:
                # under the lock for the same check-then-act reason as
                # create(): store-reading validators must see a state no
                # concurrent write can invalidate before the commit
                obj = self._admission.admit(copy.deepcopy(obj), "UPDATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            objs = self._objects.get(kind, {})
            if key not in objs:
                raise NotFound(f"{kind} {key}")
            current_rv = self._versions[kind][key]
            if not force and meta.resource_version != current_rv:
                raise Conflict(
                    f"{kind} {key}: rv {meta.resource_version} != {current_rv}"
                )
            self._rv += 1
            if not admitted:
                obj = copy.deepcopy(obj)
            obj.meta.resource_version = self._rv
            if (
                obj.meta.deletion_timestamp is not None
                and not obj.meta.finalizers
            ):
                # last finalizer dropped on a deleting object: the update
                # completes the two-phase delete (store.go:1176)
                objs.pop(key)
                self._versions[kind].pop(key)
                self._append_journal(DELETED, kind, key, None, self._rv)
                self._dispatch(
                    Event(DELETED, kind, copy.deepcopy(obj), self._rv)
                )
                return obj
            objs[key] = obj
            self._versions[kind][key] = self._rv
            self._append_journal(MODIFIED, kind, key, obj, self._rv)
            self._dispatch(Event(MODIFIED, kind, copy.deepcopy(obj), self._rv))
            return copy.deepcopy(obj) if copy_result else obj

    def update_wave(
        self,
        kind: str,
        updates: List[Tuple[str, str, Callable[[Any], None]]],
        *,
        admit: bool = True,
    ) -> Tuple[List[str], Dict[str, Exception]]:
        """Commit a wave of read-modify-write updates as ONE transaction.

        `updates` is a list of (name, namespace, mutate) where mutate(obj)
        edits a private copy of the stored object in place.  The whole
        wave runs under one lock acquisition with ONE coalesced journal
        append (a single write + flush for every record) and ONE watch
        fan-out pass — the scheduler's bind wave pays per-pod costs only
        for the copy and the mutation, not for lock/journal/dispatch.

        Failure splits per object, never per wave: a missing object, a
        mutate() exception, or an admission rejection lands in the
        returned error map under its "namespace/name" key and the rest of
        the wave commits.  Returns (applied_keys, errors).

        Each committed object still gets its own resourceVersion and its
        own watch Event, so watch/informer semantics are byte-identical
        to per-object update(); only the write-path overhead is shared.
        The dispatched Event aliases the stored object (no defensive
        copy): stored objects are never mutated in place after commit and
        watch consumers already share one Event payload across every
        watcher, so the alias adds no new mutability hazard — it removes
        the single biggest per-pod cost of a 1k-pod bind wave."""
        faults.fire("store.update_wave", kind=kind, updates=len(updates))
        applied: List[str] = []
        errors: Dict[str, Exception] = {}
        events: List[Event] = []
        records: List[Tuple[str, str, Any, int]] = []
        with self._lock:
            objs = self._objects.get(kind, {})
            vers = self._versions.setdefault(kind, {})
            for name, namespace, mutate in updates:
                if kind in api.CLUSTER_SCOPED_KINDS:
                    namespace = ""
                key = _key(namespace, name)
                cur = objs.get(key)
                if cur is None:
                    errors[key] = NotFound(f"{kind} {key}")
                    continue
                obj = copy.deepcopy(cur)
                try:
                    mutate(obj)
                    if admit and self._admission is not None:
                        obj = self._admission.admit(obj, "UPDATE")
                except Exception as e:  # noqa: BLE001 — per-object split
                    errors[key] = e
                    continue
                self._rv += 1
                obj.meta.resource_version = self._rv
                if (
                    obj.meta.deletion_timestamp is not None
                    and not obj.meta.finalizers
                ):
                    # mirror update(): dropping the last finalizer on a
                    # deleting object completes the two-phase delete
                    objs.pop(key)
                    vers.pop(key, None)
                    records.append((DELETED, key, None, self._rv))
                    events.append(Event(DELETED, kind, obj, self._rv))
                else:
                    objs[key] = obj
                    vers[key] = self._rv
                    records.append((MODIFIED, key, obj, self._rv))
                    events.append(Event(MODIFIED, kind, obj, self._rv))
                applied.append(key)
            if records:
                self._append_journal_wave(kind, records)
                self._dispatch_wave(kind, events)
        return applied, errors

    def _append_journal_wave(
        self, kind: str, records: List[Tuple[str, str, Any, int]]
    ) -> None:
        # caller holds the lock; one write + one flush for the wave
        if self._journal is None:
            return
        from . import wire

        lines = []
        for op, key, obj, rv in records:
            rec = {"op": op, "rv": rv, "kind": kind, "key": key}
            if op != DELETED:
                rec["obj"] = wire.to_wire(obj)
            lines.append(self._encode_record(rec))
        self._journal_commit(lines)

    def _dispatch_wave(self, kind: str, events: List[Event]) -> None:
        # caller holds the lock; one buffer extend + one fan-out pass
        # over the kind's watchers instead of len(events) passes
        self._buffer.extend(events)
        excess = len(self._buffer) - self._buffer_size
        if excess > 0:
            del self._buffer[: excess + self._buffer_size // 4]
        dead: List[Watch] = []
        for w in self._watchers.get(kind, ()):
            for ev in events:
                if not w._offer(ev):
                    dead.append(w)
                    break
        for w in dead:
            self._watchers[kind].remove(w)
            w._close()
            self.watchers_terminated += 1
            self.terminated_kinds.append(kind)

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        """Remove an object.  Objects carrying finalizers get the
        reference's two-phase deletion (registry/generic/registry/
        store.go:1116): deletionTimestamp is set and a MODIFIED event
        fires; the real removal happens when the last finalizer is
        dropped via update() — the node agent's graceful pod shutdown
        and any future finalizing controller ride this."""
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        with self._lock:
            objs = self._objects.get(kind, {})
            if key not in objs:
                raise NotFound(f"{kind} {key}")
            obj = objs[key]
            if obj.meta.finalizers and obj.meta.deletion_timestamp is not None:
                # already terminating: delete-on-deleting is a no-op
                # (finalizers still gate the removal; a GC re-delete must
                # not hard-remove mid-grace)
                return copy.deepcopy(obj)
            if obj.meta.finalizers and obj.meta.deletion_timestamp is None:
                obj = copy.deepcopy(obj)
                obj.meta.deletion_timestamp = time.time()
                self._rv += 1
                obj.meta.resource_version = self._rv
                objs[key] = obj
                self._versions[kind][key] = self._rv
                self._append_journal(MODIFIED, kind, key, obj, self._rv)
                self._dispatch(
                    Event(MODIFIED, kind, copy.deepcopy(obj), self._rv)
                )
                return copy.deepcopy(obj)
            objs.pop(key)
            self._versions[kind].pop(key)
            self._rv += 1
            self._append_journal(DELETED, kind, key, None, self._rv)
            self._dispatch(Event(DELETED, kind, copy.deepcopy(obj), self._rv))
            return obj

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Callable[[Any], bool]] = None,
    ) -> Tuple[List[Any], int]:
        """(items, resource_version) — the ListAndWatch handoff point."""
        with self._lock:
            items = [
                copy.deepcopy(o)
                for o in self._objects.get(kind, {}).values()
                if (namespace is None or o.meta.namespace == namespace)
                and (selector is None or selector(o))
            ]
            return items, self._rv

    def kinds(self) -> List[str]:
        """Object kinds the store currently holds (the GC/namespace
        controllers sweep every kind, like the reference's
        RESTMapper-driven resource discovery)."""
        with self._lock:
            return [k for k, objs in self._objects.items() if objs]

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, from_rv: Optional[int] = None) -> Watch:
        """Stream events for `kind` after `from_rv` (exclusive).  None
        means 'from now'.  Raises Expired when from_rv predates the event
        buffer — relist and retry (reflector.go 410 handling)."""
        with self._lock:
            w = Watch(self, self._watch_capacity)
            if from_rv is not None:
                oldest_known = self._buffer[0].rv if self._buffer else self._rv + 1
                if from_rv + 1 < oldest_known and from_rv < self._rv:
                    raise Expired(
                        f"rv {from_rv} too old (buffer starts at {oldest_known})"
                    )
                for ev in self._buffer:
                    if ev.kind == kind and ev.rv > from_rv:
                        if not w._offer(ev):
                            # the replay itself overflowed (or was
                            # fault-dropped): this stream would be lossy
                            # FROM BIRTH with no overflow-kill to expose
                            # it — the silently-lost event would never be
                            # re-delivered and its object would stay
                            # stale in every consumer forever.  Refuse
                            # the watch; the client relists (410 path).
                            self.watchers_terminated += 1
                            self.terminated_kinds.append(kind)
                            raise Expired(
                                f"rv {from_rv} replay overflowed the "
                                "watch queue; relist"
                            )
            self._watchers.setdefault(kind, []).append(w)
            return w

    def _drop_watch(self, w: Watch) -> None:
        with self._lock:
            for ws in self._watchers.values():
                if w in ws:
                    ws.remove(w)
                    return

    # -- convenience -------------------------------------------------------

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv
