"""In-memory versioned object store with watch streams.

The control-plane data path of the reference collapses into one process:
etcd revisions + the apiserver's generic registry + the watch cache
(storage/etcd3/store.go:106, registry/generic/registry/store.go:414,
storage/cacher/cacher.go:337-514) become a single store with a monotonic
resourceVersion, per-kind keyspaces, and fan-out watch channels serving
events from a bounded ring buffer.

Semantics kept from the reference:
  * every successful write bumps one global resourceVersion (etcd
    revision semantics: one counter across kinds);
  * optimistic concurrency: update with a stale resource_version fails
    with Conflict (GuaranteedUpdate's retry trigger);
  * list returns (items, rv) so a watch can resume from that rv
    (reflector's ListAndWatch contract, reflector.go:340);
  * watch(from_rv) replays buffered events after from_rv, then streams;
    a from_rv older than the buffer raises Expired — the client relists
    (the 410 Gone path).

Threading: writes hold one lock and only append the committed events to
a dispatch backlog; a dedicated fan-out thread delivers them to
per-watcher bounded COALESCING buffers off the lock, so a slow consumer
can never stall writers.  A watcher that falls behind has its MODIFIED
runs compacted latest-wins and its ADDED+DELETED pairs annihilated;
only when the coalesced backlog itself overflows (more *distinct
objects* pending than the capacity) is the watcher marked Expired —
bookmark rv + forced relist, the 410 path — never silently terminated
(the survivable-overload replacement for the cacher's
terminate-blocked-watcher behaviour; see docs/robustness.md).
"""

from __future__ import annotations

import copy
import logging
import threading
import time
import weakref
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple,
)

from ..testing import faults
from . import types as api

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """Stale resourceVersion on update/delete."""


class Expired(ValueError):
    """Watch start revision fell out of the event buffer (410 Gone)."""


class Fenced(ValueError):
    """A fenced write's leadership lease is stale: the caller was
    deposed between staging the wave and committing it.  The etcd
    analogue is a txn whose lease-ownership compare fails — the late
    wave of a dead leader must never double-bind."""


class FenceToken(NamedTuple):
    """Leadership proof threaded into ``Store.update_wave``: the wave
    commits only while `identity` still holds the named Lease at the
    same acquisition `generation` (lease_transitions when the caller
    acquired).  Minted by ``LeaderElector.fence_token()``."""

    name: str
    namespace: str
    identity: str
    generation: Optional[int] = None


@dataclass
class Event:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any           # deep copy at dispatch time
    rv: int


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}" if namespace else name


# Watch._offer verdicts (read by the fan-out thread)
OFFER_OK = "ok"
OFFER_STOPPED = "stopped"
OFFER_EXPIRED = "expired"


class Watch:
    """One watch stream backed by a bounded per-watcher COALESCING
    buffer: iterate to receive events; stop() to cancel.

    Backpressure semantics (the survivable-overload contract):

      * events for DISTINCT objects queue in rv order;
      * a MODIFIED landing on a pending entry replaces it latest-wins
        (an un-consumed ADDED stays ADDED with the newest object — the
        consumer never saw the original);
      * a DELETED landing on a pending ADDED annihilates both (the
        consumer never learns the object existed);
      * a DELETED landing on a pending MODIFIED collapses to DELETED;
      * an ADDED landing on a pending DELETED (delete + recreate while
        the consumer lagged) collapses to MODIFIED with the new object —
        cache-diffing consumers (SharedInformer) synthesize the right
        local transition either way;
      * compaction always keeps the LATEST rv and re-sorts the entry to
        the back, so delivery stays strictly rv-monotonic.

    Only when the number of distinct pending objects would exceed the
    capacity is the stream EXPIRED: pending events are dropped, the
    bookmark rv recorded, and iteration raises `Expired` so the consumer
    relists (the 410 path).  `stopped` is also set so poll-style
    consumers (agent, kubemark, the HTTP server) fall into their
    existing relist branch.  Consumer-initiated stop() ends iteration
    with StopIteration instead.
    """

    GUARDED_FIELDS = {
        "_pending": "_mu",
        "_last_rv": "_mu",
        "stopped": "_mu",
        "expired": "_mu",
        "expired_rv": "_mu",
        "coalesced": "_mu",
    }

    def __init__(self, store: "Store", capacity: int):
        self._store = store
        self._capacity = capacity
        self._mu = threading.Condition()
        # object key -> coalesced Event, insertion/compaction order ==
        # ascending rv (every insert/replace carries the current max rv
        # and moves to the back)
        self._pending: "OrderedDict[str, Event]" = OrderedDict()
        # highest rv delivered into (or compacted through) this buffer:
        # the fan-out thread's offers dedup against it, which makes the
        # replay-at-registration + async-backlog seam exactly-once
        self._last_rv = 0
        self.stopped = False
        self.expired = False
        self.expired_rv = 0     # bookmark: last consistent rv at expiry
        self.coalesced = 0      # events compacted away in this buffer

    def stop(self) -> None:
        self._store._drop_watch(self)
        with self._mu:
            self.stopped = True
            self._mu.notify_all()

    def _offer(self, ev: Event) -> str:
        # hot path (per event per watcher): the disarmed check is one
        # module-attribute load, not a function call
        if faults._registry is not None and faults.fire("watch.offer") == faults.DROP:
            # injected overload: as if coalescing itself overflowed —
            # the watcher expires and its consumer relists
            with self._mu:
                self._expire_locked()
            return OFFER_EXPIRED
        with self._mu:
            if self.expired:
                return OFFER_EXPIRED
            if self.stopped:
                return OFFER_STOPPED
            if ev.rv <= self._last_rv:
                # already replayed at registration (or re-offered by the
                # backlog after a replay covered it): exactly-once dedup
                return OFFER_OK
            key = _key(ev.obj.meta.namespace, ev.obj.meta.name)
            cur = self._pending.get(key)
            if cur is None:
                if len(self._pending) >= self._capacity:
                    self._expire_locked()
                    return OFFER_EXPIRED
                self._pending[key] = ev
            elif cur.type == ADDED and ev.type == DELETED:
                # annihilation: the consumer never saw the object
                del self._pending[key]
                self.coalesced += 2
            else:
                typ = ev.type
                if cur.type == ADDED and ev.type == MODIFIED:
                    typ = ADDED          # still unseen: stays a create
                elif cur.type == DELETED and ev.type == ADDED:
                    typ = MODIFIED       # delete+recreate: latest-wins
                self._pending[key] = Event(typ, ev.kind, ev.obj, ev.rv)
                self._pending.move_to_end(key)
                self.coalesced += 1
            self._last_rv = ev.rv
            self._mu.notify_all()
            return OFFER_OK

    def _expire_locked(self) -> None:
        if self.expired:
            return
        self.expired = True
        self.stopped = True  # poll-style consumers relist off .stopped
        self.expired_rv = self._last_rv
        # pending events are dropped: the forced relist recovers them
        # (and everything after) from one consistent snapshot
        self._pending.clear()
        self._mu.notify_all()

    def depth(self) -> int:
        with self._mu:
            return len(self._pending)

    def __iter__(self) -> Iterator[Event]:
        return self

    def __next__(self) -> Event:
        if faults._registry is not None:
            faults.fire("watch.consume")  # injected slow consumer
        with self._mu:
            while True:
                if self._pending:
                    _, ev = self._pending.popitem(last=False)
                    return ev
                if self.expired:
                    raise Expired(
                        f"watch expired at rv {self.expired_rv}; relist"
                    )
                if self.stopped:
                    raise StopIteration
                # bounded wait: a missed notify can never park the
                # consumer forever
                self._mu.wait(0.5)

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """One event, or None on timeout / stream end (expiry included —
        check `.expired` / `.stopped` to distinguish and relist)."""
        if faults._registry is not None:
            faults.fire("watch.consume")  # injected slow consumer
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._mu:
            while True:
                if self._pending:
                    _, ev = self._pending.popitem(last=False)
                    return ev
                if self.stopped or self.expired:
                    return None
                if deadline is None:
                    self._mu.wait(0.5)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._mu.wait(remaining)


class Store:
    """The single-process control-plane store (see module docstring).

    With `journal_path`, every committed write appends one JSON line
    (op, rv, type-tagged object — api.wire codec) and construction
    replays the file: the crash-only resume property whose reference
    counterpart is every component rebuilding from etcd on restart
    (storage/etcd3/store.go; SURVEY §5.4).  Replay re-applies writes
    without re-journaling and leaves the event buffer empty — watchers
    attach after recovery and relist, exactly like a reflector hitting a
    fresh apiserver.

    Checkpointing bounds replay: ``checkpoint()`` (also triggered by
    journal growth and, optionally, a wall-clock interval) writes a
    point-in-time snapshot of every live object via write-temp + fsync +
    atomic-rename and truncates the journal past the checkpoint rv, so
    recovery = load snapshot + replay the journal SUFFIX instead of
    replaying history from byte zero (the etcd snapshot + WAL-rotation
    discipline).  A corrupt snapshot falls back to replaying whatever
    the journal holds; ``update_wave`` records are replayed atomically
    (a torn final wave is dropped whole, never half-applied).  Recovery
    observability: ``recovery_duration_ms`` / ``snapshot_records`` /
    ``journal_suffix_records``, mirrored into the scheduler Registry."""

    # graftlint guarded-by declarations: object maps, version counters,
    # the event ring, watcher fan-out lists, and all journal state share
    # the store mutex; the fan-out backlog has its own condition (writers
    # append under _lock -> _dispatch_cv, the dispatcher pops under
    # _dispatch_cv alone — one lock-order direction, never a cycle)
    GUARDED_FIELDS = {
        "_rv": "_lock",
        "_objects": "_lock",
        "_versions": "_lock",
        "_buffer": "_lock",
        "_watchers": "_lock",
        "_journal": "_lock",
        "_journal_records": "_lock",
        "_journal_dirty": "_lock",
        "_journal_flushed_at": "_lock",
        "watchers_terminated": "_lock",
        "terminated_by_kind": "_lock",
        "watch_expired_total": "_lock",
        "_watch_coalesced_closed": "_lock",
        "_dispatch_thread": "_lock",
        "_dispatch_backlog": "_dispatch_cv",
        "_dispatch_inflight": "_dispatch_cv",
        "journal_recovered_records": "_lock",
        "journal_tail_truncations": "_lock",
        "journal_write_errors": "_lock",
        "journal_torn_waves": "_lock",
        "_snapshot_rv": "_lock",
        "_wave_seq": "_lock",
        "_last_checkpoint": "_lock",
        "checkpoints_total": "_lock",
        "snapshot_fallbacks": "_lock",
        "snapshot_records": "_lock",
        "journal_suffix_records": "_lock",
        "recovery_duration_ms": "_lock",
        "fenced_writes_total": "_lock",
    }
    # reviewed lock-free: replay/snapshot-load run from __init__ before
    # the store is shared; the rest document "caller holds the lock"
    LOCKED_METHODS = frozenset({
        "_replay_journal",
        "_load_snapshot",
        "_flush_journal",
        "_journal_commit",
        "_append_journal",
        "_append_journal_wave",
        "_dispatch",
        "_dispatch_wave",
    })

    def __init__(
        self,
        buffer_size: int = 4096,
        # per-watcher queue matches the event buffer: a watcher that
        # can't hold buffer_size events couldn't relist-recover either,
        # and a 4k bind wave must not kill the scheduler's own informer
        watch_capacity: int = 4096,
        journal_path: Optional[str] = None,
        admission=None,
        journal_sync: str = "write",  # "write" | "interval"
        snapshot_path: Optional[str] = None,
        # journal records (post-checkpoint suffix) that trigger an
        # automatic checkpoint; None = max(1024, 8 * live objects)
        checkpoint_records: Optional[int] = None,
        # wall-clock checkpoint cadence; 0 disables periodic checkpoints
        # (growth-triggered ones still run)
        checkpoint_interval_seconds: float = 0.0,
    ):
        self._lock = threading.RLock()
        self._rv = 0
        self._objects: Dict[str, Dict[str, Any]] = {}   # kind -> key -> obj
        self._versions: Dict[str, Dict[str, int]] = {}  # kind -> key -> rv
        self._buffer: List[Event] = []                  # ring of recent events
        self._buffer_size = buffer_size
        self._watch_capacity = watch_capacity
        self._watchers: Dict[str, List[Watch]] = {}     # kind -> watches
        # destructive slow-watcher kills — the backpressured fan-out
        # never performs them, so churn benches assert this stays 0
        self.watchers_terminated = 0
        self.terminated_by_kind: Dict[str, int] = {}    # bounded: one key/kind
        # overload-protection observability (mirrored into the scheduler
        # Registry as scheduler_watch_* each cycle):
        #   expired — watchers converted to bookmark+relist after their
        #       coalescing buffer overflowed (or a replay overflowed);
        #   coalesced (closed) — compacted-event counts folded in from
        #       watchers that have since expired or stopped (live
        #       watchers keep their own counters; watch_stats() sums).
        self.watch_expired_total = 0
        self._watch_coalesced_closed = 0
        # fan-out backlog: writers append committed event batches under
        # the store lock; the dedicated dispatch thread (started lazily
        # with the first watcher, weakly referenced so abandoned stores
        # don't leak pollers) delivers them to the coalescing buffers
        # OFF the lock — a slow consumer can never stall writers
        self._dispatch_cv = threading.Condition()
        self._dispatch_backlog: deque = deque()
        self._dispatch_inflight = False
        self._dispatch_thread: Optional[threading.Thread] = None
        # optional api.admission.AdmissionChain: mutate-then-validate on
        # every create/update before the commit (the apiserver admission
        # chain's position in the write path, server/config.go:983)
        self._admission = admission
        if admission is not None and getattr(admission, "store", None) is None:
            admission.store = self  # plugin initializer (wants_store)
        self._journal = None
        self._journal_path = journal_path
        self._journal_records = 0
        self._journal_dirty = False
        self._journal_flushed_at = time.monotonic()
        # journal health/recovery counters (surfaced as
        # scheduler_journal_recovered_records by the perf collectors):
        #   recovered — corrupt records replay survived (skipped mid-file
        #       lines + truncated tails), i.e. every time the CRC path
        #       saved a restart;
        #   tail truncations — torn final appends cut back to the last
        #       good record;
        #   write errors — appends/flushes that failed and were contained
        #       (the store keeps serving; durability is degraded until
        #       appends succeed again).
        self.journal_recovered_records = 0
        self.journal_tail_truncations = 0
        self.journal_write_errors = 0
        # checkpoint / recovery state (docs/robustness.md recovery
        # contract): the snapshot sits next to the journal; recovery
        # loads it and replays only the journal suffix past its rv.
        self._snapshot_path = snapshot_path or (
            journal_path + ".snap" if journal_path else None
        )
        self._snapshot_rv = 0       # rv the current snapshot covers
        self._wave_seq = 0          # update_wave journal grouping id
        self._checkpoint_records = checkpoint_records
        self._checkpoint_interval = checkpoint_interval_seconds
        self._last_checkpoint = time.monotonic()
        self.checkpoints_total = 0
        # recoveries that found the snapshot corrupt/unreadable and fell
        # back to replaying the full journal instead
        self.snapshot_fallbacks = 0
        # update_wave suffixes dropped whole at replay (torn final wave
        # — atomicity preserved, never half-applied)
        self.journal_torn_waves = 0
        # last recovery's cost split: objects loaded from the snapshot,
        # journal records replayed past it, and the wall time both took
        self.snapshot_records = 0
        self.journal_suffix_records = 0
        self.recovery_duration_ms = 0.0
        # update_wave commits rejected because the caller's FenceToken
        # no longer matched the Lease (a deposed leader's late wave)
        self.fenced_writes_total = 0
        # "write": flush per record — every acknowledged write is on
        # disk (etcd's ack-after-fsync contract; the replay test's
        # kill-anywhere guarantee).  "interval": group-commit with a
        # bounded <=_JOURNAL_FLUSH_S loss window for write-heavy
        # deployments (etcd batches proposals into one fsync the same
        # way; our window trades the ack barrier for throughput).
        self._journal_sync = journal_sync
        if journal_path:
            t_rec = time.monotonic()
            snap_n = self._load_snapshot()
            applied, lines = self._replay_journal(
                journal_path, min_rv=self._snapshot_rv
            )
            self.snapshot_records = snap_n or 0
            self.journal_suffix_records = applied
            self.recovery_duration_ms = (
                time.monotonic() - t_rec
            ) * 1000.0
            live = sum(len(objs) for objs in self._objects.values())
            self._journal = open(journal_path, "a")
            self._journal_records = lines
            if lines > max(1024, 4 * live):
                # replay-time bound: a journal whose suffix dwarfs the
                # live set (churny writers — lease renewals every few
                # seconds) is checkpointed right away, so the NEXT
                # restart pays snapshot + near-empty suffix instead of
                # replaying history (the etcd-compaction analogue)
                try:
                    self._checkpoint_locked()
                except Exception:  # noqa: BLE001 — durability degradation
                    self.journal_write_errors += 1
                    logging.getLogger(__name__).exception(
                        "post-recovery checkpoint failed; journal kept"
                    )
            if journal_sync == "interval":
                # bounds the crash window left by batched flushing: any
                # record older than _JOURNAL_FLUSH_S is on disk
                t = threading.Thread(
                    target=self._journal_flusher,
                    name="journal-flush",
                    daemon=True,
                )
                t.start()

    _JOURNAL_FLUSH_S = 0.05

    def _journal_flusher(self) -> None:
        while True:
            time.sleep(self._JOURNAL_FLUSH_S)
            with self._lock:
                if self._journal is None:
                    return
                if self._journal_dirty:
                    try:
                        self._journal.flush()
                    except ValueError:  # closed mid-compaction race
                        pass
                    self._journal_dirty = False
                    self._journal_flushed_at = time.monotonic()

    # -- journal (crash-only durability) -----------------------------------

    @staticmethod
    def _encode_record(rec: dict) -> str:
        """One journal line: the record JSON with a trailing crc32 over
        the crc-less serialization.  Replay re-serializes the parsed
        record (key order and value round-trips are stable under
        json.dumps) and compares — a partial page write or bit flip
        anywhere in the line fails the check even when the damage still
        parses as JSON."""
        import json

        s = json.dumps(rec)
        return '%s, "crc": %d}\n' % (s[:-1], zlib.crc32(s.encode()))

    @staticmethod
    def _record_crc_ok(rec: dict, crc) -> bool:
        import json

        if crc is None:
            return True  # pre-CRC journal line: accept (upgrade path)
        return zlib.crc32(json.dumps(rec).encode()) == crc

    def _replay_journal(
        self, path: str, min_rv: int = 0
    ) -> Tuple[int, int]:
        """Replay the journal; records at or below `min_rv` (covered by
        the loaded snapshot) are skipped.  update_wave records carry a
        wave id and a terminator: a wave is buffered and applied only
        when its terminator arrives, so a torn final wave is dropped
        WHOLE (truncated like a torn tail — it was never acknowledged
        durable) and a wave holed by mid-file corruption is skipped
        whole, never half-applied.  Returns (applied, good_lines)."""
        import json
        import os

        from . import wire

        if not os.path.exists(path):
            return 0, 0
        replayed = 0
        lines = 0
        good_offset = 0
        size = os.path.getsize(path)
        # wave buffering: (op, rv, kind, key, obj) per pending record
        pending: List[tuple] = []
        pending_wid = None
        pending_offset = 0       # byte offset where the pending wave began
        dead_waves: set = set()  # wave ids dropped by corruption holes

        def apply(op, rv, kind, key, obj) -> None:
            nonlocal replayed
            objs = self._objects.setdefault(kind, {})
            vers = self._versions.setdefault(kind, {})
            if op == DELETED:
                objs.pop(key, None)
                vers.pop(key, None)
            else:
                objs[key] = obj
                vers[key] = rv
            self._rv = max(self._rv, rv)
            replayed += 1

        def drop_pending(why: str) -> None:
            nonlocal pending, pending_wid
            if pending:
                self.journal_torn_waves += 1
                logging.getLogger(__name__).error(
                    "journal %s: dropping incomplete wave %s whole "
                    "(%d records; %s)", path, pending_wid, len(pending),
                    why,
                )
            if pending_wid is not None:
                dead_waves.add(pending_wid)
            pending, pending_wid = [], None

        with open(path, "rb") as f:
            for raw in f:
                line = raw.decode(errors="replace").strip()
                if not line:
                    good_offset += len(raw)
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("journal record is not an object")
                    crc = rec.pop("crc", None)
                    if not self._record_crc_ok(rec, crc):
                        raise ValueError("journal record crc mismatch")
                    op, rv, kind = rec["op"], rec["rv"], rec["kind"]
                    key = rec["key"]
                    obj = (
                        None if op == DELETED else wire.from_wire(rec["obj"])
                    )
                except (json.JSONDecodeError, ValueError, KeyError, TypeError):
                    # undecodable, CRC-failing, OR structurally-corrupt
                    # record (a line that parses as JSON but lost its
                    # fields or its object payload aborts replay just as
                    # hard as a torn one)
                    self.journal_recovered_records += 1
                    if good_offset + len(raw) >= size:
                        # corrupt TAIL (the first corrupt record with
                        # nothing valid after it): the process died
                        # mid-append; the record was never acknowledged
                        # durable — stop replay and truncate so appends
                        # continue from the last good line.  A wave the
                        # torn record belonged to is dropped whole: the
                        # truncation point backs up to the wave's start.
                        self.journal_tail_truncations += 1
                        cut = (
                            pending_offset if pending else good_offset
                        )
                        drop_pending("torn tail inside the wave")
                        with open(path, "r+b") as t:
                            t.truncate(cut)
                        break
                    # mid-file corruption (partial page write): records
                    # AFTER it were acknowledged durable — skip the bad
                    # line, keep replaying, do NOT truncate them away.
                    # A wave holed by the corruption loses its atomicity
                    # guarantee, so the whole wave is dropped instead.
                    drop_pending("mid-file corruption inside the wave")
                    logging.getLogger(__name__).error(
                        "journal %s: corrupt record at offset %d "
                        "(not tail); skipping it and keeping later "
                        "records", path, good_offset,
                    )
                    good_offset += len(raw)
                    continue
                lines += 1
                wid = rec.get("w")
                if wid is not None:
                    self._wave_seq = max(self._wave_seq, int(wid))
                if wid is not None and wid in dead_waves:
                    good_offset += len(raw)
                    continue  # straggler of a dropped wave
                if wid is None:
                    # a plain record while a wave is open means the wave
                    # never terminated (should not happen: waves append
                    # contiguously under the lock) — atomicity wins
                    drop_pending("unterminated wave before plain record")
                    if rv > min_rv:
                        apply(op, rv, kind, key, obj)
                else:
                    if pending_wid is not None and wid != pending_wid:
                        drop_pending("unterminated wave before next wave")
                    if not pending:
                        pending_offset = good_offset
                    pending_wid = wid
                    if rv > min_rv:
                        pending.append((op, rv, kind, key, obj))
                    if rec.get("wz"):
                        # terminator: the whole wave is on disk — commit
                        for entry in pending:
                            apply(*entry)
                        pending, pending_wid = [], None
                good_offset += len(raw)
            else:
                if pending:
                    # EOF with an open wave: the terminator never made
                    # it to disk — drop the wave whole and truncate so
                    # appends continue from before it
                    drop_pending("torn final wave (no terminator)")
                    self.journal_tail_truncations += 1
                    with open(path, "r+b") as t:
                        t.truncate(pending_offset)
        return replayed, lines

    @staticmethod
    def _fsync_dir(path: str) -> None:
        """fsync the directory holding `path` so a rename into it is
        itself durable."""
        import os

        try:
            dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without directory fsync

    def _load_snapshot(self) -> Optional[int]:
        """Load the checkpoint snapshot into empty object maps; returns
        the record count, or None when the snapshot is absent OR corrupt
        (any CRC/parse failure, a record-count mismatch against the
        header, a missing header).  Corruption rolls the maps back to
        empty and counts `snapshot_fallbacks` — the caller falls back to
        replaying the full journal, so a damaged snapshot degrades
        recovery time, never correctness.  Runs from __init__ before the
        store is shared."""
        import json
        import os

        from . import wire

        path = self._snapshot_path
        if path is None or not os.path.exists(path):
            return None
        objects: Dict[str, Dict[str, Any]] = {}
        versions: Dict[str, Dict[str, int]] = {}
        header = None
        n = 0
        max_rv = 0
        try:
            with open(path, "rb") as f:
                for raw in f:
                    line = raw.decode(errors="replace").strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("snapshot record is not an object")
                    crc = rec.pop("crc", None)
                    if not self._record_crc_ok(rec, crc):
                        raise ValueError("snapshot record crc mismatch")
                    if header is None:
                        if "snapshot_rv" not in rec:
                            raise ValueError("snapshot header missing")
                        header = rec
                        continue
                    rv, kind, key = rec["rv"], rec["kind"], rec["key"]
                    obj = wire.from_wire(rec["obj"])
                    objects.setdefault(kind, {})[key] = obj
                    versions.setdefault(kind, {})[key] = rv
                    max_rv = max(max_rv, rv)
                    n += 1
            if header is None or n != header["records"]:
                raise ValueError(
                    f"snapshot truncated: {n} records, header says "
                    f"{header['records'] if header else '?'}"
                )
        except Exception:  # noqa: BLE001 — recovery containment
            self.snapshot_fallbacks += 1
            logging.getLogger(__name__).exception(
                "snapshot %s corrupt; falling back to full journal "
                "replay", path,
            )
            return None
        self._objects = objects
        self._versions = versions
        self._rv = max(int(header["snapshot_rv"]), max_rv)
        self._snapshot_rv = int(header["snapshot_rv"])
        return n

    def checkpoint(self, truncate: bool = True) -> int:
        """Write a point-in-time snapshot of every live object and (by
        default) truncate the journal past the checkpoint rv, bounding
        the next recovery to snapshot + journal suffix.  Crash-safe by
        construction: the snapshot is written to a temp file, flushed,
        fsynced, then atomically renamed over the old one (directory
        fsynced too) — a crash at ANY point leaves the previous snapshot
        or the complete new one; the journal is only truncated AFTER the
        snapshot is durable, so history is never lost to a half-written
        checkpoint.  ``truncate=False`` keeps the journal (full-replay
        oracle mode — the chaos suite's bit-parity check; recovery
        skips journal records the snapshot already covers).  Returns the
        snapshot's record count."""
        with self._lock:
            return self._checkpoint_locked(truncate=truncate)

    def _checkpoint_locked(self, truncate: bool = True) -> int:
        import os

        from . import wire

        path = self._journal_path
        if path is None or self._snapshot_path is None:
            return 0
        faults.fire("store.checkpoint")
        tmp = self._snapshot_path + ".tmp"
        n = sum(len(objs) for objs in self._objects.values())
        with open(tmp, "w") as f:
            f.write(self._encode_record(
                {"snapshot_rv": self._rv, "records": n}
            ))
            for kind, objs in self._objects.items():
                for key, obj in objs.items():
                    f.write(self._encode_record({
                        "op": ADDED,
                        "rv": self._versions[kind][key],
                        "kind": kind,
                        "key": key,
                        "obj": wire.to_wire(obj),
                    }))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        self._fsync_dir(self._snapshot_path)
        self._snapshot_rv = self._rv
        self.snapshot_records = n
        self.checkpoints_total += 1
        self._last_checkpoint = time.monotonic()
        if truncate:
            # everything at or below the snapshot rv is covered by the
            # durable snapshot; the journal restarts empty
            if self._journal is not None:
                try:
                    self._journal.close()
                except (OSError, ValueError):
                    pass
            with open(path, "w") as jf:
                jf.flush()
                os.fsync(jf.fileno())
            self._journal = open(path, "a")
            self._journal_records = 0
        return n

    def _flush_journal(self) -> None:
        # caller holds the lock
        faults.fire("store.journal.fsync")
        self._journal.flush()

    def _journal_commit(self, lines: List[str]) -> None:
        """Write+flush journal lines with failure containment: a torn or
        failed append degrades durability (counted, logged) but never
        fails the already-committed in-memory write — the store keeps
        serving (availability over the fsync ack, unlike etcd's
        fail-stop; replay's CRC path handles whatever landed)."""
        try:
            act = faults.fire("store.journal.append", records=len(lines))
            data = "".join(lines)
            if isinstance(act, faults.TornWrite):
                cut = max(1, int(len(data) * act.frac))
                self._journal.write(data[:cut].rstrip("\n"))
                self._journal.flush()
                raise faults.FaultInjected("torn journal append")
            self._journal.write(data)
            if self._journal_sync == "write":
                self._flush_journal()
            else:
                # group commit: one flush covers a burst of records (a
                # bind wave is thousands back-to-back); the flusher
                # thread bounds the window at _JOURNAL_FLUSH_S
                self._journal_dirty = True
                now = time.monotonic()
                if now - self._journal_flushed_at >= self._JOURNAL_FLUSH_S:
                    self._flush_journal()
                    self._journal_dirty = False
                    self._journal_flushed_at = now
        except Exception:  # noqa: BLE001 — durability degradation, not an API error
            self.journal_write_errors += 1
            logging.getLogger(__name__).exception(
                "journal append failed; continuing with degraded durability"
            )
            return
        self._journal_records += len(lines)
        live = sum(len(objs) for objs in self._objects.values())
        threshold = self._checkpoint_records or max(1024, 8 * max(live, 1))
        due = (
            self._checkpoint_interval > 0
            and time.monotonic() - self._last_checkpoint
            >= self._checkpoint_interval
        )
        if self._journal_records > threshold or due:
            try:
                self._checkpoint_locked()
            except Exception:  # noqa: BLE001
                self.journal_write_errors += 1
                logging.getLogger(__name__).exception(
                    "checkpoint failed; reopening journal for append"
                )
                if self._journal is None or self._journal.closed:
                    self._journal = open(self._journal_path, "a")

    def _append_journal(self, op: str, kind: str, key: str, obj, rv: int) -> None:
        # caller holds the lock; called after the in-memory commit
        if self._journal is None:
            return
        from . import wire

        rec = {"op": op, "rv": rv, "kind": kind, "key": key}
        if op != DELETED:
            rec["obj"] = wire.to_wire(obj)
        self._journal_commit([self._encode_record(rec)])

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _meta(obj: Any) -> api.ObjectMeta:
        return obj.meta

    def _kind_of(self, obj: Any) -> str:
        kind = getattr(obj, "KIND", None)
        if not kind:
            raise TypeError(f"object {obj!r} has no KIND")
        return kind

    def _dispatch(self, ev: Event) -> None:
        # caller holds the lock: ring append + backlog handoff only —
        # the fan-out itself runs on the dispatch thread off the lock
        self._buffer.append(ev)
        if len(self._buffer) > self._buffer_size:
            del self._buffer[: self._buffer_size // 4]
        self._queue_fanout_locked(ev.kind, [ev])

    def _queue_fanout_locked(self, kind: str, events: List[Event]) -> None:
        # caller holds the lock.  No watchers for the kind means no
        # delivery obligation: a watcher registered later replays from
        # the ring (watch(from_rv)) or starts from-now with _last_rv
        # pinned to the current rv, so skipping the backlog is exact.
        if not self._watchers.get(kind):
            return
        self._ensure_dispatcher_locked()
        with self._dispatch_cv:
            self._dispatch_backlog.append((kind, events))
            self._dispatch_cv.notify_all()

    def _ensure_dispatcher_locked(self) -> None:
        # caller holds the lock.  Lazy + self-healing: the thread starts
        # with the first watcher and is restarted here if an injected
        # crash killed it (every dispatch passes through this check).
        t = self._dispatch_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(
            target=_watch_dispatch_loop,
            args=(weakref.ref(self),),
            name="watch-dispatch",
            daemon=True,
        )
        self._dispatch_thread = t
        t.start()

    def _fan_out(self, kind: str, events: List[Event]) -> None:
        """Deliver one committed batch to every watcher of `kind` — the
        dispatch thread's half of the watch path, running OFF the store
        lock so per-watcher coalescing work never blocks writers."""
        with self._lock:
            watchers = list(self._watchers.get(kind, ()))
        expired: List[Watch] = []
        for w in watchers:
            for ev in events:
                verdict = w._offer(ev)
                if verdict is OFFER_EXPIRED:
                    expired.append(w)
                    break
                if verdict is OFFER_STOPPED:
                    break  # _drop_watch unregisters it; skip the rest
        for w in expired:
            self._retire_expired_watch(w, kind)

    def _retire_expired_watch(self, w: Watch, kind: str) -> None:
        with self._lock:
            ws = self._watchers.get(kind)
            if ws is not None and w in ws:
                ws.remove(w)
            self.watch_expired_total += 1
            with w._mu:  # Store._lock -> Watch._mu (same order as replay)
                self._watch_coalesced_closed += w.coalesced
                w.coalesced = 0

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            admitted = False
            if self._admission is not None:
                # admit a server-side COPY: mutators must never edit the
                # caller's object (a rejected or conflicting write would
                # leave the caller's template silently modified — every other
                # store path deep-copies for exactly this isolation).
                # Admission runs UNDER the store lock: store-reading
                # plugins (quota validator, ClusterIP allocation) are
                # check-then-act otherwise — two concurrent creates could
                # both pass quota or allocate the same ClusterIP.  The
                # reference enforces these inside a storage transaction;
                # the lock is reentrant, so plugin reads are fine.
                obj = self._admission.admit(copy.deepcopy(obj), "CREATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                # resource scope normalization: cluster-scoped objects live
                # at namespace "" regardless of what the caller set (the
                # apiserver rejects these; normalizing keeps every
                # convenience-default caller working)
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            objs = self._objects.setdefault(kind, {})
            if key in objs:
                raise AlreadyExists(f"{kind} {key} exists")
            self._rv += 1
            if not admitted:  # the admitted copy is already unaliased
                obj = copy.deepcopy(obj)
            obj.meta.resource_version = self._rv
            if not obj.meta.creation_timestamp:
                obj.meta.creation_timestamp = time.time()
            objs[key] = obj
            self._versions.setdefault(kind, {})[key] = self._rv
            self._append_journal(ADDED, kind, key, obj, self._rv)
            self._dispatch(Event(ADDED, kind, copy.deepcopy(obj), self._rv))
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        with self._lock:
            try:
                return copy.deepcopy(self._objects[kind][key])
            except KeyError:
                raise NotFound(f"{kind} {key}") from None

    def update(
        self, obj: Any, *, force: bool = False, copy_result: bool = True
    ) -> Any:
        """Optimistic-concurrency update: obj.meta.resource_version must
        match the stored version unless force (the GuaranteedUpdate retry
        loop's compare step).  copy_result=False skips the defensive
        deep copy of the return value for hot-path callers that discard
        it (the scheduler's bind wave) — the returned object is then the
        STORED one and must not be mutated."""
        with self._lock:
            admitted = False
            if self._admission is not None:
                # under the lock for the same check-then-act reason as
                # create(): store-reading validators must see a state no
                # concurrent write can invalidate before the commit
                obj = self._admission.admit(copy.deepcopy(obj), "UPDATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            objs = self._objects.get(kind, {})
            if key not in objs:
                raise NotFound(f"{kind} {key}")
            current_rv = self._versions[kind][key]
            if not force and meta.resource_version != current_rv:
                raise Conflict(
                    f"{kind} {key}: rv {meta.resource_version} != {current_rv}"
                )
            self._rv += 1
            if not admitted:
                obj = copy.deepcopy(obj)
            obj.meta.resource_version = self._rv
            if (
                obj.meta.deletion_timestamp is not None
                and not obj.meta.finalizers
            ):
                # last finalizer dropped on a deleting object: the update
                # completes the two-phase delete (store.go:1176)
                objs.pop(key)
                self._versions[kind].pop(key)
                self._append_journal(DELETED, kind, key, None, self._rv)
                self._dispatch(
                    Event(DELETED, kind, copy.deepcopy(obj), self._rv)
                )
                return obj
            objs[key] = obj
            self._versions[kind][key] = self._rv
            self._append_journal(MODIFIED, kind, key, obj, self._rv)
            self._dispatch(Event(MODIFIED, kind, copy.deepcopy(obj), self._rv))
            return copy.deepcopy(obj) if copy_result else obj

    def update_wave(
        self,
        kind: str,
        updates: List[Tuple[str, str, Callable[[Any], None]]],
        *,
        admit: bool = True,
        fence: Optional[FenceToken] = None,
    ) -> Tuple[List[str], Dict[str, Exception]]:
        """Commit a wave of read-modify-write updates as ONE transaction.

        `updates` is a list of (name, namespace, mutate) where mutate(obj)
        edits a private copy of the stored object in place.  The whole
        wave runs under one lock acquisition with ONE coalesced journal
        append (a single write + flush for every record) and ONE watch
        fan-out pass — the scheduler's bind wave pays per-pod costs only
        for the copy and the mutation, not for lock/journal/dispatch.

        Failure splits per object, never per wave: a missing object, a
        mutate() exception, or an admission rejection lands in the
        returned error map under its "namespace/name" key and the rest of
        the wave commits.  Returns (applied_keys, errors).

        Each committed object still gets its own resourceVersion and its
        own watch Event, so watch/informer semantics are byte-identical
        to per-object update(); only the write-path overhead is shared.
        The dispatched Event aliases the stored object (no defensive
        copy): stored objects are never mutated in place after commit and
        watch consumers already share one Event payload across every
        watcher, so the alias adds no new mutability hazard — it removes
        the single biggest per-pod cost of a 1k-pod bind wave.

        `fence` (a FenceToken) makes the wave a LEADERSHIP-CONDITIONAL
        transaction: under the store lock, the named Lease must still be
        held by the token's identity at the token's acquisition
        generation, or the whole wave is rejected with `Fenced` (counted
        in `fenced_writes_total`) — a deposed leader's late bind wave
        can never double-bind behind its successor's back (the etcd
        lease-ownership txn compare)."""
        faults.fire("store.update_wave", kind=kind, updates=len(updates))
        applied: List[str] = []
        errors: Dict[str, Exception] = {}
        events: List[Event] = []
        records: List[Tuple[str, str, Any, int]] = []
        with self._lock:
            if fence is not None:
                lease = self._objects.get("Lease", {}).get(
                    _key(fence.namespace, fence.name)
                )
                spec = getattr(lease, "spec", None)
                if (
                    spec is None
                    or spec.holder_identity != fence.identity
                    or (
                        fence.generation is not None
                        and spec.lease_transitions != fence.generation
                    )
                ):
                    self.fenced_writes_total += 1
                    holder = getattr(spec, "holder_identity", None)
                    raise Fenced(
                        f"wave fenced: lease {fence.namespace}/"
                        f"{fence.name} held by {holder!r}, caller "
                        f"{fence.identity!r} gen {fence.generation}"
                    )
            objs = self._objects.get(kind, {})
            vers = self._versions.setdefault(kind, {})
            for name, namespace, mutate in updates:
                if kind in api.CLUSTER_SCOPED_KINDS:
                    namespace = ""
                key = _key(namespace, name)
                cur = objs.get(key)
                if cur is None:
                    errors[key] = NotFound(f"{kind} {key}")
                    continue
                obj = copy.deepcopy(cur)
                try:
                    mutate(obj)
                    if admit and self._admission is not None:
                        obj = self._admission.admit(obj, "UPDATE")
                except Exception as e:  # noqa: BLE001 — per-object split
                    errors[key] = e
                    continue
                self._rv += 1
                obj.meta.resource_version = self._rv
                if (
                    obj.meta.deletion_timestamp is not None
                    and not obj.meta.finalizers
                ):
                    # mirror update(): dropping the last finalizer on a
                    # deleting object completes the two-phase delete
                    objs.pop(key)
                    vers.pop(key, None)
                    records.append((DELETED, key, None, self._rv))
                    events.append(Event(DELETED, kind, obj, self._rv))
                else:
                    objs[key] = obj
                    vers[key] = self._rv
                    records.append((MODIFIED, key, obj, self._rv))
                    events.append(Event(MODIFIED, kind, obj, self._rv))
                applied.append(key)
            if records:
                self._append_journal_wave(kind, records)
                self._dispatch_wave(kind, events)
        return applied, errors

    def _append_journal_wave(
        self, kind: str, records: List[Tuple[str, str, Any, int]]
    ) -> None:
        # caller holds the lock; one write + one flush for the wave.
        # Every record carries the wave id ("w") and the last one the
        # terminator ("wz"): replay applies the wave atomically — a tail
        # torn anywhere inside it drops the WHOLE wave, so a recovered
        # store never holds half a bind wave.
        if self._journal is None:
            return
        from . import wire

        self._wave_seq += 1
        wid = self._wave_seq
        lines = []
        for i, (op, key, obj, rv) in enumerate(records):
            rec = {"op": op, "rv": rv, "kind": kind, "key": key, "w": wid}
            if i == len(records) - 1:
                rec["wz"] = 1
            if op != DELETED:
                rec["obj"] = wire.to_wire(obj)
            lines.append(self._encode_record(rec))
        self._journal_commit(lines)

    def _dispatch_wave(self, kind: str, events: List[Event]) -> None:
        # caller holds the lock; one buffer extend + ONE backlog handoff
        # for the whole wave (the fan-out thread delivers it as a batch)
        self._buffer.extend(events)
        excess = len(self._buffer) - self._buffer_size
        if excess > 0:
            del self._buffer[: excess + self._buffer_size // 4]
        self._queue_fanout_locked(kind, events)

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        """Remove an object.  Objects carrying finalizers get the
        reference's two-phase deletion (registry/generic/registry/
        store.go:1116): deletionTimestamp is set and a MODIFIED event
        fires; the real removal happens when the last finalizer is
        dropped via update() — the node agent's graceful pod shutdown
        and any future finalizing controller ride this."""
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        with self._lock:
            objs = self._objects.get(kind, {})
            if key not in objs:
                raise NotFound(f"{kind} {key}")
            obj = objs[key]
            if obj.meta.finalizers and obj.meta.deletion_timestamp is not None:
                # already terminating: delete-on-deleting is a no-op
                # (finalizers still gate the removal; a GC re-delete must
                # not hard-remove mid-grace)
                return copy.deepcopy(obj)
            if obj.meta.finalizers and obj.meta.deletion_timestamp is None:
                obj = copy.deepcopy(obj)
                obj.meta.deletion_timestamp = time.time()
                self._rv += 1
                obj.meta.resource_version = self._rv
                objs[key] = obj
                self._versions[kind][key] = self._rv
                self._append_journal(MODIFIED, kind, key, obj, self._rv)
                self._dispatch(
                    Event(MODIFIED, kind, copy.deepcopy(obj), self._rv)
                )
                return copy.deepcopy(obj)
            objs.pop(key)
            self._versions[kind].pop(key)
            self._rv += 1
            self._append_journal(DELETED, kind, key, None, self._rv)
            self._dispatch(Event(DELETED, kind, copy.deepcopy(obj), self._rv))
            return obj

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Callable[[Any], bool]] = None,
    ) -> Tuple[List[Any], int]:
        """(items, resource_version) — the ListAndWatch handoff point."""
        if faults._registry is not None:
            # relist-storm chaos: injected list latency models a control
            # plane whose snapshot path is the contended resource
            faults.fire("store.list", kind=kind)
        with self._lock:
            items = [
                copy.deepcopy(o)
                for o in self._objects.get(kind, {}).values()
                if (namespace is None or o.meta.namespace == namespace)
                and (selector is None or selector(o))
            ]
            return items, self._rv

    def kinds(self) -> List[str]:
        """Object kinds the store currently holds (the GC/namespace
        controllers sweep every kind, like the reference's
        RESTMapper-driven resource discovery)."""
        with self._lock:
            return [k for k, objs in self._objects.items() if objs]

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, from_rv: Optional[int] = None) -> Watch:
        """Stream events for `kind` after `from_rv` (exclusive).  None
        means 'from now'.  Raises Expired when from_rv predates the event
        buffer — relist and retry (reflector.go 410 handling)."""
        with self._lock:
            w = Watch(self, self._watch_capacity)
            if from_rv is not None:
                oldest_known = self._buffer[0].rv if self._buffer else self._rv + 1
                if from_rv + 1 < oldest_known and from_rv < self._rv:
                    raise Expired(
                        f"rv {from_rv} too old (buffer starts at {oldest_known})"
                    )
                for ev in self._buffer:
                    if ev.kind == kind and ev.rv > from_rv:
                        if w._offer(ev) is not OFFER_OK:
                            # the replay itself overflowed the coalescing
                            # buffer (or was fault-dropped): this stream
                            # would be lossy FROM BIRTH — refuse it; the
                            # client relists (410 path)
                            self.watch_expired_total += 1
                            raise Expired(
                                f"rv {from_rv} replay overflowed the "
                                "watch buffer; relist"
                            )
            with w._mu:
                # pin the dedup horizon to the commit the registration
                # is consistent with: backlog stragglers at or below it
                # were covered by the replay (or predate a from-now
                # watch) and must not be re-delivered
                w._last_rv = max(w._last_rv, self._rv)
            self._watchers.setdefault(kind, []).append(w)
            self._ensure_dispatcher_locked()
            return w

    def _drop_watch(self, w: Watch) -> None:
        with self._lock:
            for ws in self._watchers.values():
                if w in ws:
                    ws.remove(w)
                    break
            with w._mu:
                self._watch_coalesced_closed += w.coalesced
                w.coalesced = 0

    def watch_stats(self) -> Dict[str, int]:
        """Fan-out observability snapshot: deepest per-watcher pending
        backlog, total compacted events, expiries, and (legacy)
        destructive terminations — mirrored into the scheduler Registry
        as scheduler_watch_* gauges every cycle."""
        with self._lock:
            depth = 0
            coalesced = self._watch_coalesced_closed
            for ws in self._watchers.values():
                for w in ws:
                    with w._mu:
                        depth = max(depth, len(w._pending))
                        coalesced += w.coalesced
            return {
                "watch_queue_depth": depth,
                "watch_coalesced_total": coalesced,
                "watch_expired_total": self.watch_expired_total,
                "watchers_terminated": self.watchers_terminated,
            }

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Graceful shutdown: drain the watch-dispatch backlog (pending
        committed batches reach their watchers), then flush AND fsync
        the journal before returning — under ``journal_sync="interval"``
        the final dirty group-commit batch would otherwise sit in the
        userspace buffer and die with the process.  The store stops
        journaling afterwards; reads keep working (tests inspect closed
        stores)."""
        import os

        deadline = time.monotonic() + timeout
        with self._dispatch_cv:
            while (
                (self._dispatch_backlog or self._dispatch_inflight)
                and time.monotonic() < deadline
            ):
                self._dispatch_cv.wait(0.05)
        with self._lock:
            j, self._journal = self._journal, None
            self._journal_dirty = False
        if j is not None:
            try:
                j.flush()
                os.fsync(j.fileno())
                j.close()
            except (OSError, ValueError):
                logging.getLogger(__name__).exception(
                    "journal close flush failed; tail durability degraded"
                )

    def state_fingerprint(self) -> Dict[str, Any]:
        """A stable, comparison-friendly serialization of the full
        committed state: store rv plus (kind, key) -> (rv, wire(obj)).
        Two stores with equal fingerprints hold bit-identical state —
        the chaos suite compares snapshot+suffix recovery against a
        full-replay oracle with this."""
        from . import wire

        with self._lock:
            return {
                "rv": self._rv,
                "objects": {
                    kind: {
                        key: (self._versions[kind][key], wire.to_wire(obj))
                        for key, obj in sorted(objs.items())
                    }
                    for kind, objs in sorted(self._objects.items())
                    if objs
                },
            }

    # -- convenience -------------------------------------------------------

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv


def _watch_dispatch_loop(store_ref: "weakref.ref[Store]") -> None:
    """The fan-out worker: drains the store's dispatch backlog and
    delivers each committed batch to its watchers off the store lock.

    Holds the store only through a weakref between iterations, so an
    abandoned store's dispatcher exits instead of leaking one polling
    thread per Store (tests construct thousands).  Fault-schedule
    exceptions escaping a delivery are contained — a poisoned offer must
    not take the whole fan-out path down (and _ensure_dispatcher_locked
    restarts the thread if something interpreter-grade does)."""
    while True:
        store = store_ref()
        if store is None:
            return
        batch = None
        with store._dispatch_cv:
            if not store._dispatch_backlog:
                store._dispatch_cv.wait(0.2)
            if store._dispatch_backlog:
                batch = store._dispatch_backlog.popleft()
                # close() waits for backlog-empty AND not-inflight, so a
                # batch mid-fan-out still blocks a graceful shutdown
                store._dispatch_inflight = True
        if batch is not None:
            try:
                store._fan_out(*batch)
            except Exception:  # noqa: BLE001 — delivery containment
                logging.getLogger(__name__).exception(
                    "watch fan-out batch failed; continuing"
                )
            finally:
                with store._dispatch_cv:
                    store._dispatch_inflight = False
                    store._dispatch_cv.notify_all()
        # drop the strong reference before sleeping so GC can collect
        # an otherwise-abandoned store
        store = None
        batch = None
