"""In-memory versioned object store with watch streams — SHARDED.

The control-plane data path of the reference collapses into one process:
etcd revisions + the apiserver's generic registry + the watch cache
(storage/etcd3/store.go:106, registry/generic/registry/store.go:414,
storage/cacher/cacher.go:337-514) become a single store with a monotonic
resourceVersion, per-kind keyspaces, and fan-out watch channels serving
events from a bounded ring buffer.

Semantics kept from the reference:
  * every successful write bumps one global resourceVersion (etcd
    revision semantics: one counter across kinds);
  * optimistic concurrency: update with a stale resource_version fails
    with Conflict (GuaranteedUpdate's retry trigger);
  * list returns (items, rv) so a watch can resume from that rv
    (reflector's ListAndWatch contract, reflector.go:340) — the item
    set is a POINT-IN-TIME-CONSISTENT cut across every shard (taken
    under the publish lock; sub-waves are all-or-nothing in it);
  * watch(from_rv) replays buffered events after from_rv, then streams;
    a from_rv older than the buffer raises Expired — the client relists
    (the 410 Gone path).

Sharding (the etcd-concurrent-MVCC analogue): objects hash by
``(kind, namespace)`` into N ``_StoreShard``s, each owning its own
lock, object maps, journal + checkpoint snapshot (PR 8 semantics per
shard: CRC'd snapshot + wave-atomic journal-suffix replay), and
watch-dispatch backlog + fan-out thread.  Writes take only their
shard's lock for the expensive work (deep copies, mutation, admission,
wire encode, journal fsync); resourceVersion allocation and the
in-memory publish (map update + ring append + backlog handoff) happen
under ONE small global ``_rv_lock`` so rvs stay globally monotonic, the
event ring stays globally rv-ordered, and ``watch(from_rv)`` replay is
unchanged.  ``update_wave`` is a PER-SHARD transaction: a wave spanning
shards commits as one atomic sub-wave per shard (each journaled with
its own wave id, each fence-checked at publish), which is what lets the
scheduler's binder commit sub-waves concurrently and overlap store
fan-out with the next solve.

Lock order (fixed; the graftlint runtime tracker enforces it):
``_admission_lock`` (admission-armed writers only) -> ``shard._lock``
-> ``Store._rv_lock`` -> ``shard._dispatch_cv`` / ``Watch._mu``.
Shard locks are never nested with each other.

Threading: writes hold their shard lock and only append the committed
events to that shard's dispatch backlog (under the publish lock); each
shard's dedicated fan-out thread delivers them to per-watcher bounded
COALESCING buffers off every lock, so a slow consumer can never stall
writers.  A watcher that falls behind has its MODIFIED runs compacted
latest-wins and its ADDED+DELETED pairs annihilated; only when the
coalesced backlog itself overflows (more *distinct objects* pending
than the capacity) is the watcher marked Expired — bookmark rv + forced
relist, the 410 path — never silently terminated (the
survivable-overload replacement for the cacher's
terminate-blocked-watcher behaviour; see docs/robustness.md).

Delivery ordering with N fan-out threads: per OBJECT (and per shard)
delivery is strictly rv-monotonic — an object lives on exactly one
shard and one thread drains that shard's backlog in commit order.
Events of one kind that span namespaces on different shards may
interleave across shards while both fan-outs are in flight; cache-
diffing consumers (SharedInformer, the poll-style agents) are per-key
and relists resume from the list rv, so no consumer observes the skew.
A single-shard stream (one kind, one namespace — every existing
consumer) is totally ordered exactly as before.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
import weakref
import zlib
from collections import OrderedDict, deque
from contextlib import nullcontext
from dataclasses import dataclass
from typing import (
    Any, Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple,
)

from ..analysis import ledger as _ledger
from ..testing import faults
from . import framing
from . import types as api

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"

# default shard count for new stores: enough to split the hot kinds
# (Pod traffic per namespace, Node heartbeats, Lease renewals) onto
# independent locks/journals without paying thread overhead — shard
# fan-out threads start lazily, so small test stores stay cheap
DEFAULT_SHARDS = 4


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """Stale resourceVersion on update/delete."""


class Expired(ValueError):
    """Watch start revision fell out of the event buffer (410 Gone)."""


class Fenced(ValueError):
    """A fenced write's leadership lease is stale: the caller was
    deposed between staging the wave and committing it.  The etcd
    analogue is a txn whose lease-ownership compare fails — the late
    wave of a dead leader must never double-bind."""


class FenceToken(NamedTuple):
    """Leadership proof threaded into ``Store.update_wave``: the wave
    commits only while `identity` still holds the named Lease at the
    same acquisition `generation` (lease_transitions when the caller
    acquired).  Minted by ``LeaderElector.fence_token()``.  With the
    sharded store the check runs per SUB-wave, under the publish lock,
    atomically with that sub-wave's commit."""

    name: str
    namespace: str
    identity: str
    generation: Optional[int] = None


@dataclass
class Event:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any           # committed object (immutable after publish)
    rv: int


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}" if namespace else name


def _shard_hash(kind: str, namespace: str) -> int:
    """Stable (kind, namespace) hash — crc32 so the shard map survives
    process restarts and interpreter hash randomization (recovery must
    route every journaled object back to the shard that owns it)."""
    return zlib.crc32(f"{kind}\x00{namespace}".encode())


# Watch._offer verdicts (read by the fan-out threads)
OFFER_OK = "ok"
OFFER_STOPPED = "stopped"
OFFER_EXPIRED = "expired"


class Watch:
    """One watch stream backed by a bounded per-watcher COALESCING
    buffer: iterate to receive events; stop() to cancel.

    Backpressure semantics (the survivable-overload contract):

      * events for DISTINCT objects queue in rv order;
      * a MODIFIED landing on a pending entry replaces it latest-wins
        (an un-consumed ADDED stays ADDED with the newest object — the
        consumer never saw the original);
      * a DELETED landing on a pending ADDED annihilates both (the
        consumer never learns the object existed);
      * a DELETED landing on a pending MODIFIED collapses to DELETED;
      * an ADDED landing on a pending DELETED (delete + recreate while
        the consumer lagged) collapses to MODIFIED with the new object —
        cache-diffing consumers (SharedInformer) synthesize the right
        local transition either way;
      * compaction always keeps the LATEST rv and re-sorts the entry to
        the back, so delivery stays strictly rv-monotonic per shard
        (and totally ordered for single-shard streams).

    With the sharded store, offers arrive from one fan-out thread per
    shard; the exactly-once dedup horizon is therefore PER SHARD
    (``_horizons``): each shard's offers are ascending in rv, so "at or
    below the shard's horizon" still means "already replayed at
    registration or already delivered".  ``_last_rv`` keeps the max
    across shards for observability and the expiry bookmark.

    Only when the number of distinct pending objects would exceed the
    capacity is the stream EXPIRED: pending events are dropped, the
    bookmark rv recorded, and iteration raises `Expired` so the consumer
    relists (the 410 path).  `stopped` is also set so poll-style
    consumers (agent, kubemark, the HTTP server) fall into their
    existing relist branch.  Consumer-initiated stop() ends iteration
    with StopIteration instead.
    """

    GUARDED_FIELDS = {
        "_pending": "_mu",
        "_last_rv": "_mu",
        "_horizons": "_mu",
        "stopped": "_mu",
        "expired": "_mu",
        "expired_rv": "_mu",
        "coalesced": "_mu",
    }

    def __init__(self, store: "Store", capacity: int):
        self._store = store
        self._capacity = capacity
        self._mu = threading.Condition()
        # object key -> coalesced Event, insertion/compaction order ==
        # ascending rv per shard (every insert/replace carries the
        # shard's current max rv and moves to the back)
        self._pending: "OrderedDict[str, Event]" = OrderedDict()
        # per-shard dedup horizon: highest rv this shard has delivered
        # into (or compacted through) this buffer — the fan-out threads'
        # offers dedup against it, which makes the replay-at-registration
        # + async-backlog seam exactly-once per shard
        self._horizons: List[int] = [0] * store.shard_count
        # max horizon across shards (observability + expiry bookmark)
        self._last_rv = 0
        self.stopped = False
        self.expired = False
        self.expired_rv = 0     # bookmark: last consistent rv at expiry
        self.coalesced = 0      # events compacted away in this buffer

    def stop(self) -> None:
        self._store._drop_watch(self)
        with self._mu:
            self.stopped = True
            self._mu.notify_all()

    def _pin_locked(self, rv: int) -> None:
        # registration pin (caller holds _mu): the dedup horizon of
        # EVERY shard moves to the commit the registration is consistent
        # with — backlog stragglers at or below it were covered by the
        # ring replay (or predate a from-now watch)
        for i, h in enumerate(self._horizons):
            if rv > h:
                self._horizons[i] = rv
        if rv > self._last_rv:
            self._last_rv = rv

    def _offer(self, ev: Event) -> str:
        # hot path (per event per watcher): the disarmed check is one
        # module-attribute load, not a function call
        if faults._registry is not None and faults.fire("watch.offer") == faults.DROP:
            # injected overload: as if coalescing itself overflowed —
            # the watcher expires and its consumer relists
            with self._mu:
                self._expire_locked()
            return OFFER_EXPIRED
        sid = self._store._hash_index(ev.kind, ev.obj.meta.namespace)
        with self._mu:
            if self.expired:
                return OFFER_EXPIRED
            if self.stopped:
                return OFFER_STOPPED
            if ev.rv <= self._horizons[sid]:
                # already replayed at registration (or re-offered by the
                # shard backlog after a replay covered it): exactly-once
                # dedup — per shard, because each shard's offers arrive
                # in its own ascending commit order
                return OFFER_OK
            key = _key(ev.obj.meta.namespace, ev.obj.meta.name)
            cur = self._pending.get(key)
            if cur is None:
                if len(self._pending) >= self._capacity:
                    self._expire_locked()
                    return OFFER_EXPIRED
                self._pending[key] = ev
            elif cur.type == ADDED and ev.type == DELETED:
                # annihilation: the consumer never saw the object
                del self._pending[key]
                self.coalesced += 2
            else:
                typ = ev.type
                if cur.type == ADDED and ev.type == MODIFIED:
                    typ = ADDED          # still unseen: stays a create
                elif cur.type == DELETED and ev.type == ADDED:
                    typ = MODIFIED       # delete+recreate: latest-wins
                self._pending[key] = Event(typ, ev.kind, ev.obj, ev.rv)
                self._pending.move_to_end(key)
                self.coalesced += 1
            self._horizons[sid] = ev.rv
            if ev.rv > self._last_rv:
                self._last_rv = ev.rv
            self._mu.notify_all()
            return OFFER_OK

    def _offer_batch(self, events: List["Event"]) -> str:
        """Deliver a committed chunk under ONE ``_mu`` acquisition — the
        fan-out thread's batched half of the watch path.  Per-event
        semantics (fault point, per-shard horizon dedup, coalescing
        rules, capacity expiry) are identical to ``_offer``; only the
        locking is chunked: one acquire + one notify per chunk instead
        of per event."""
        armed = faults._registry is not None
        store = self._store
        with self._mu:
            for ev in events:
                if armed and faults.fire("watch.offer") == faults.DROP:
                    # injected overload: as if coalescing overflowed
                    self._expire_locked()
                    return OFFER_EXPIRED
                if self.expired:
                    return OFFER_EXPIRED
                if self.stopped:
                    return OFFER_STOPPED
                sid = store._hash_index(ev.kind, ev.obj.meta.namespace)
                if ev.rv <= self._horizons[sid]:
                    continue  # exactly-once dedup (see _offer)
                key = _key(ev.obj.meta.namespace, ev.obj.meta.name)
                cur = self._pending.get(key)
                if cur is None:
                    if len(self._pending) >= self._capacity:
                        self._expire_locked()
                        return OFFER_EXPIRED
                    self._pending[key] = ev
                elif cur.type == ADDED and ev.type == DELETED:
                    del self._pending[key]
                    self.coalesced += 2
                else:
                    typ = ev.type
                    if cur.type == ADDED and ev.type == MODIFIED:
                        typ = ADDED          # still unseen: stays a create
                    elif cur.type == DELETED and ev.type == ADDED:
                        typ = MODIFIED       # delete+recreate: latest-wins
                    self._pending[key] = Event(typ, ev.kind, ev.obj, ev.rv)
                    self._pending.move_to_end(key)
                    self.coalesced += 1
                self._horizons[sid] = ev.rv
                if ev.rv > self._last_rv:
                    self._last_rv = ev.rv
            self._mu.notify_all()
            return OFFER_OK

    def _expire_locked(self) -> None:
        if self.expired:
            return
        self.expired = True
        self.stopped = True  # poll-style consumers relist off .stopped
        self.expired_rv = self._last_rv
        # pending events are dropped: the forced relist recovers them
        # (and everything after) from one consistent snapshot
        self._pending.clear()
        self._mu.notify_all()

    def depth(self) -> int:
        with self._mu:
            return len(self._pending)

    def __iter__(self) -> Iterator[Event]:
        return self

    def __next__(self) -> Event:
        if faults._registry is not None:
            faults.fire("watch.consume")  # injected slow consumer
        with self._mu:
            while True:
                if self._pending:
                    _, ev = self._pending.popitem(last=False)
                    return ev
                if self.expired:
                    raise Expired(
                        f"watch expired at rv {self.expired_rv}; relist"
                    )
                if self.stopped:
                    raise StopIteration
                # bounded wait: a missed notify can never park the
                # consumer forever
                self._mu.wait(0.5)

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """One event, or None on timeout / stream end (expiry included —
        check `.expired` / `.stopped` to distinguish and relist)."""
        if faults._registry is not None:
            faults.fire("watch.consume")  # injected slow consumer
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._mu:
            while True:
                if self._pending:
                    _, ev = self._pending.popitem(last=False)
                    return ev
                if self.stopped or self.expired:
                    return None
                if deadline is None:
                    self._mu.wait(0.5)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._mu.wait(remaining)


# -- journal record codec (shared by every shard) ---------------------------


def _encode_record(rec: dict) -> str:
    """One journal line: the record JSON with a trailing crc32 over
    the crc-less serialization.  Replay re-serializes the parsed
    record (key order and value round-trips are stable under
    json.dumps) and compares — a partial page write or bit flip
    anywhere in the line fails the check even when the damage still
    parses as JSON."""
    import json

    s = json.dumps(rec)
    return '%s, "crc": %d}\n' % (s[:-1], zlib.crc32(s.encode()))


def _record_crc_ok(rec: dict, crc) -> bool:
    import json

    if crc is None:
        return True  # pre-CRC journal line: accept (upgrade path)
    return zlib.crc32(json.dumps(rec).encode()) == crc


def _fsync_dir(path: str) -> None:
    """fsync the directory holding `path` so a rename into it is
    itself durable."""
    import os

    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without directory fsync


class _StoreShard:
    """One shard of the store: its own lock, object maps, journal +
    checkpoint snapshot, and watch-dispatch backlog/thread.

    The shard owns every EXPENSIVE half of the write path — deep
    copies, mutation, admission output, wire encode, journal append +
    fsync, checkpoint I/O — so shards commit concurrently; only the
    tiny publish step (rv allocation + map update + ring/backlog
    append) serializes through the facade's ``_rv_lock``.  Recovery is
    per shard: load the shard's CRC'd snapshot, replay its journal
    suffix with PR 8 wave atomicity (a torn final wave is dropped
    whole), exactly the single-store contract scaled down to one
    shard's keyspace.
    """

    # graftlint guarded-by declarations: object maps and all journal /
    # checkpoint state share the shard mutex; the fan-out backlog has
    # its own condition (publishers append under Store._rv_lock ->
    # _dispatch_cv, the dispatcher pops under _dispatch_cv alone — one
    # lock-order direction, never a cycle)
    GUARDED_FIELDS = {
        "_objects": "_lock",
        "_versions": "_lock",
        "_last_rv": "_lock",
        "_journal": "_lock",
        "_journal_records": "_lock",
        "_journal_dirty": "_lock",
        "_journal_flushed_at": "_lock",
        "_snapshot_rv": "_lock",
        "_wave_seq": "_lock",
        "_last_checkpoint": "_lock",
        "checkpoints_total": "_lock",
        "snapshot_fallbacks": "_lock",
        "snapshot_records": "_lock",
        "journal_suffix_records": "_lock",
        "journal_recovered_records": "_lock",
        "journal_tail_truncations": "_lock",
        "journal_write_errors": "_lock",
        "journal_torn_waves": "_lock",
        "journal_frames": "_lock",
        "journal_frame_bytes": "_lock",
        "_dispatch_backlog": "_dispatch_cv",
        "_dispatch_inflight": "_dispatch_cv",
        "_dispatch_thread": "_dispatch_cv",
    }
    # reviewed lock-free: recovery runs from Store.__init__ before the
    # store is shared; the rest document "caller holds the shard lock"
    LOCKED_METHODS = frozenset({
        "_recover",
        "_replay_journal",
        "_load_snapshot",
        "_open_journal",
        "_flush_journal",
        "_journal_commit",
        "_append_journal",
        "_append_journal_wave",
    })

    def __init__(
        self,
        index: int,
        journal_path: Optional[str],
        snapshot_path: Optional[str],
        journal_sync: str,
        checkpoint_records: Optional[int],
        checkpoint_interval_seconds: float,
        journal_framing: bool = True,
    ):
        self.index = index
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, Any]] = {}   # kind -> key -> obj
        self._versions: Dict[str, Dict[str, int]] = {}  # kind -> key -> rv
        # highest rv this shard has committed (snapshot header rv; the
        # facade's recovered _rv is the max across shards)
        self._last_rv = 0
        # fan-out backlog: publishers append committed event batches
        # under the publish lock; this shard's dispatch thread (started
        # lazily with the first delivery, weakly referenced so abandoned
        # stores don't leak pollers) delivers them to the coalescing
        # buffers OFF every lock
        self._dispatch_cv = threading.Condition()
        self._dispatch_backlog: deque = deque()
        self._dispatch_inflight = False
        self._dispatch_thread: Optional[threading.Thread] = None
        self._journal = None
        self._journal_path = journal_path
        self._journal_sync = journal_sync
        self._journal_records = 0
        self._journal_dirty = False
        self._journal_flushed_at = time.monotonic()
        # journal health/recovery counters (the facade sums them across
        # shards; surfaced as scheduler_journal_recovered_records etc.):
        #   recovered — corrupt records replay survived;
        #   tail truncations — torn final appends cut back;
        #   write errors — appends/flushes contained (durability
        #       degraded, store keeps serving).
        self.journal_recovered_records = 0
        self.journal_tail_truncations = 0
        self.journal_write_errors = 0
        self.journal_torn_waves = 0
        # sub-wave frame mode (api/framing.py): one line + one CRC pass
        # per commit sub-wave; off reproduces the legacy per-line wave
        # format (which replay accepts forever — upgrade path)
        self._journal_framing = journal_framing
        self.journal_frames = 0
        self.journal_frame_bytes = 0
        # checkpoint / recovery state (docs/robustness.md recovery
        # contract): the snapshot sits next to the shard's journal;
        # recovery loads it and replays only the journal suffix past
        # its rv.
        self._snapshot_path = snapshot_path
        self._snapshot_rv = 0       # rv the current snapshot covers
        self._wave_seq = 0          # update_wave journal grouping id
        self._checkpoint_records = checkpoint_records
        self._checkpoint_interval = checkpoint_interval_seconds
        self._last_checkpoint = time.monotonic()
        self.checkpoints_total = 0
        self.snapshot_fallbacks = 0
        self.snapshot_records = 0
        self.journal_suffix_records = 0

    # -- recovery (runs from Store.__init__, pre-sharing) ------------------

    def _recover(self) -> None:
        """Load snapshot + replay the journal suffix + open the journal
        for append; checkpoints immediately when the replayed suffix
        dwarfs the live set (the etcd-compaction analogue)."""
        path = self._journal_path
        if path is None:
            return
        snap_n = self._load_snapshot()
        applied, lines = self._replay_journal(path, min_rv=self._snapshot_rv)
        self.snapshot_records = snap_n or 0
        self.journal_suffix_records = applied
        live = sum(len(objs) for objs in self._objects.values())
        self._journal = open(path, "a")
        self._journal_records = lines
        if lines > max(1024, 4 * live):
            # replay-time bound: a journal whose suffix dwarfs the
            # live set (churny writers — lease renewals every few
            # seconds) is checkpointed right away, so the NEXT
            # restart pays snapshot + near-empty suffix instead of
            # replaying history
            try:
                self._checkpoint_locked()
            except Exception:  # noqa: BLE001 — durability degradation
                self.journal_write_errors += 1
                logging.getLogger(__name__).exception(
                    "post-recovery checkpoint failed; journal kept"
                )

    def _open_journal(self) -> None:
        if self._journal_path is not None and self._journal is None:
            self._journal = open(self._journal_path, "a")

    def _replay_journal(
        self, path: str, min_rv: int = 0
    ) -> Tuple[int, int]:
        """Replay the shard journal; records at or below `min_rv`
        (covered by the loaded snapshot) are skipped.  update_wave
        records carry a wave id and a terminator: a wave is buffered and
        applied only when its terminator arrives, so a torn final wave
        is dropped WHOLE (truncated like a torn tail — it was never
        acknowledged durable) and a wave holed by mid-file corruption is
        skipped whole, never half-applied.  Returns (applied, good_lines)."""
        import json
        import os

        from . import wire

        if not os.path.exists(path):
            return 0, 0
        replayed = 0
        lines = 0
        good_offset = 0
        size = os.path.getsize(path)
        # wave buffering: (op, rv, kind, key, obj) per pending record
        pending: List[tuple] = []
        pending_wid = None
        pending_offset = 0       # byte offset where the pending wave began
        dead_waves: set = set()  # wave ids dropped by corruption holes

        def apply(op, rv, kind, key, obj) -> None:
            nonlocal replayed
            objs = self._objects.setdefault(kind, {})
            vers = self._versions.setdefault(kind, {})
            if op == DELETED:
                objs.pop(key, None)
                vers.pop(key, None)
            else:
                objs[key] = obj
                vers[key] = rv
            self._last_rv = max(self._last_rv, rv)
            replayed += 1

        def drop_pending(why: str) -> None:
            nonlocal pending, pending_wid
            if pending:
                self.journal_torn_waves += 1
                logging.getLogger(__name__).error(
                    "journal %s: dropping incomplete wave %s whole "
                    "(%d records; %s)", path, pending_wid, len(pending),
                    why,
                )
            if pending_wid is not None:
                dead_waves.add(pending_wid)
            pending, pending_wid = [], None

        with open(path, "rb") as f:
            for raw in f:
                line = raw.decode(errors="replace").strip()
                if not line:
                    good_offset += len(raw)
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("journal record is not an object")
                    crc = rec.pop("crc", None)
                    if framing.is_frame(rec):
                        # one-line sub-wave frame (api/framing.py): its
                        # single CRC covers every record, its crc is
                        # MANDATORY (no pre-CRC frames exist), and the
                        # whole frame decodes up front so structural
                        # damage anywhere inside drops it atomically
                        try:
                            if not framing.frame_crc_ok(rec, crc):
                                raise ValueError("journal frame crc mismatch")
                            frame = [
                                (
                                    sub["op"], sub["rv"], sub["kind"],
                                    sub["key"],
                                    None if sub["op"] == DELETED
                                    else wire.from_wire(sub["obj"]),
                                )
                                for sub in rec["recs"]
                            ]
                        except (ValueError, KeyError, TypeError):
                            # unlike a plain corrupt line we KNOW this
                            # was a wave — count it as one
                            self.journal_torn_waves += 1
                            raise
                        op = rv = kind = key = obj = None
                    else:
                        frame = None
                        if not _record_crc_ok(rec, crc):
                            raise ValueError("journal record crc mismatch")
                        op, rv, kind = rec["op"], rec["rv"], rec["kind"]
                        key = rec["key"]
                        obj = (
                            None if op == DELETED
                            else wire.from_wire(rec["obj"])
                        )
                except (json.JSONDecodeError, ValueError, KeyError, TypeError):
                    # undecodable, CRC-failing, OR structurally-corrupt
                    # record (a line that parses as JSON but lost its
                    # fields or its object payload aborts replay just as
                    # hard as a torn one)
                    self.journal_recovered_records += 1
                    if good_offset + len(raw) >= size:
                        # corrupt TAIL (the first corrupt record with
                        # nothing valid after it): the process died
                        # mid-append; the record was never acknowledged
                        # durable — stop replay and truncate so appends
                        # continue from the last good line.  A wave the
                        # torn record belonged to is dropped whole: the
                        # truncation point backs up to the wave's start.
                        self.journal_tail_truncations += 1
                        cut = (
                            pending_offset if pending else good_offset
                        )
                        drop_pending("torn tail inside the wave")
                        with open(path, "r+b") as t:
                            t.truncate(cut)
                        break
                    # mid-file corruption (partial page write): records
                    # AFTER it were acknowledged durable — skip the bad
                    # line, keep replaying, do NOT truncate them away.
                    # A wave holed by the corruption loses its atomicity
                    # guarantee, so the whole wave is dropped instead.
                    drop_pending("mid-file corruption inside the wave")
                    logging.getLogger(__name__).error(
                        "journal %s: corrupt record at offset %d "
                        "(not tail); skipping it and keeping later "
                        "records", path, good_offset,
                    )
                    good_offset += len(raw)
                    continue
                lines += 1
                wid = rec.get("w")
                if wid is not None:
                    self._wave_seq = max(self._wave_seq, int(wid))
                if frame is not None:
                    # the frame IS its wave: no terminator protocol, no
                    # buffering — apply atomically.  A legacy wave left
                    # open before it never terminated: atomicity wins.
                    drop_pending("unterminated wave before frame")
                    for entry in frame:
                        if entry[1] > min_rv:
                            apply(*entry)
                    good_offset += len(raw)
                    continue
                if wid is not None and wid in dead_waves:
                    good_offset += len(raw)
                    continue  # straggler of a dropped wave
                if wid is None:
                    # a plain record while a wave is open means the wave
                    # never terminated (should not happen: waves append
                    # contiguously under the lock) — atomicity wins
                    drop_pending("unterminated wave before plain record")
                    if rv > min_rv:
                        apply(op, rv, kind, key, obj)
                else:
                    if pending_wid is not None and wid != pending_wid:
                        drop_pending("unterminated wave before next wave")
                    if not pending:
                        pending_offset = good_offset
                    pending_wid = wid
                    if rv > min_rv:
                        pending.append((op, rv, kind, key, obj))
                    if rec.get("wz"):
                        # terminator: the whole wave is on disk — commit
                        for entry in pending:
                            apply(*entry)
                        pending, pending_wid = [], None
                good_offset += len(raw)
            else:
                if pending:
                    # EOF with an open wave: the terminator never made
                    # it to disk — drop the wave whole and truncate so
                    # appends continue from before it
                    drop_pending("torn final wave (no terminator)")
                    self.journal_tail_truncations += 1
                    with open(path, "r+b") as t:
                        t.truncate(pending_offset)
        return replayed, lines

    def _load_snapshot(self) -> Optional[int]:
        """Load the checkpoint snapshot into empty object maps; returns
        the record count, or None when the snapshot is absent OR corrupt
        (any CRC/parse failure, a record-count mismatch against the
        header, a missing header).  Corruption rolls the maps back to
        empty and counts `snapshot_fallbacks` — the caller falls back to
        replaying the full journal, so a damaged snapshot degrades
        recovery time, never correctness.  Runs from __init__ before the
        store is shared."""
        import json
        import os

        from . import wire

        path = self._snapshot_path
        if path is None or not os.path.exists(path):
            return None
        objects: Dict[str, Dict[str, Any]] = {}
        versions: Dict[str, Dict[str, int]] = {}
        header = None
        n = 0
        max_rv = 0
        try:
            with open(path, "rb") as f:
                for raw in f:
                    line = raw.decode(errors="replace").strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("snapshot record is not an object")
                    crc = rec.pop("crc", None)
                    if not _record_crc_ok(rec, crc):
                        raise ValueError("snapshot record crc mismatch")
                    if header is None:
                        if "snapshot_rv" not in rec:
                            raise ValueError("snapshot header missing")
                        header = rec
                        continue
                    rv, kind, key = rec["rv"], rec["kind"], rec["key"]
                    obj = wire.from_wire(rec["obj"])
                    objects.setdefault(kind, {})[key] = obj
                    versions.setdefault(kind, {})[key] = rv
                    max_rv = max(max_rv, rv)
                    n += 1
            if header is None or n != header["records"]:
                raise ValueError(
                    f"snapshot truncated: {n} records, header says "
                    f"{header['records'] if header else '?'}"
                )
        except Exception:  # noqa: BLE001 — recovery containment
            self.snapshot_fallbacks += 1
            logging.getLogger(__name__).exception(
                "snapshot %s corrupt; falling back to full journal "
                "replay", path,
            )
            return None
        self._objects = objects
        self._versions = versions
        self._last_rv = max(int(header["snapshot_rv"]), max_rv)
        self._snapshot_rv = int(header["snapshot_rv"])
        return n

    # -- checkpoint --------------------------------------------------------

    def _checkpoint_locked(self, truncate: bool = True) -> int:
        import os

        from . import wire

        path = self._journal_path
        if path is None or self._snapshot_path is None:
            return 0
        faults.fire("store.checkpoint", shard=self.index)
        tmp = self._snapshot_path + ".tmp"
        n = sum(len(objs) for objs in self._objects.values())
        with open(tmp, "w") as f:
            f.write(_encode_record(
                {"snapshot_rv": self._last_rv, "records": n}
            ))
            for kind, objs in self._objects.items():
                for key, obj in objs.items():
                    f.write(_encode_record({
                        "op": ADDED,
                        "rv": self._versions[kind][key],
                        "kind": kind,
                        "key": key,
                        "obj": wire.to_wire(obj),
                    }))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        _fsync_dir(self._snapshot_path)
        self._snapshot_rv = self._last_rv
        self.snapshot_records = n
        self.checkpoints_total += 1
        self._last_checkpoint = time.monotonic()
        if truncate:
            # everything at or below the snapshot rv is covered by the
            # durable snapshot; the journal restarts empty
            if self._journal is not None:
                try:
                    self._journal.close()
                except (OSError, ValueError):
                    pass
            with open(path, "w") as jf:
                jf.flush()
                os.fsync(jf.fileno())
            self._journal = open(path, "a")
            self._journal_records = 0
        return n

    # -- journal (crash-only durability; caller holds the shard lock) ------

    _JOURNAL_FLUSH_S = 0.05

    def _flush_journal(self) -> None:
        faults.fire("store.journal.fsync", shard=self.index)
        self._journal.flush()

    def _journal_commit(self, lines: List[str]) -> None:
        """Write+flush journal lines with failure containment: a torn or
        failed append degrades durability (counted, logged) but never
        fails the already-committed in-memory write — the store keeps
        serving (availability over the fsync ack, unlike etcd's
        fail-stop; replay's CRC path handles whatever landed)."""
        try:
            act = faults.fire("store.journal.append", records=len(lines))
            act2 = faults.fire(
                "store.shard.journal.append",
                shard=self.index, records=len(lines),
            )
            act = act if act is not None else act2
            data = "".join(lines)
            if isinstance(act, faults.TornWrite):
                cut = max(1, int(len(data) * act.frac))
                self._journal.write(data[:cut].rstrip("\n"))
                self._journal.flush()
                raise faults.FaultInjected("torn journal append")
            self._journal.write(data)
            if self._journal_sync == "write":
                self._flush_journal()
            else:
                # group commit: one flush covers a burst of records (a
                # bind wave is thousands back-to-back); the flusher
                # thread bounds the window at _JOURNAL_FLUSH_S
                self._journal_dirty = True
                now = time.monotonic()
                if now - self._journal_flushed_at >= self._JOURNAL_FLUSH_S:
                    self._flush_journal()
                    self._journal_dirty = False
                    self._journal_flushed_at = now
        except Exception:  # noqa: BLE001 — durability degradation, not an API error
            self.journal_write_errors += 1
            logging.getLogger(__name__).exception(
                "journal append failed; continuing with degraded durability"
            )
            return
        self._journal_records += len(lines)
        live = sum(len(objs) for objs in self._objects.values())
        threshold = self._checkpoint_records or max(1024, 8 * max(live, 1))
        due = (
            self._checkpoint_interval > 0
            and time.monotonic() - self._last_checkpoint
            >= self._checkpoint_interval
        )
        if self._journal_records > threshold or due:
            try:
                self._checkpoint_locked()
            except Exception:  # noqa: BLE001
                self.journal_write_errors += 1
                logging.getLogger(__name__).exception(
                    "checkpoint failed; reopening journal for append"
                )
                if self._journal is None or self._journal.closed:
                    self._journal = open(self._journal_path, "a")

    def _append_journal(self, op: str, kind: str, key: str, obj, rv: int) -> None:
        # caller holds the shard lock; called after the publish
        if self._journal is None:
            return
        from . import wire

        rec = {"op": op, "rv": rv, "kind": kind, "key": key}
        if op != DELETED:
            rec["obj"] = wire.to_wire(obj)
        self._journal_commit([_encode_record(rec)])

    def _append_journal_wave(
        self, kind: str, records: List[Tuple[str, str, Any, int]]
    ) -> None:
        # caller holds the shard lock; one write + one flush for the
        # sub-wave.  Every record carries the shard-local wave id ("w")
        # and the last one the terminator ("wz"): replay applies the
        # wave atomically — a tail torn anywhere inside it drops the
        # WHOLE wave, so a recovered shard never holds half a bind wave.
        if self._journal is None:
            return
        from . import wire

        self._wave_seq += 1
        wid = self._wave_seq
        if self._journal_framing:
            # frame mode: ONE line, one json.dumps pass, one crc32 pass
            # for the whole sub-wave (api/framing.py) — same atomicity
            # (the frame is the wave), ~records× fewer codec calls
            recs = []
            for op, key, obj, rv in records:
                rec = {"op": op, "rv": rv, "kind": kind, "key": key}
                if op != DELETED:
                    rec["obj"] = wire.to_wire(obj)
                recs.append(rec)
            line = framing.encode_frame(wid, recs)
            if faults._registry is not None:
                action = faults.fire(
                    "journal.frame",
                    shard=self.index, wid=wid, records=len(recs),
                )
                if action is faults.CORRUPT:
                    # poison one byte in the middle of the encoded frame
                    # (trailing newline intact, so later lines survive):
                    # replay must reject the whole wave through the CRC
                    # check — torn, never half-applied.  Exercised with
                    # the native _hostplane splice AND the pure-Python
                    # fallback (the chaos parity seed).
                    mid = len(line) // 2
                    flip = "0" if line[mid] != "0" else "1"
                    line = line[:mid] + flip + line[mid + 1:]
            self.journal_frames += 1
            self.journal_frame_bytes += len(line)
            self._journal_commit([line])
            return
        lines = []
        for i, (op, key, obj, rv) in enumerate(records):
            rec = {"op": op, "rv": rv, "kind": kind, "key": key, "w": wid}
            if i == len(records) - 1:
                rec["wz"] = 1
            if op != DELETED:
                rec["obj"] = wire.to_wire(obj)
            lines.append(_encode_record(rec))
        self._journal_commit(lines)


class Store:
    """The single-process control-plane store, sharded by
    (kind, namespace) — see the module docstring for the concurrency
    contract.

    With `journal_path`, every committed write appends one JSON line to
    its SHARD's journal (``<path>`` for a 1-shard store, ``<path>.s<i>``
    otherwise) and construction replays every shard: the crash-only
    resume property whose reference counterpart is every component
    rebuilding from etcd on restart (storage/etcd3/store.go; SURVEY
    §5.4).  Replay re-applies writes without re-journaling and leaves
    the event ring empty — watchers attach after recovery and relist,
    exactly like a reflector hitting a fresh apiserver.  The shard
    count of an existing on-disk layout is inferred from the files, so
    ``Store(journal_path=...)`` restarts any layout; an EXPLICIT
    `shards` that disagrees triggers a reshard (replay old layout,
    re-route every object by the current hash, checkpoint the new
    shards, drop the old files).

    Checkpointing bounds replay PER SHARD: ``checkpoint()`` writes each
    shard's point-in-time snapshot via write-temp + fsync +
    atomic-rename and truncates that shard's journal past its
    checkpoint rv, so recovery = N × (load snapshot + replay journal
    SUFFIX), shards independently.  A corrupt snapshot falls back to
    replaying that shard's whole journal; ``update_wave`` records
    replay atomically per shard.  Recovery observability:
    ``recovery_duration_ms`` / ``snapshot_records`` /
    ``journal_suffix_records`` (summed across shards), mirrored into
    the scheduler Registry."""

    # graftlint guarded-by declarations: the rv counter, the global
    # event ring, the watcher registry and its counters all share the
    # small publish lock (shard-owned state is annotated on _StoreShard)
    GUARDED_FIELDS = {
        "_rv": "_rv_lock",
        "_buffer": "_rv_lock",
        "_watchers": "_rv_lock",
        "watchers_terminated": "_rv_lock",
        "terminated_by_kind": "_rv_lock",
        "watch_expired_total": "_rv_lock",
        "_watch_coalesced_closed": "_rv_lock",
        "fenced_writes_total": "_rv_lock",
        "fanout_chunks": "_rv_lock",
        "fanout_chunk_events": "_rv_lock",
    }
    # reviewed lock-free / caller-holds-the-publish-lock helpers
    LOCKED_METHODS = frozenset({
        "_dispatch",
        "_dispatch_wave",
        "_queue_fanout_locked",
        "_check_fence_locked",
        "_publish_one_locked",
        "_reshard",
    })

    def __init__(
        self,
        buffer_size: int = 4096,
        # per-watcher queue matches the event buffer: a watcher that
        # can't hold buffer_size events couldn't relist-recover either,
        # and a 4k bind wave must not kill the scheduler's own informer
        watch_capacity: int = 4096,
        journal_path: Optional[str] = None,
        admission=None,
        journal_sync: str = "write",  # "write" | "interval"
        snapshot_path: Optional[str] = None,
        # journal records (post-checkpoint suffix) that trigger an
        # automatic checkpoint, PER SHARD; None = max(1024, 8 * live)
        checkpoint_records: Optional[int] = None,
        # wall-clock checkpoint cadence; 0 disables periodic checkpoints
        # (growth-triggered ones still run)
        checkpoint_interval_seconds: float = 0.0,
        # store shards (per-shard lock/journal/checkpoint/fan-out);
        # None = infer from an existing journal layout, else
        # DEFAULT_SHARDS.  1 reproduces the legacy single-lock layout
        # (journal at `journal_path` itself).
        shards: Optional[int] = None,
        # journal sub-waves as one-line frames (api/framing.py): one
        # serialization + one CRC pass per commit sub-wave.  False
        # writes the legacy per-line wave format; replay accepts BOTH,
        # interleaved, regardless of this flag (upgrade path).
        journal_framing: bool = True,
    ):
        inferred = (
            self._infer_shards(journal_path) if journal_path else None
        )
        n = shards or inferred or DEFAULT_SHARDS
        if n < 1:
            raise ValueError("shards must be >= 1")
        # the one small global rv lock: allocation + publish only — all
        # expensive write work runs under the owning shard's lock
        self._rv_lock = threading.RLock()
        self._rv = 0
        self._buffer: List[Event] = []      # global ring of recent events
        self._buffer_size = buffer_size
        self._watch_capacity = watch_capacity
        self._watchers: Dict[str, List[Watch]] = {}     # kind -> watches
        # destructive slow-watcher kills — the backpressured fan-out
        # never performs them, so churn benches assert this stays 0
        self.watchers_terminated = 0
        self.terminated_by_kind: Dict[str, int] = {}    # bounded: one key/kind
        # overload-protection observability (mirrored into the scheduler
        # Registry as scheduler_watch_* each cycle):
        #   expired — watchers converted to bookmark+relist after their
        #       coalescing buffer overflowed (or a replay overflowed);
        #   coalesced (closed) — compacted-event counts folded in from
        #       watchers that have since expired or stopped (live
        #       watchers keep their own counters; watch_stats() sums).
        self.watch_expired_total = 0
        self._watch_coalesced_closed = 0
        # update_wave sub-waves rejected because the caller's FenceToken
        # no longer matched the Lease (a deposed leader's late wave)
        self.fenced_writes_total = 0
        # batched fan-out accounting: chunks handed to watchers and the
        # events they carried (mean = fanout chunk size — mirrored into
        # the Registry's scheduler_fanout_chunk_size)
        self.fanout_chunks = 0
        self.fanout_chunk_events = 0
        # optional api.admission.AdmissionChain: mutate-then-validate on
        # every create/update before the commit (the apiserver admission
        # chain's position in the write path, server/config.go:983).
        # Admission-armed writes serialize on _admission_lock (held
        # through the commit) so store-reading plugins (quota validator,
        # ClusterIP allocation) stay check-then-act-safe across shards.
        self._admission = admission
        self._admission_lock = threading.RLock()
        if admission is not None and getattr(admission, "store", None) is None:
            admission.store = self  # plugin initializer (wants_store)
        self._journal_path = journal_path
        self._journal_sync = journal_sync
        # last recovery's wall time (snapshot loads + suffix replays,
        # all shards); set once at construction
        self.recovery_duration_ms = 0.0
        self._shards: List[_StoreShard] = [
            _StoreShard(
                i,
                self._shard_journal_path(journal_path, i, n),
                self._shard_snapshot_path(
                    journal_path, snapshot_path, i, n
                ),
                journal_sync,
                checkpoint_records,
                checkpoint_interval_seconds,
                journal_framing=journal_framing,
            )
            for i in range(n)
        ]
        if journal_path:
            t_rec = time.monotonic()
            if inferred is not None and shards and inferred != shards:
                # explicit shard count disagrees with the on-disk layout:
                # replay the OLD layout and re-route every object
                self._reshard(inferred, journal_path, snapshot_path)
            else:
                for shard in self._shards:
                    shard._recover()
            with self._rv_lock:
                self._rv = max(
                    [shard._last_rv for shard in self._shards] + [0]
                )
            self.recovery_duration_ms = (
                time.monotonic() - t_rec
            ) * 1000.0
            if journal_sync == "interval":
                # bounds the crash window left by batched flushing: any
                # record older than _JOURNAL_FLUSH_S is on disk
                t = threading.Thread(
                    target=self._journal_flusher,
                    name="journal-flush",
                    daemon=True,
                )
                t.start()

    # -- shard plumbing ----------------------------------------------------

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    def _hash_index(self, kind: str, namespace: str) -> int:
        # raw (kind, namespace) hash — callers that accept caller-typed
        # namespaces go through shard_index() for scope normalization
        return _shard_hash(kind, namespace) % len(self._shards)

    def shard_index(self, kind: str, namespace: str = "default") -> int:
        """The shard owning (kind, namespace) — the scheduler's binder
        partitions bind waves with this so sub-waves commit per shard."""
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        return self._hash_index(kind, namespace)

    @staticmethod
    def _shard_journal_path(
        base: Optional[str], index: int, n: int
    ) -> Optional[str]:
        if base is None:
            return None
        return base if n == 1 else f"{base}.s{index}"

    @classmethod
    def _shard_snapshot_path(
        cls,
        base: Optional[str],
        snapshot_path: Optional[str],
        index: int,
        n: int,
    ) -> Optional[str]:
        if snapshot_path is not None and n == 1:
            return snapshot_path
        jp = cls._shard_journal_path(base, index, n)
        return jp + ".snap" if jp else None

    @staticmethod
    def _infer_shards(journal_path: str) -> Optional[int]:
        """Shard count of an existing on-disk layout: ``<path>.s<i>``
        files (or their snapshots) win; a bare ``<path>``/``.snap`` is
        the 1-shard (legacy) layout; nothing on disk means no layout."""
        import glob
        import os
        import re

        found = -1
        pat = re.compile(
            re.escape(journal_path) + r"\.s(\d+)(\.snap)?$"
        )
        for p in glob.glob(glob.escape(journal_path) + ".s*"):
            m = pat.match(p)
            if m:
                found = max(found, int(m.group(1)))
        if found >= 0:
            return found + 1
        if (
            os.path.exists(journal_path)
            or os.path.exists(journal_path + ".snap")
        ):
            return 1
        return None

    def _reshard(
        self,
        old_n: int,
        journal_path: str,
        snapshot_path: Optional[str],
    ) -> None:
        """Re-route an on-disk layout of `old_n` shards into the current
        shard set: replay the old layout (full PR 8 recovery per old
        shard), hash every live object to its new shard, checkpoint the
        new shards (their journals start empty past the snapshot), then
        drop the old files.  Runs from __init__ before sharing."""
        import os

        old = [
            _StoreShard(
                i,
                self._shard_journal_path(journal_path, i, old_n),
                self._shard_snapshot_path(
                    journal_path, snapshot_path, i, old_n
                ),
                self._journal_sync,
                None,
                0.0,
            )
            for i in range(old_n)
        ]
        rv = 0
        for osh in old:
            osh._recover()
            rv = max(rv, osh._last_rv)
            for kind, objs in osh._objects.items():
                for key, obj in objs.items():
                    tgt = self._shards[
                        self._hash_index(kind, obj.meta.namespace)
                    ]
                    tgt._objects.setdefault(kind, {})[key] = obj
                    tgt._versions.setdefault(kind, {})[key] = (
                        osh._versions[kind][key]
                    )
            if osh._journal is not None:
                try:
                    osh._journal.close()
                except (OSError, ValueError):
                    pass
        old_files = []
        for osh in old:
            old_files += [osh._journal_path, osh._snapshot_path]
        for shard in self._shards:
            shard._last_rv = rv
            shard._open_journal()
            shard._checkpoint_locked(truncate=True)
        keep = set()
        for shard in self._shards:
            keep.update({shard._journal_path, shard._snapshot_path})
        for path in old_files:
            if path and path not in keep and os.path.exists(path):
                os.remove(path)

    def _journal_flusher(self) -> None:
        while True:
            time.sleep(_StoreShard._JOURNAL_FLUSH_S)
            live = False
            for shard in self._shards:
                with shard._lock:
                    if shard._journal is None:
                        continue
                    live = True
                    if shard._journal_dirty:
                        try:
                            shard._journal.flush()
                        except ValueError:  # closed mid-compaction race
                            pass
                        shard._journal_dirty = False
                        shard._journal_flushed_at = time.monotonic()
            if not live:
                return

    # -- aggregated shard counters (legacy single-store surface) -----------

    def _sum(self, field: str) -> int:
        return sum(getattr(shard, field) for shard in self._shards)

    @property
    def journal_recovered_records(self) -> int:
        return self._sum("journal_recovered_records")

    @property
    def journal_tail_truncations(self) -> int:
        return self._sum("journal_tail_truncations")

    @property
    def journal_write_errors(self) -> int:
        return self._sum("journal_write_errors")

    @property
    def journal_torn_waves(self) -> int:
        return self._sum("journal_torn_waves")

    @property
    def journal_frames(self) -> int:
        return self._sum("journal_frames")

    @property
    def journal_frame_bytes(self) -> int:
        return self._sum("journal_frame_bytes")

    @property
    def snapshot_fallbacks(self) -> int:
        return self._sum("snapshot_fallbacks")

    @property
    def checkpoints_total(self) -> int:
        return self._sum("checkpoints_total")

    @property
    def snapshot_records(self) -> int:
        return self._sum("snapshot_records")

    @property
    def journal_suffix_records(self) -> int:
        return self._sum("journal_suffix_records")

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _meta(obj: Any) -> api.ObjectMeta:
        return obj.meta

    def _kind_of(self, obj: Any) -> str:
        kind = getattr(obj, "KIND", None)
        if not kind:
            raise TypeError(f"object {obj!r} has no KIND")
        return kind

    def _write_guard(self):
        """Admission-armed writes hold the admission lock THROUGH the
        commit (check-then-act atomicity across shards — two concurrent
        creates must not both pass quota or allocate one ClusterIP);
        plain stores pay nothing."""
        if self._admission is not None:
            return self._admission_lock
        return nullcontext()

    def _dispatch(self, ev: Event) -> None:
        # caller holds the publish lock: global ring append + backlog
        # handoff to the owning shard only — the fan-out itself runs on
        # that shard's dispatch thread off every lock
        self._buffer.append(ev)
        if len(self._buffer) > self._buffer_size:
            del self._buffer[: self._buffer_size // 4]
        self._queue_fanout_locked(
            self._hash_index(ev.kind, ev.obj.meta.namespace),
            ev.kind, [ev],
        )

    def _dispatch_wave(self, kind: str, events: List[Event]) -> None:
        # caller holds the publish lock; one ring extend + ONE backlog
        # handoff for the whole sub-wave (the shard's fan-out thread
        # delivers it as a batch)
        self._buffer.extend(events)
        excess = len(self._buffer) - self._buffer_size
        if excess > 0:
            del self._buffer[: excess + self._buffer_size // 4]
        self._queue_fanout_locked(
            self._hash_index(kind, events[0].obj.meta.namespace),
            kind, events,
        )

    def _queue_fanout_locked(
        self, sid: int, kind: str, events: List[Event]
    ) -> None:
        # caller holds the publish lock.  No watchers for the kind means
        # no delivery obligation: a watcher registered later replays
        # from the ring (watch(from_rv)) or starts from-now with its
        # horizons pinned to the current rv, so skipping the backlog is
        # exact.
        if not self._watchers.get(kind):
            return
        shard = self._shards[sid]
        with shard._dispatch_cv:
            self._ensure_dispatcher_cv_held(shard)
            shard._dispatch_backlog.append((kind, events))
            shard._dispatch_cv.notify_all()

    def _ensure_dispatcher_cv_held(self, shard: _StoreShard) -> None:
        # caller holds the shard's dispatch condition.  Lazy +
        # self-healing: the thread starts with the first delivery and is
        # restarted here if an injected crash killed it (every handoff
        # passes through this check).
        t = shard._dispatch_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(
            target=_watch_dispatch_loop,
            args=(weakref.ref(self), shard.index),
            name=f"watch-dispatch-{shard.index}",
            daemon=True,
        )
        shard._dispatch_thread = t
        t.start()

    def _fan_out(self, kind: str, events: List[Event]) -> None:
        """Deliver one committed batch to every watcher of `kind` — a
        shard dispatch thread's half of the watch path, running OFF
        every store lock so per-watcher coalescing work never blocks
        writers.  The chunk reaches each watcher through ONE
        ``Watch._mu`` acquisition (``_offer_batch``) instead of a
        per-event lock round-trip."""
        with self._rv_lock:
            watchers = list(self._watchers.get(kind, ()))
            if watchers:
                self.fanout_chunks += 1
                self.fanout_chunk_events += len(events)
        expired: List[Watch] = []
        for w in watchers:
            try:
                if w._offer_batch(events) is OFFER_EXPIRED:
                    expired.append(w)
            except Exception:  # noqa: BLE001 — per-watcher containment
                # a poisoned offer (fault-schedule exception, corrupt
                # payload) must cost only THIS watcher, and it must cost
                # it loudly: expire the stream so the consumer relists.
                # Letting the exception unwind the whole batch silently
                # starved every remaining watcher of the rest of the
                # batch with no 410 signal — a stale informer cache with
                # no recovery path (interleave scenario
                # 'writers_vs_dispatch' with a watch.offer fail schedule
                # pins this).
                logging.getLogger(__name__).exception(
                    "watch offer failed; expiring the watcher"
                )
                with w._mu:
                    w._expire_locked()
                expired.append(w)
        for w in expired:
            self._retire_expired_watch(w, kind)

    def _retire_expired_watch(self, w: Watch, kind: str) -> None:
        with self._rv_lock:
            ws = self._watchers.get(kind)
            if ws is not None and w in ws:
                ws.remove(w)
            self.watch_expired_total += 1
            with w._mu:  # _rv_lock -> Watch._mu (same order as replay)
                self._watch_coalesced_closed += w.coalesced
                w.coalesced = 0

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._write_guard():
            admitted = False
            if self._admission is not None:
                # admit a server-side COPY: mutators must never edit the
                # caller's object (a rejected or conflicting write would
                # leave the caller's template silently modified — every
                # other store path deep-copies for exactly this
                # isolation).  The admission lock is held through the
                # commit, so store-reading plugins stay
                # check-then-act-safe (see _write_guard).
                obj = self._admission.admit(copy.deepcopy(obj), "CREATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                # resource scope normalization: cluster-scoped objects
                # live at namespace "" regardless of what the caller set
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            shard = self._shards[self._hash_index(kind, meta.namespace)]
            with shard._lock:
                objs = shard._objects.setdefault(kind, {})
                if key in objs:
                    raise AlreadyExists(f"{kind} {key} exists")
                if not admitted:  # the admitted copy is already unaliased
                    obj = copy.deepcopy(obj)
                if not obj.meta.creation_timestamp:
                    obj.meta.creation_timestamp = time.time()
                with self._rv_lock:
                    rv = self._publish_one_locked(
                        shard, ADDED, kind, key, obj
                    )
                shard._append_journal(ADDED, kind, key, obj, rv)
                return copy.deepcopy(obj)

    def _publish_one_locked(
        self,
        shard: _StoreShard,
        op: str,
        kind: str,
        key: str,
        obj: Any,
        set_rv: bool = True,
        event_copy: bool = False,
    ) -> int:
        """The tiny global publish step (caller holds the shard lock AND
        the publish lock): allocate the rv, install/remove the object in
        the shard maps, append the event to the ring and the shard
        backlog.  The dispatched Event aliases the committed object by
        default (no defensive copy): committed objects are never mutated
        in place — an update replaces the map entry — and watch
        consumers already share one Event payload across every watcher.
        `set_rv=False` leaves the object's meta untouched (delete() of a
        STORED object: mutating its rv would break the immutability the
        lock-free list() cut depends on); `event_copy=True` deep-copies
        the event payload (paths that hand the same object back to the
        caller, who may mutate it while the fan-out is in flight)."""
        self._rv += 1
        rv = self._rv
        if set_rv:
            obj.meta.resource_version = rv
        objs = shard._objects.setdefault(kind, {})
        vers = shard._versions.setdefault(kind, {})
        if op == DELETED:
            objs.pop(key, None)
            vers.pop(key, None)
        else:
            objs[key] = obj
            vers[key] = rv
        shard._last_rv = rv
        ev_obj = copy.deepcopy(obj) if event_copy else obj
        self._dispatch(Event(op, kind, ev_obj, rv))
        return rv

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        shard = self._shards[self._hash_index(kind, namespace)]
        with shard._lock:
            try:
                return copy.deepcopy(shard._objects[kind][key])
            except KeyError:
                raise NotFound(f"{kind} {key}") from None

    def update(
        self, obj: Any, *, force: bool = False, copy_result: bool = True
    ) -> Any:
        """Optimistic-concurrency update: obj.meta.resource_version must
        match the stored version unless force (the GuaranteedUpdate retry
        loop's compare step).  copy_result=False skips the defensive
        deep copy of the return value for hot-path callers that discard
        it (the scheduler's bind wave) — the returned object is then the
        COMMITTED one and must not be mutated."""
        with self._write_guard():
            admitted = False
            if self._admission is not None:
                obj = self._admission.admit(copy.deepcopy(obj), "UPDATE")
                admitted = True
            kind = self._kind_of(obj)
            meta = self._meta(obj)
            if kind in api.CLUSTER_SCOPED_KINDS and meta.namespace:
                meta.namespace = ""
            key = _key(meta.namespace, meta.name)
            shard = self._shards[self._hash_index(kind, meta.namespace)]
            with shard._lock:
                objs = shard._objects.get(kind, {})
                if key not in objs:
                    raise NotFound(f"{kind} {key}")
                current_rv = shard._versions[kind][key]
                if not force and meta.resource_version != current_rv:
                    raise Conflict(
                        f"{kind} {key}: rv {meta.resource_version} != "
                        f"{current_rv}"
                    )
                if not admitted:
                    obj = copy.deepcopy(obj)
                if (
                    obj.meta.deletion_timestamp is not None
                    and not obj.meta.finalizers
                ):
                    # last finalizer dropped on a deleting object: the
                    # update completes the two-phase delete (store.go:1176)
                    with self._rv_lock:
                        rv = self._publish_one_locked(
                            shard, DELETED, kind, key, obj,
                            event_copy=True,  # obj is handed back below
                        )
                    shard._append_journal(DELETED, kind, key, None, rv)
                    return obj
                with self._rv_lock:
                    rv = self._publish_one_locked(
                        shard, MODIFIED, kind, key, obj
                    )
                shard._append_journal(MODIFIED, kind, key, obj, rv)
                return copy.deepcopy(obj) if copy_result else obj

    def update_wave(
        self,
        kind: str,
        updates: List[Tuple[str, str, Callable[[Any], None]]],
        *,
        admit: bool = True,
        fence: Optional[FenceToken] = None,
        shard_hint: Optional[int] = None,
    ) -> Tuple[List[str], Dict[str, Exception]]:
        """Commit a wave of read-modify-write updates as per-shard
        transactions.

        `updates` is a list of (name, namespace, mutate) where mutate(obj)
        edits a private copy of the stored object in place.  The wave is
        partitioned by shard; each SUB-wave runs under one shard-lock
        acquisition with ONE coalesced journal append (a single write +
        flush for every record of that shard) and ONE watch fan-out
        handoff — the scheduler's bind wave pays per-pod costs only for
        the copy and the mutation, not for lock/journal/dispatch.  A
        single-shard wave (one kind, one namespace — every bind sub-wave
        the scheduler commits) is exactly the PR 1 single-transaction
        contract; a wave SPANNING shards is atomic per shard, not across
        them (callers that need cross-shard atomicity — none in-tree —
        must partition with ``shard_index`` themselves).

        Failure splits per object, never per wave: a missing object, a
        mutate() exception, or an admission rejection lands in the
        returned error map under its "namespace/name" key and the rest of
        the wave commits.  Returns (applied_keys, errors).

        Each committed object still gets its own resourceVersion and its
        own watch Event, so watch/informer semantics are byte-identical
        to per-object update(); only the write-path overhead is shared.

        `fence` (a FenceToken) makes every sub-wave a LEADERSHIP-
        CONDITIONAL transaction: under the publish lock, the named Lease
        must still be held by the token's identity at the token's
        acquisition generation, or the sub-wave is rejected whole with
        `Fenced` (counted in `fenced_writes_total`) — a deposed leader's
        late bind wave can never double-bind behind its successor's back
        (the etcd lease-ownership txn compare).  The fence is also
        pre-checked before the first sub-wave so an already-stale wave
        commits nothing.

        `shard_hint` is the STREAMED HAND-OFF fast path: a caller that
        already partitioned its wave with ``shard_index`` (the binder's
        per-shard sub-waves, streamed or pooled) names the owning shard
        and the store verifies it with ONE hash per distinct namespace
        instead of re-hashing every object.  A mismatched hint (a wave
        that actually spans shards) falls back to the full partition —
        misrouted records would split ownership silently, so the hint
        is an optimization, never a trust boundary."""
        faults.fire("store.update_wave", kind=kind, updates=len(updates))
        applied: List[str] = []
        errors: Dict[str, Exception] = {}
        # partition by shard, preserving caller order within each shard
        groups: "OrderedDict[int, List[tuple]]" = OrderedDict()
        hinted = False
        if (
            shard_hint is not None
            and 0 <= shard_hint < len(self._shards)
            and updates
        ):
            hinted = True
            memo: Dict[str, int] = {}
            normalized: List[tuple] = []
            for name, namespace, mutate in updates:
                if kind in api.CLUSTER_SCOPED_KINDS:
                    namespace = ""
                sid = memo.get(namespace)
                if sid is None:
                    sid = memo[namespace] = self._hash_index(kind, namespace)
                if sid != shard_hint:
                    hinted = False
                    break
                normalized.append((name, namespace, mutate))
            if hinted:
                groups[shard_hint] = normalized
        if not hinted:
            groups.clear()
            for name, namespace, mutate in updates:
                if kind in api.CLUSTER_SCOPED_KINDS:
                    namespace = ""
                sid = self._hash_index(kind, namespace)
                groups.setdefault(sid, []).append((name, namespace, mutate))
        with self._write_guard():
            if fence is not None:
                # pre-flight: a wave staged by an already-deposed leader
                # commits NOTHING (matches the single-store contract for
                # empty and single-shard waves alike)
                with self._rv_lock:
                    self._check_fence_locked(fence)
            for sid, group in groups.items():
                a, e = self._update_subwave(
                    self._shards[sid], kind, group, admit, fence
                )
                applied.extend(a)
                errors.update(e)
        return applied, errors

    def _update_subwave(
        self,
        shard: _StoreShard,
        kind: str,
        group: List[tuple],
        admit: bool,
        fence: Optional[FenceToken],
    ) -> Tuple[List[str], Dict[str, Exception]]:
        """One shard's sub-wave: prepare (copy + mutate + admit) under
        the shard lock, publish atomically under the publish lock
        (fence-checked), then ONE journal append for the sub-wave."""
        faults.fire(
            "store.shard.update_wave",
            shard=shard.index, kind=kind, updates=len(group),
        )
        applied: List[str] = []
        errors: Dict[str, Exception] = {}
        with shard._lock:
            objs = shard._objects.get(kind, {})
            prepared: List[Tuple[str, Any]] = []   # (key, mutated copy)
            for name, namespace, mutate in group:
                key = _key(namespace, name)
                cur = objs.get(key)
                if cur is None:
                    errors[key] = NotFound(f"{kind} {key}")
                    continue
                obj = copy.deepcopy(cur)
                try:
                    mutate(obj)
                    if admit and self._admission is not None:
                        obj = self._admission.admit(obj, "UPDATE")
                except Exception as e:  # noqa: BLE001 — per-object split
                    errors[key] = e
                    continue
                prepared.append((key, obj))
            if not prepared:
                return applied, errors
            records: List[Tuple[str, str, Any, int]] = []
            events: List[Event] = []
            with self._rv_lock:
                if fence is not None:
                    self._check_fence_locked(fence)
                vers = shard._versions.setdefault(kind, {})
                for key, obj in prepared:
                    self._rv += 1
                    rv = self._rv
                    obj.meta.resource_version = rv
                    if (
                        obj.meta.deletion_timestamp is not None
                        and not obj.meta.finalizers
                    ):
                        # mirror update(): dropping the last finalizer on
                        # a deleting object completes the two-phase delete
                        objs.pop(key, None)
                        vers.pop(key, None)
                        records.append((DELETED, key, None, rv))
                        events.append(Event(DELETED, kind, obj, rv))
                    else:
                        objs[key] = obj
                        vers[key] = rv
                        records.append((MODIFIED, key, obj, rv))
                        events.append(Event(MODIFIED, kind, obj, rv))
                    applied.append(key)
                shard._last_rv = self._rv
                self._dispatch_wave(kind, events)
            shard._append_journal_wave(kind, records)
        return applied, errors

    def _check_fence_locked(self, fence: FenceToken) -> None:
        # caller holds the publish lock — the Lease cannot change while
        # the sub-wave publishes, so the compare-and-commit is atomic
        lease_shard = self._shards[
            self._hash_index("Lease", fence.namespace)
        ]
        lease = lease_shard._objects.get("Lease", {}).get(
            _key(fence.namespace, fence.name)
        )
        spec = getattr(lease, "spec", None)
        if (
            spec is None
            or spec.holder_identity != fence.identity
            or (
                fence.generation is not None
                and spec.lease_transitions != fence.generation
            )
        ):
            self.fenced_writes_total += 1
            holder = getattr(spec, "holder_identity", None)
            raise Fenced(
                f"wave fenced: lease {fence.namespace}/"
                f"{fence.name} held by {holder!r}, caller "
                f"{fence.identity!r} gen {fence.generation}"
            )

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        """Remove an object.  Objects carrying finalizers get the
        reference's two-phase deletion (registry/generic/registry/
        store.go:1116): deletionTimestamp is set and a MODIFIED event
        fires; the real removal happens when the last finalizer is
        dropped via update() — the node agent's graceful pod shutdown
        and any future finalizing controller ride this."""
        if kind in api.CLUSTER_SCOPED_KINDS:
            namespace = ""
        key = _key(namespace, name)
        shard = self._shards[self._hash_index(kind, namespace)]
        with shard._lock:
            objs = shard._objects.get(kind, {})
            if key not in objs:
                raise NotFound(f"{kind} {key}")
            obj = objs[key]
            if obj.meta.finalizers and obj.meta.deletion_timestamp is not None:
                # already terminating: delete-on-deleting is a no-op
                # (finalizers still gate the removal; a GC re-delete must
                # not hard-remove mid-grace)
                return copy.deepcopy(obj)
            if obj.meta.finalizers and obj.meta.deletion_timestamp is None:
                obj = copy.deepcopy(obj)
                obj.meta.deletion_timestamp = time.time()
                with self._rv_lock:
                    rv = self._publish_one_locked(
                        shard, MODIFIED, kind, key, obj
                    )
                shard._append_journal(MODIFIED, kind, key, obj, rv)
                return copy.deepcopy(obj)
            with self._rv_lock:
                # the STORED object: its meta stays at its committed rv
                # (set_rv=False) and the event payload is a copy — the
                # raw object is returned to the caller below
                rv = self._publish_one_locked(
                    shard, DELETED, kind, key, obj,
                    set_rv=False, event_copy=True,
                )
            shard._append_journal(DELETED, kind, key, None, rv)
            return obj

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Callable[[Any], bool]] = None,
    ) -> Tuple[List[Any], int]:
        """(items, resource_version) — the ListAndWatch handoff point.

        The cut is POINT-IN-TIME CONSISTENT across shards: object
        references and the rv are captured under the publish lock (all
        publishes serialize through it, so a sub-wave is all-or-nothing
        in the cut), and the defensive deep copies happen OUTSIDE the
        lock — committed objects are immutable, an update replaces the
        map entry — so the snapshot path no longer blocks writers for
        the O(items) copy cost."""
        if faults._registry is not None:
            # relist-storm chaos: injected list latency models a control
            # plane whose snapshot path is the contended resource
            faults.fire("store.list", kind=kind)
        with self._rv_lock:
            refs = [
                o
                for shard in self._shards
                for o in shard._objects.get(kind, {}).values()
            ]
            rv = self._rv
        items = [
            copy.deepcopy(o)
            for o in refs
            if (namespace is None or o.meta.namespace == namespace)
            and (selector is None or selector(o))
        ]
        return items, rv

    def kinds(self) -> List[str]:
        """Object kinds the store currently holds (the GC/namespace
        controllers sweep every kind, like the reference's
        RESTMapper-driven resource discovery)."""
        with self._rv_lock:
            out: List[str] = []
            for shard in self._shards:
                for k, objs in shard._objects.items():
                    if objs and k not in out:
                        out.append(k)
            return out

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self, truncate: bool = True) -> int:
        """Checkpoint every shard: each writes a point-in-time snapshot
        of its live objects and (by default) truncates its journal past
        the checkpoint rv, bounding the next recovery to N × (snapshot +
        journal suffix).  Crash-safe by construction per shard
        (write-temp + fsync + atomic-rename + dir fsync; the journal is
        only truncated AFTER the snapshot is durable).  Shards
        checkpoint one at a time — a crash between shards leaves some
        shards on the old snapshot + full journal, which recovery
        handles per shard.  ``truncate=False`` keeps the journals
        (full-replay oracle mode — the chaos suite's bit-parity check).
        Returns the total snapshot record count."""
        total = 0
        for shard in self._shards:
            with shard._lock:
                total += shard._checkpoint_locked(truncate=truncate)
        return total

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, from_rv: Optional[int] = None) -> Watch:
        """Stream events for `kind` after `from_rv` (exclusive).  None
        means 'from now'.  Raises Expired when from_rv predates the event
        buffer — relist and retry (reflector.go 410 handling).  The ring
        is GLOBAL and rv-ordered (appends happen under the publish
        lock), so replay across shards is exactly the single-store
        replay."""
        with self._rv_lock:
            w = Watch(self, self._watch_capacity)
            if from_rv is not None:
                oldest_known = self._buffer[0].rv if self._buffer else self._rv + 1
                if from_rv + 1 < oldest_known and from_rv < self._rv:
                    raise Expired(
                        f"rv {from_rv} too old (buffer starts at {oldest_known})"
                    )
                for ev in self._buffer:
                    if ev.kind == kind and ev.rv > from_rv:
                        if w._offer(ev) is not OFFER_OK:
                            # the replay itself overflowed the coalescing
                            # buffer (or was fault-dropped): this stream
                            # would be lossy FROM BIRTH — refuse it; the
                            # client relists (410 path)
                            self.watch_expired_total += 1
                            raise Expired(
                                f"rv {from_rv} replay overflowed the "
                                "watch buffer; relist"
                            )
            with w._mu:
                # pin the dedup horizons to the commit the registration
                # is consistent with: backlog stragglers at or below it
                # were covered by the replay (or predate a from-now
                # watch) and must not be re-delivered
                w._pin_locked(self._rv)
            self._watchers.setdefault(kind, []).append(w)
            return w

    def _drop_watch(self, w: Watch) -> None:
        with self._rv_lock:
            for ws in self._watchers.values():
                if w in ws:
                    ws.remove(w)
                    break
            with w._mu:
                self._watch_coalesced_closed += w.coalesced
                w.coalesced = 0

    def dispatch_depth(self) -> int:
        """Committed-but-undelivered watch events queued at the shard
        fan-out threads — the store-side overload signal the adaptive
        APF controller reads (a deep backlog means watchers cannot keep
        up with the commit rate, so admission should shed)."""
        total = 0
        for shard in self._shards:
            with shard._dispatch_cv:
                total += sum(
                    len(evs) for _, evs in shard._dispatch_backlog
                )
        return total

    def watch_stats(self) -> Dict[str, int]:
        """Fan-out observability snapshot: deepest per-watcher pending
        backlog, fan-out dispatch backlog, total compacted events,
        expiries, and (legacy) destructive terminations — mirrored into
        the scheduler Registry as scheduler_watch_* gauges every
        cycle."""
        dispatch_depth = self.dispatch_depth()
        with self._rv_lock:
            depth = 0
            coalesced = self._watch_coalesced_closed
            for ws in self._watchers.values():
                for w in ws:
                    with w._mu:
                        depth = max(depth, len(w._pending))
                        coalesced += w.coalesced
            return {
                "watch_queue_depth": depth,
                "watch_dispatch_depth": dispatch_depth,
                "watch_coalesced_total": coalesced,
                "watch_expired_total": self.watch_expired_total,
                "watchers_terminated": self.watchers_terminated,
            }

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Graceful shutdown: drain every shard's watch-dispatch backlog
        (pending committed batches reach their watchers), then flush AND
        fsync every shard journal before returning — under
        ``journal_sync="interval"`` the final dirty group-commit batch
        would otherwise sit in the userspace buffer and die with the
        process.  The store stops journaling afterwards; reads keep
        working (tests inspect closed stores)."""
        import os

        deadline = time.monotonic() + timeout
        for shard in self._shards:
            with shard._dispatch_cv:
                while (
                    (shard._dispatch_backlog or shard._dispatch_inflight)
                    and time.monotonic() < deadline
                ):
                    shard._dispatch_cv.wait(0.05)
        for shard in self._shards:
            with shard._lock:
                j, shard._journal = shard._journal, None
                shard._journal_dirty = False
            if j is not None:
                try:
                    j.flush()
                    os.fsync(j.fileno())
                    j.close()
                except (OSError, ValueError):
                    logging.getLogger(__name__).exception(
                        "journal close flush failed; tail durability "
                        "degraded"
                    )

    def state_fingerprint(self) -> Dict[str, Any]:
        """A stable, comparison-friendly serialization of the full
        committed state: store rv plus (kind, key) -> (rv, wire(obj)),
        merged across shards (shard topology is invisible — a 1-shard
        and an 8-shard store holding the same objects fingerprint
        identically).  Two stores with equal fingerprints hold
        bit-identical state — the chaos suite compares snapshot+suffix
        recovery against a full-replay oracle with this."""
        from . import wire

        with self._rv_lock:
            merged: Dict[str, Dict[str, tuple]] = {}
            for shard in self._shards:
                for kind, objs in shard._objects.items():
                    if not objs:
                        continue
                    out = merged.setdefault(kind, {})
                    for key, obj in objs.items():
                        out[key] = (
                            shard._versions[kind][key], wire.to_wire(obj)
                        )
            return {
                "rv": self._rv,
                "objects": {
                    kind: dict(sorted(entries.items()))
                    for kind, entries in sorted(merged.items())
                },
            }

    # -- convenience -------------------------------------------------------

    @property
    def resource_version(self) -> int:
        with self._rv_lock:
            return self._rv


def _watch_dispatch_loop(store_ref: "weakref.ref[Store]", sid: int) -> None:
    """One shard's fan-out worker: drains that shard's dispatch backlog
    and delivers each committed batch to its watchers off every store
    lock.

    Holds the store only through a weakref between iterations, so an
    abandoned store's dispatchers exit instead of leaking polling
    threads per Store (tests construct thousands).  Fault-schedule
    exceptions escaping a delivery are contained — a poisoned offer must
    not take the shard's fan-out path down (and the handoff path
    restarts the thread if something interpreter-grade does)."""
    while True:
        store = store_ref()
        if store is None:
            return
        shard = store._shards[sid]
        batch = None
        # deadline-bounded predicate loop: doze until a batch arrives,
        # re-checking the backlog under the SAME acquisition after every
        # wakeup (graftlint atomicity cv-discipline), but still fall out
        # after ~0.2 s so the strong store/shard refs drop and an
        # abandoned store can be collected
        doze = time.monotonic() + 0.2
        with shard._dispatch_cv:
            while not shard._dispatch_backlog:
                remaining = doze - time.monotonic()
                if remaining <= 0:
                    break
                shard._dispatch_cv.wait(remaining)
            if shard._dispatch_backlog:
                batch = shard._dispatch_backlog.popleft()
                # close() waits for backlog-empty AND not-inflight, so a
                # batch mid-fan-out still blocks a graceful shutdown
                shard._dispatch_inflight = True  # graftlint: disable=obligations -- armed only when a batch popped; the fan-out finally below clears it under the same cv (the batch-is-None correlation is beyond the engine)
                _ledger.push("dispatch_inflight", id(shard))
        if batch is not None:
            try:
                store._fan_out(*batch)
            except Exception:  # noqa: BLE001 — delivery containment
                logging.getLogger(__name__).exception(
                    "watch fan-out batch failed; continuing"
                )
            finally:
                with shard._dispatch_cv:
                    shard._dispatch_inflight = False
                    _ledger.pop("dispatch_inflight", id(shard))
                    shard._dispatch_cv.notify_all()
        # drop the strong references before sleeping so GC can collect
        # an otherwise-abandoned store
        store = None
        shard = None
        batch = None
