"""Admission: the mutating → validating plugin chain on API writes.

Reference: apiserver/pkg/admission ({chain,interfaces}.go) — every
create/update runs mutators (defaulting) then validators (reject) before
the storage commit.  Ours is a chain of plain callables installed on the
Store; the built-in set covers the defaulting/validation the scheduler
stack depends on (the slice of pkg/registry/core/pod/strategy.go and
pkg/apis/core/validation that would otherwise let malformed objects
poison batch encodes).
"""

from __future__ import annotations

from typing import Any, Callable, List

from . import types as api


class AdmissionError(ValueError):
    """A validating plugin rejected the write (HTTP 400/422 family)."""


Mutator = Callable[[Any, str], None]    # (obj, operation) — edit in place
Validator = Callable[[Any, str], None]  # raise AdmissionError to reject


class AdmissionChain:
    def __init__(self):
        self.mutators: List[Mutator] = []
        self.validators: List[Validator] = []

    def register_mutator(self, fn: Mutator) -> None:
        self.mutators.append(fn)

    def register_validator(self, fn: Validator) -> None:
        self.validators.append(fn)

    def admit(self, obj: Any, operation: str) -> Any:
        """Run the chain (mutate, then validate).  Raises AdmissionError
        on rejection; returns the (mutated) object."""
        for m in self.mutators:
            m(obj, operation)
        for v in self.validators:
            v(obj, operation)
        return obj


# -- built-in plugins -------------------------------------------------------


def default_pod(obj: Any, operation: str) -> None:
    """Pod defaulting (strategy.PrepareForCreate slice): ensure at least
    one container and a restart policy."""
    if not isinstance(obj, api.Pod):
        return
    if not obj.spec.containers:
        obj.spec.containers = [api.Container()]
    if not obj.spec.restart_policy:
        obj.spec.restart_policy = "Always"


def validate_meta(obj: Any, operation: str) -> None:
    meta = getattr(obj, "meta", None)
    if meta is None or not meta.name:
        raise AdmissionError("metadata.name is required")
    if any(c.isspace() or c == "/" for c in meta.name):
        raise AdmissionError(f"invalid name {meta.name!r}")


def validate_pod(obj: Any, operation: str) -> None:
    """The validation slice that protects the scheduler: non-negative
    requests, sane priority/gang fields, known spread/affinity enums
    (pkg/apis/core/validation ValidatePodSpec reduced)."""
    if not isinstance(obj, api.Pod):
        return
    for c in obj.spec.containers + obj.spec.init_containers:
        for k, v in c.requests.items():
            if v < 0:
                raise AdmissionError(f"negative request {k}={v}")
    if obj.spec.preemption_policy not in ("PreemptLowerPriority", "Never"):
        raise AdmissionError(
            f"invalid preemptionPolicy {obj.spec.preemption_policy!r}"
        )
    gsize = obj.spec.scheduling_group_size
    if gsize is not None and gsize < 1:
        raise AdmissionError(f"schedulingGroupSize must be >= 1, got {gsize}")
    if gsize and not obj.spec.scheduling_group:
        raise AdmissionError("schedulingGroupSize set without schedulingGroup")
    for con in obj.spec.topology_spread_constraints:
        if con.max_skew < 1:
            raise AdmissionError(f"maxSkew must be >= 1, got {con.max_skew}")
        if con.when_unsatisfiable not in ("DoNotSchedule", "ScheduleAnyway"):
            raise AdmissionError(
                f"invalid whenUnsatisfiable {con.when_unsatisfiable!r}"
            )


def validate_node(obj: Any, operation: str) -> None:
    if not isinstance(obj, api.Node):
        return
    for k, v in obj.status.allocatable.items():
        if v < 0:
            raise AdmissionError(f"negative allocatable {k}={v}")
    for t in obj.spec.taints:
        if t.effect not in api.TAINT_EFFECTS:
            raise AdmissionError(f"invalid taint effect {t.effect!r}")


def default_chain() -> AdmissionChain:
    chain = AdmissionChain()
    chain.register_mutator(default_pod)
    chain.register_validator(validate_meta)
    chain.register_validator(validate_pod)
    chain.register_validator(validate_node)
    return chain
