"""Admission: the mutating → validating plugin chain on API writes.

Reference: apiserver/pkg/admission ({chain,interfaces}.go) — every
create/update runs mutators (defaulting) then validators (reject) before
the storage commit.  Ours is a chain of plain callables installed on the
Store; the built-in set covers the defaulting/validation the scheduler
stack depends on (the slice of pkg/registry/core/pod/strategy.go and
pkg/apis/core/validation that would otherwise let malformed objects
poison batch encodes).
"""

from __future__ import annotations

from typing import Any, Callable, List

from . import types as api


class AdmissionError(ValueError):
    """A validating plugin rejected the write (HTTP 400/422 family)."""


Mutator = Callable[[Any, str], None]    # (obj, operation) — edit in place
Validator = Callable[[Any, str], None]  # raise AdmissionError to reject


class AdmissionChain:
    def __init__(self):
        self.mutators: List[Mutator] = []
        self.validators: List[Validator] = []
        # set by the Store that owns this chain: plugins marked
        # `wants_store` receive it (the reference's admission plugins
        # get informers/clients via plugin initializers —
        # apiserver/pkg/admission/initializer)
        self.store = None

    def register_mutator(self, fn: Mutator) -> None:
        self.mutators.append(fn)

    def register_validator(self, fn: Validator) -> None:
        self.validators.append(fn)

    def admit(self, obj: Any, operation: str) -> Any:
        """Run the chain (mutate, then validate).  Raises AdmissionError
        on rejection; returns the (mutated) object."""
        for m in self.mutators:
            if getattr(m, "wants_store", False):
                m(obj, operation, self.store)
            else:
                m(obj, operation)
        for v in self.validators:
            if getattr(v, "wants_store", False):
                v(obj, operation, self.store)
            else:
                v(obj, operation)
        return obj


# -- built-in plugins -------------------------------------------------------


def default_pod(obj: Any, operation: str) -> None:
    """Pod defaulting (strategy.PrepareForCreate slice): ensure at least
    one container and a restart policy."""
    if not isinstance(obj, api.Pod):
        return
    if not obj.spec.containers:
        obj.spec.containers = [api.Container()]
    if not obj.spec.restart_policy:
        obj.spec.restart_policy = "Always"


def validate_meta(obj: Any, operation: str) -> None:
    meta = getattr(obj, "meta", None)
    if meta is None or not meta.name:
        raise AdmissionError("metadata.name is required")
    if any(c.isspace() or c == "/" for c in meta.name):
        raise AdmissionError(f"invalid name {meta.name!r}")


def validate_pod(obj: Any, operation: str) -> None:
    """The validation slice that protects the scheduler: non-negative
    requests, sane priority/gang fields, known spread/affinity enums
    (pkg/apis/core/validation ValidatePodSpec reduced)."""
    if not isinstance(obj, api.Pod):
        return
    for c in obj.spec.containers + obj.spec.init_containers:
        for k, v in c.requests.items():
            if v < 0:
                raise AdmissionError(f"negative request {k}={v}")
    if obj.spec.preemption_policy not in ("PreemptLowerPriority", "Never"):
        raise AdmissionError(
            f"invalid preemptionPolicy {obj.spec.preemption_policy!r}"
        )
    gsize = obj.spec.scheduling_group_size
    if gsize is not None and gsize < 1:
        raise AdmissionError(f"schedulingGroupSize must be >= 1, got {gsize}")
    if gsize and not obj.spec.scheduling_group:
        raise AdmissionError("schedulingGroupSize set without schedulingGroup")
    for con in obj.spec.topology_spread_constraints:
        if con.max_skew < 1:
            raise AdmissionError(f"maxSkew must be >= 1, got {con.max_skew}")
        if con.when_unsatisfiable not in ("DoNotSchedule", "ScheduleAnyway"):
            raise AdmissionError(
                f"invalid whenUnsatisfiable {con.when_unsatisfiable!r}"
            )


def default_service(obj: Any, operation: str, store=None) -> None:
    """ClusterIP allocation (the apiserver Service REST strategy's
    allocator, pkg/registry/core/service/ipallocator): a deterministic
    hash into 10.96.0.0/12, linear-probed against the Services already
    stored so two names hashing together never share a VIP (the bitmap
    allocator's uniqueness guarantee).  "None" (headless) and explicit
    IPs pass through."""
    if not isinstance(obj, api.Service):
        return
    if obj.spec.type == "ExternalName" or obj.spec.cluster_ip:
        return
    if operation == "CREATE":
        import zlib

        used = set()
        if store is not None:
            services, _ = store.list("Service")
            used = {s.spec.cluster_ip for s in services if s.spec.cluster_ip}
        space = (1 << 20) - 2  # /12 host space, avoiding .0.0.0
        h = zlib.crc32(
            f"{obj.meta.namespace}/{obj.meta.name}".encode()
        ) % space + 1
        for _ in range(space):
            ip = f"10.{96 + (h >> 16)}.{(h >> 8) & 0xFF}.{h & 0xFF}"
            if ip not in used:
                obj.spec.cluster_ip = ip
                return
            h = h % space + 1
        raise AdmissionError("cluster IP space exhausted")


default_service.wants_store = True


def default_secret(obj: Any, operation: str) -> None:
    """stringData is WRITE-ONLY (core/v1 Secret docs): fold it into
    data base64-encoded at admission and clear it, so readers always
    find secret.data[...] and plaintext never persists in the journal
    under a side field."""
    if not isinstance(obj, api.Secret) or not obj.string_data:
        return
    import base64

    for k, v in obj.string_data.items():
        obj.data[k] = base64.b64encode(v.encode()).decode()
    obj.string_data = {}


def validate_service(obj: Any, operation: str) -> None:
    if not isinstance(obj, api.Service):
        return
    if obj.spec.type == "ExternalName":
        if not obj.spec.external_name:
            raise AdmissionError("externalName required for ExternalName type")
        return
    if not obj.spec.ports:
        raise AdmissionError("service must declare at least one port")
    seen = set()
    for p in obj.spec.ports:
        if not (0 < p.port < 65536):
            raise AdmissionError(f"invalid service port {p.port}")
        if (p.name, p.protocol, p.port) in seen:
            raise AdmissionError(f"duplicate service port {p.port}")
        seen.add((p.name, p.protocol, p.port))
    if len(obj.spec.ports) > 1 and any(not p.name for p in obj.spec.ports):
        raise AdmissionError("multi-port services require port names")


def validate_node(obj: Any, operation: str) -> None:
    if not isinstance(obj, api.Node):
        return
    for k, v in obj.status.allocatable.items():
        if v < 0:
            raise AdmissionError(f"negative allocatable {k}={v}")
    for t in obj.spec.taints:
        if t.effect not in api.TAINT_EFFECTS:
            raise AdmissionError(f"invalid taint effect {t.effect!r}")


def default_chain() -> AdmissionChain:
    chain = AdmissionChain()
    chain.register_mutator(default_pod)
    chain.register_mutator(default_service)
    chain.register_mutator(default_secret)
    # serviceaccount admission (plugin/pkg/admission/serviceaccount)
    from ..controllers.serviceaccount import default_service_account

    chain.register_mutator(default_service_account)
    chain.register_validator(validate_meta)
    chain.register_validator(validate_pod)
    chain.register_validator(validate_node)
    chain.register_validator(validate_service)
    # quota enforcement (plugin/pkg/admission/resourcequota)
    from ..controllers.resourcequota import quota_validator

    chain.register_validator(quota_validator)
    # CRD schema validation (apiextensions structural schemas)
    from .crd import validate_crd, validate_custom_resource

    chain.register_validator(validate_crd)
    chain.register_validator(validate_custom_resource)
    # dynamic admission: webhook callouts + expression policies
    # (admissionregistration.k8s.io; mutating hooks run LAST among
    # mutators, validating hooks/policies last among validators — the
    # reference's chain position, server/config.go:983)
    from .webhooks import (
        mutating_webhooks,
        validate_policy_object,
        validating_policies,
        validating_webhooks,
    )

    chain.register_mutator(mutating_webhooks)
    chain.register_validator(validate_policy_object)
    chain.register_validator(validating_webhooks)
    chain.register_validator(validating_policies)
    return chain
