"""API Priority & Fairness, reduced to its load-bearing core.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol
(apf_controller.go, apf_filter.go; wired into the handler chain at
server/config.go:990-996).  The reference implementation is a
config-driven controller reconciling FlowSchema/PriorityLevel objects
into fair-queuing dispatchers (1,128 LoC of shuffle-sharding).  What
that machinery BUYS an apiserver is: (1) requests are classified into
priority levels, (2) each level has its own concurrency seats and a
bounded FIFO queue, (3) when a level's queue is full new arrivals are
shed with 429 + Retry-After, so (4) a flood in one level cannot starve
another level's traffic.  This module provides exactly those four
properties with static levels — the config-object dance is not what
protects the store.

  exempt         healthz/readyz + system:masters      (never queued)
  system         system:* users/groups (schedulers, controllers, nodes)
  workload-high  authenticated non-system users
  catch-all      anonymous + everything else

Watches hold a seat for their (long) lifetime in the reference too;
here they are classified but acquire with a short timeout so a full
level sheds them quickly instead of hanging the handler thread.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import auth as authmod


class PriorityLevel:
    """One level's seats + bounded waiting room (apf_filter.go's
    queueSet reduced to a single FIFO-ish queue per level)."""

    def __init__(self, name: str, seats: int, queue_limit: int):
        self.name = name
        self.seats = seats
        self.queue_limit = queue_limit
        self.in_flight = 0
        self.queued = 0
        self.rejected_total = 0
        self.dispatched_total = 0
        self._cond = threading.Condition()

    def acquire(self, timeout: float) -> bool:
        """Take a seat, waiting up to `timeout` in the queue; False =
        shed (queue full or wait expired) — reply 429."""
        with self._cond:
            if self.in_flight < self.seats:
                self.in_flight += 1
                self.dispatched_total += 1
                return True
            if self.queued >= self.queue_limit:
                self.rejected_total += 1
                return False
            self.queued += 1
            deadline = time.monotonic() + timeout
            try:
                while self.in_flight >= self.seats:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.rejected_total += 1
                        return False
                    self._cond.wait(remaining)
                self.in_flight += 1
                self.dispatched_total += 1
                return True
            finally:
                self.queued -= 1

    def release(self) -> None:
        with self._cond:
            self.in_flight -= 1
            self._cond.notify()


@dataclass
class FlowSchema:
    """Classification rule: first match wins (FlowSchema precedence)."""

    name: str
    level: str
    users: Tuple[str, ...] = ()     # exact names; () = any
    groups: Tuple[str, ...] = ()    # any-of; () = any
    verbs: Tuple[str, ...] = ()     # () = any

    def matches(self, subject: authmod.Subject, verb: str) -> bool:
        if self.users and subject.name not in self.users:
            return False
        if self.groups and not set(self.groups) & set(subject.groups):
            return False
        if self.verbs and verb not in self.verbs:
            return False
        return True


DEFAULT_LEVELS = {
    # seats sized like the reference defaults' spirit: system traffic
    # gets guaranteed headroom, the catch-all gets a small slice
    "system": (16, 128),
    "workload-high": (16, 128),
    "catch-all": (4, 16),
}

DEFAULT_SCHEMAS = [
    FlowSchema("system-leader-election", "system", groups=("system:masters",)),
    FlowSchema("system-components", "system",
               groups=("system:schedulers", "system:controllers",
                       "system:nodes")),
    FlowSchema("workload-high", "workload-high",
               groups=("system:authenticated",)),
    FlowSchema("catch-all", "catch-all"),
]


class APFGate:
    """The filter the server calls around every request
    (apf_filter.go Handle): classify -> acquire -> handle -> release."""

    def __init__(
        self,
        levels: Optional[Dict[str, Tuple[int, int]]] = None,
        schemas: Optional[List[FlowSchema]] = None,
        queue_wait_s: float = 5.0,
    ):
        self.levels = {
            name: PriorityLevel(name, seats, qlen)
            for name, (seats, qlen) in (levels or DEFAULT_LEVELS).items()
        }
        self.schemas = list(schemas or DEFAULT_SCHEMAS)
        self.queue_wait_s = queue_wait_s

    def classify(self, subject: authmod.Subject, verb: str) -> PriorityLevel:
        for schema in self.schemas:
            if schema.matches(subject, verb) and schema.level in self.levels:
                return self.levels[schema.level]
        return self.levels["catch-all"]

    def acquire(
        self, subject: authmod.Subject, verb: str
    ) -> Optional[PriorityLevel]:
        """Seat for this request, or None → reply 429."""
        level = self.classify(subject, verb)
        if level.acquire(self.queue_wait_s):
            return level
        return None

    def metrics(self) -> str:
        """Prometheus text exposition of per-level state (the reference's
        apiserver_flowcontrol_* series reduced)."""
        lines = [
            "# TYPE apiserver_flowcontrol_current_inqueue_requests gauge",
        ]
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_inqueue_requests"
                f'{{priority_level="{lv.name}"}} {lv.queued}'
            )
        lines.append(
            "# TYPE apiserver_flowcontrol_current_executing_requests gauge"
        )
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_executing_requests"
                f'{{priority_level="{lv.name}"}} {lv.in_flight}'
            )
        lines.append("# TYPE apiserver_flowcontrol_rejected_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_rejected_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.rejected_total}'
            )
        lines.append("# TYPE apiserver_flowcontrol_dispatched_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_dispatched_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.dispatched_total}'
            )
        return "\n".join(lines) + "\n"
