"""API Priority & Fairness, reduced to its load-bearing core.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol
(apf_controller.go, apf_filter.go; wired into the handler chain at
server/config.go:990-996).  The reference implementation is a
config-driven controller reconciling FlowSchema/PriorityLevel objects
into fair-queuing dispatchers (1,128 LoC of shuffle-sharding).  What
that machinery BUYS an apiserver is: (1) requests are classified into
priority levels, (2) each level has its own concurrency seats and a
bounded FIFO queue, (3) when a level's queue is full new arrivals are
shed with 429 + Retry-After, so (4) a flood in one level cannot starve
another level's traffic.  This module provides exactly those four
properties with static levels — the config-object dance is not what
protects the store.

  exempt         healthz/readyz + system:masters      (never queued)
  system         system:* users/groups (schedulers, controllers, nodes)
  workload-high  authenticated non-system users
  catch-all      anonymous + everything else

Watches hold a seat for their (long) lifetime in the reference too;
here they are classified but acquire with a short timeout so a full
level sheds them quickly instead of hanging the handler thread.

Dispatch discipline (one gate-wide lock, not per-level ones):

  * FIFO within a level — a fresh arrival never takes a seat while the
    same level has queued waiters (no barging);
  * priority across levels — every freed seat re-runs a dispatch scan
    in level-declaration order (system first), so a higher-priority
    waiter claims capacity before any lower level's arrival;
  * borrow DOWNWARD only — a higher-priority level out of its own
    seats may execute on a lower level's idle effective capacity, but
    never the reverse: a catch-all flood can never consume system
    seats (the isolation property the flood tests pin).

On top of the static knobs sits :class:`AdaptiveAPF`: the scheduler's
OverloadController level and the store's watch/dispatch depth feed a
pressure ladder that shrinks every non-system level's effective seats
and queue limits under overload (halving per pressure step) and
restores the configured values with hysteresis — the serving-plane
mirror of the solve side's shed ladder.  Load-shed responses carry a
Retry-After that widens with pressure (``retry_after_s``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import auth as authmod
from ..analysis import ledger as _ledger
from ..testing import faults


class _Ticket:
    """One queued request: granted under the gate lock by the dispatch
    scan, then observed by its waiting thread."""

    __slots__ = ("granted", "donor")

    def __init__(self):
        self.granted = False
        self.donor: Optional["PriorityLevel"] = None


class Seat:
    """A held admission: release() returns the capacity to whichever
    level lent it (the request's own level, or a lower-priority donor
    on the borrow-downward path)."""

    __slots__ = ("_gate", "level", "donor", "_released")

    def __init__(self, gate: "APFGate", level: "PriorityLevel",
                 donor: "PriorityLevel"):
        self._gate = gate
        self.level = level
        self.donor = donor
        self._released = False
        _ledger.acquire("seat", id(self))

    # compat: callers that logged the old PriorityLevel return value's
    # name keep working
    @property
    def name(self) -> str:
        return self.level.name

    def release(self) -> None:
        self._gate._release(self)


class PriorityLevel:
    """One level's seats + bounded FIFO waiting room (apf_filter.go's
    queueSet reduced to one queue per level).  All mutable state is
    guarded by the owning gate's ``_cond`` — the level itself holds no
    lock (single-lock dispatch is what makes cross-level fairness
    decidable atomically)."""

    def __init__(self, name: str, seats: int, queue_limit: int):
        self.name = name
        self.seats = seats                    # configured
        self.queue_limit = queue_limit        # configured
        self.seats_effective = seats          # adaptive (<= seats)
        self.queue_limit_effective = queue_limit
        self.rank = 0                         # 0 = highest priority
        self.in_flight = 0       # requests of THIS level executing
        self.seats_used = 0      # capacity charged here (own + lent)
        self.rejected_total = 0
        self.dispatched_total = 0
        self._waiters: deque = deque()

    @property
    def queued(self) -> int:
        return len(self._waiters)


@dataclass
class FlowSchema:
    """Classification rule: first match wins (FlowSchema precedence)."""

    name: str
    level: str
    users: Tuple[str, ...] = ()     # exact names; () = any
    groups: Tuple[str, ...] = ()    # any-of; () = any
    verbs: Tuple[str, ...] = ()     # () = any

    def matches(self, subject: authmod.Subject, verb: str) -> bool:
        if self.users and subject.name not in self.users:
            return False
        if self.groups and not set(self.groups) & set(subject.groups):
            return False
        if self.verbs and verb not in self.verbs:
            return False
        return True


DEFAULT_LEVELS = {
    # seats sized like the reference defaults' spirit: system traffic
    # gets guaranteed headroom, the catch-all gets a small slice.
    # Declaration order IS priority order (system highest).
    "system": (16, 128),
    "workload-high": (16, 128),
    "catch-all": (4, 16),
}

DEFAULT_SCHEMAS = [
    FlowSchema("system-leader-election", "system", groups=("system:masters",)),
    FlowSchema("system-components", "system",
               groups=("system:schedulers", "system:controllers",
                       "system:nodes")),
    FlowSchema("workload-high", "workload-high",
               groups=("system:authenticated",)),
    FlowSchema("catch-all", "catch-all"),
]

_LEVEL_KEYS = {"seats", "queueLimit"}


def levels_from_config(doc: dict) -> Dict[str, Tuple[int, int]]:
    """Per-level seat/queue knobs from a config mapping — the
    fleet-scale serving path's tuning surface (the seats were
    compile-time constants before; thousands of informers through one
    apiserver need per-deployment sizing).

    Shape: ``{level: {"seats": int, "queueLimit": int}}``.  Levels merge
    ONTO :data:`DEFAULT_LEVELS`, so a document tuning one level keeps
    the defaults for the rest; new level names are allowed (schemas must
    route to them explicitly).  Validated: unknown per-level keys are
    rejected, ``seats`` must be >= 1 (a 0-seat level deadlocks every
    request routed to it), ``queueLimit`` >= 0, and the ``catch-all``
    level cannot be removed (classification falls back to it)."""
    levels: Dict[str, Tuple[int, int]] = dict(DEFAULT_LEVELS)
    for name, spec in (doc or {}).items():
        if not isinstance(spec, dict):
            raise ValueError(
                f"apfLevels[{name!r}] must be a mapping with "
                f"{sorted(_LEVEL_KEYS)}"
            )
        unknown = set(spec) - _LEVEL_KEYS
        if unknown:
            raise ValueError(
                f"apfLevels[{name!r}]: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_LEVEL_KEYS)})"
            )
        cur = levels.get(name, (0, 0))
        seats = int(spec.get("seats", cur[0]))
        qlen = int(spec.get("queueLimit", cur[1]))
        if seats < 1:
            raise ValueError(
                f"apfLevels[{name!r}]: seats must be >= 1 (a 0-seat "
                "level rejects every request routed to it)"
            )
        if qlen < 0:
            raise ValueError(f"apfLevels[{name!r}]: queueLimit must be >= 0")
        levels[name] = (seats, qlen)
    if "catch-all" not in levels:
        raise ValueError("apfLevels must keep the catch-all level")
    return levels


class APFGate:
    """The filter the server calls around every request
    (apf_filter.go Handle): classify -> acquire -> handle -> release.

    One lock for the whole gate: every grant decision (fresh arrival,
    freed seat, pressure change) runs the same priority-ordered FIFO
    dispatch scan, so fairness holds atomically across levels."""

    GUARDED_FIELDS = {
        "pressure": "_cond",
    }

    def __init__(
        self,
        levels: Optional[Dict[str, Tuple[int, int]]] = None,
        schemas: Optional[List[FlowSchema]] = None,
        queue_wait_s: float = 5.0,
    ):
        self._cond = threading.Condition()
        self.levels = {
            name: PriorityLevel(name, seats, qlen)
            for name, (seats, qlen) in (levels or DEFAULT_LEVELS).items()
        }
        for rank, lv in enumerate(self.levels.values()):
            lv.rank = rank
        self._by_rank = sorted(self.levels.values(), key=lambda l: l.rank)
        self.schemas = list(schemas or DEFAULT_SCHEMAS)
        self.queue_wait_s = queue_wait_s
        self.pressure = 0

    @classmethod
    def from_config(cls, source) -> "APFGate":
        """Build a gate from a config document: a dict, a YAML string,
        or a YAML file path.  Top-level keys: ``apfLevels`` (per-level
        seat/queue knobs, see :func:`levels_from_config`) and
        ``queueWaitSeconds``; unknown keys are rejected (the strict
        decoding posture the scheduler config takes)."""
        import os

        if isinstance(source, dict):
            doc = source
        else:
            import yaml

            text = source
            if isinstance(source, str) and os.path.exists(source):
                with open(source) as f:
                    text = f.read()
            doc = yaml.safe_load(text) or {}
        unknown = set(doc) - {"apfLevels", "queueWaitSeconds"}
        if unknown:
            raise ValueError(
                f"unknown APF configuration fields: {sorted(unknown)}"
            )
        return cls(
            levels=levels_from_config(doc.get("apfLevels")),
            queue_wait_s=float(doc.get("queueWaitSeconds", 5.0)),
        )

    def classify(self, subject: authmod.Subject, verb: str) -> PriorityLevel:
        for schema in self.schemas:
            if schema.matches(subject, verb) and schema.level in self.levels:
                return self.levels[schema.level]
        return self.levels["catch-all"]

    # -- dispatch core (all *_locked: caller holds self._cond) -----------

    def _find_capacity_locked(
        self, level: PriorityLevel
    ) -> Optional[PriorityLevel]:
        """The level that will lend a seat to `level`, or None.  Own
        effective capacity first; then borrow DOWNWARD from a
        lower-priority level with idle effective seats and no waiters
        of its own.  Never upward — lower levels cannot touch
        higher-priority capacity."""
        if level.seats_used < level.seats_effective:
            return level
        for donor in self._by_rank[level.rank + 1:]:
            if (
                donor.seats_used < donor.seats_effective
                and not donor._waiters
            ):
                return donor
        return None

    def _grant_locked(
        self, level: PriorityLevel, donor: PriorityLevel
    ) -> None:
        donor.seats_used += 1
        level.in_flight += 1
        level.dispatched_total += 1

    def _dispatch_locked(self) -> bool:
        """Serve queued waiters while capacity exists: levels in
        priority order, FIFO within each.  Returns True if anything was
        granted (caller must notify_all)."""
        granted = False
        for level in self._by_rank:
            while level._waiters:
                donor = self._find_capacity_locked(level)
                if donor is None:
                    break
                ticket = level._waiters.popleft()
                ticket.granted = True
                ticket.donor = donor
                self._grant_locked(level, donor)
                granted = True
        return granted

    # -- the request path -------------------------------------------------

    def acquire(
        self, subject: authmod.Subject, verb: str
    ) -> Optional[Seat]:
        """Seat for this request, or None → reply 429."""
        level = self.classify(subject, verb)
        faults.fire("apf.admit", level=level.name, verb=verb)
        with self._cond:
            # fresh arrivals never barge past their level's FIFO
            if not level._waiters:
                donor = self._find_capacity_locked(level)
                if donor is not None:
                    self._grant_locked(level, donor)
                    return Seat(self, level, donor)
            if len(level._waiters) >= level.queue_limit_effective:
                level.rejected_total += 1
                return None
            ticket = _Ticket()
            level._waiters.append(ticket)
            deadline = time.monotonic() + self.queue_wait_s
            while not ticket.granted:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            if ticket.granted:
                return Seat(self, level, ticket.donor)
            # timed out: a grant can no longer race in — we hold the lock
            try:
                level._waiters.remove(ticket)
            except ValueError:
                pass
            level.rejected_total += 1
            return None

    def _release(self, seat: Seat) -> None:
        with self._cond:
            if seat._released:
                return
            seat._released = True
            _ledger.discharge("seat", id(seat))
            seat.level.in_flight -= 1
            seat.donor.seats_used -= 1
            if self._dispatch_locked():
                self._cond.notify_all()

    # -- adaptive pressure -------------------------------------------------

    def set_pressure(self, pressure: int) -> None:
        """Apply an overload pressure step: every non-system level's
        effective seats and queue limit halve per step (floor 1 seat /
        0 queue); the system level keeps its full configured seats so
        control traffic (scheduler, kubelets, leader leases) always has
        headroom.  Recovery (pressure falling) restores the configured
        values and re-runs dispatch — capacity that reappears goes to
        the queue heads immediately."""
        pressure = max(0, int(pressure))
        with self._cond:
            if pressure == self.pressure:
                return
            self.pressure = pressure
            for lv in self._by_rank:
                if lv.name == "system" or pressure == 0:
                    lv.seats_effective = lv.seats
                    lv.queue_limit_effective = lv.queue_limit
                else:
                    lv.seats_effective = max(1, lv.seats >> pressure)
                    lv.queue_limit_effective = lv.queue_limit >> pressure
            if self._dispatch_locked():
                self._cond.notify_all()

    def retry_after_s(self) -> float:
        """The Retry-After a 429 should carry: widens with pressure so
        shed clients back off harder the deeper the overload."""
        with self._cond:
            return float(1 << self.pressure)

    def seats_current(self) -> int:
        """Effective seats across all levels (apf_seats_current)."""
        with self._cond:
            return sum(lv.seats_effective for lv in self._by_rank)

    def rejected_total(self) -> int:
        with self._cond:
            return sum(lv.rejected_total for lv in self._by_rank)

    def metrics(self) -> str:
        """Prometheus text exposition of per-level state (the reference's
        apiserver_flowcontrol_* series reduced)."""
        lines = [
            "# TYPE apiserver_flowcontrol_current_inqueue_requests gauge",
        ]
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_inqueue_requests"
                f'{{priority_level="{lv.name}"}} {lv.queued}'
            )
        lines.append(
            "# TYPE apiserver_flowcontrol_current_executing_requests gauge"
        )
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_executing_requests"
                f'{{priority_level="{lv.name}"}} {lv.in_flight}'
            )
        lines.append(
            "# TYPE apiserver_flowcontrol_current_limit_seats gauge"
        )
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_limit_seats"
                f'{{priority_level="{lv.name}"}} {lv.seats_effective}'
            )
        lines.append("# TYPE apiserver_flowcontrol_rejected_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_rejected_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.rejected_total}'
            )
        lines.append("# TYPE apiserver_flowcontrol_dispatched_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_dispatched_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.dispatched_total}'
            )
        return "\n".join(lines) + "\n"


class AdaptiveAPF:
    """The serving-plane shed ladder: overload observations in,
    pressure steps out (mirroring OverloadController's rise-fast /
    recover-slow shape).

    ``note()`` takes the scheduler's overload level (0/1/2) and the
    store's watch/dispatch backlog depths; the raw pressure is the max
    of the overload level and the depth ladder (>= threshold → 1,
    >= 4x threshold → 2).  Rising pressure applies IMMEDIATELY (shed
    now, ask questions later); falling pressure needs ``recover_after``
    consecutive lower observations and then steps down ONE level at a
    time — the hysteresis that keeps a flapping signal from thrashing
    the seat limits."""

    def __init__(
        self,
        gate: APFGate,
        depth_threshold: int = 256,
        recover_after: int = 3,
    ):
        self.gate = gate
        self.depth_threshold = depth_threshold
        self.recover_after = recover_after
        self._level = 0
        self._below = 0
        self._lock = threading.Lock()

    @property
    def level(self) -> int:
        return self._level

    def note(
        self,
        overload_level: int = 0,
        watch_depth: int = 0,
        dispatch_depth: int = 0,
    ) -> int:
        depth = max(int(watch_depth), int(dispatch_depth))
        from_depth = 0
        if depth >= self.depth_threshold:
            from_depth = 1
        if depth >= 4 * self.depth_threshold:
            from_depth = 2
        raw = max(int(overload_level), from_depth)
        with self._lock:
            if raw > self._level:
                self._level = raw
                self._below = 0
            elif raw < self._level:
                self._below += 1
                if self._below >= self.recover_after:
                    self._level -= 1
                    self._below = 0
            else:
                self._below = 0
            level = self._level
        self.gate.set_pressure(level)
        return level
