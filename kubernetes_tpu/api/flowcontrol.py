"""API Priority & Fairness, reduced to its load-bearing core.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol
(apf_controller.go, apf_filter.go; wired into the handler chain at
server/config.go:990-996).  The reference implementation is a
config-driven controller reconciling FlowSchema/PriorityLevel objects
into fair-queuing dispatchers (1,128 LoC of shuffle-sharding).  What
that machinery BUYS an apiserver is: (1) requests are classified into
priority levels, (2) each level has its own concurrency seats and a
bounded FIFO queue, (3) when a level's queue is full new arrivals are
shed with 429 + Retry-After, so (4) a flood in one level cannot starve
another level's traffic.  This module provides exactly those four
properties with static levels — the config-object dance is not what
protects the store.

  exempt         healthz/readyz + system:masters      (never queued)
  system         system:* users/groups (schedulers, controllers, nodes)
  workload-high  authenticated non-system users
  catch-all      anonymous + everything else

Watches hold a seat for their (long) lifetime in the reference too;
here they are classified but acquire with a short timeout so a full
level sheds them quickly instead of hanging the handler thread.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import auth as authmod


class PriorityLevel:
    """One level's seats + bounded waiting room (apf_filter.go's
    queueSet reduced to a single FIFO-ish queue per level)."""

    def __init__(self, name: str, seats: int, queue_limit: int):
        self.name = name
        self.seats = seats
        self.queue_limit = queue_limit
        self.in_flight = 0
        self.queued = 0
        self.rejected_total = 0
        self.dispatched_total = 0
        self._cond = threading.Condition()

    def acquire(self, timeout: float) -> bool:
        """Take a seat, waiting up to `timeout` in the queue; False =
        shed (queue full or wait expired) — reply 429."""
        with self._cond:
            if self.in_flight < self.seats:
                self.in_flight += 1
                self.dispatched_total += 1
                return True
            if self.queued >= self.queue_limit:
                self.rejected_total += 1
                return False
            self.queued += 1
            deadline = time.monotonic() + timeout
            try:
                while self.in_flight >= self.seats:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.rejected_total += 1
                        return False
                    self._cond.wait(remaining)
                self.in_flight += 1
                self.dispatched_total += 1
                return True
            finally:
                self.queued -= 1

    def release(self) -> None:
        with self._cond:
            self.in_flight -= 1
            self._cond.notify()


@dataclass
class FlowSchema:
    """Classification rule: first match wins (FlowSchema precedence)."""

    name: str
    level: str
    users: Tuple[str, ...] = ()     # exact names; () = any
    groups: Tuple[str, ...] = ()    # any-of; () = any
    verbs: Tuple[str, ...] = ()     # () = any

    def matches(self, subject: authmod.Subject, verb: str) -> bool:
        if self.users and subject.name not in self.users:
            return False
        if self.groups and not set(self.groups) & set(subject.groups):
            return False
        if self.verbs and verb not in self.verbs:
            return False
        return True


DEFAULT_LEVELS = {
    # seats sized like the reference defaults' spirit: system traffic
    # gets guaranteed headroom, the catch-all gets a small slice
    "system": (16, 128),
    "workload-high": (16, 128),
    "catch-all": (4, 16),
}

DEFAULT_SCHEMAS = [
    FlowSchema("system-leader-election", "system", groups=("system:masters",)),
    FlowSchema("system-components", "system",
               groups=("system:schedulers", "system:controllers",
                       "system:nodes")),
    FlowSchema("workload-high", "workload-high",
               groups=("system:authenticated",)),
    FlowSchema("catch-all", "catch-all"),
]

_LEVEL_KEYS = {"seats", "queueLimit"}


def levels_from_config(doc: dict) -> Dict[str, Tuple[int, int]]:
    """Per-level seat/queue knobs from a config mapping — the
    fleet-scale serving path's tuning surface (the seats were
    compile-time constants before; thousands of informers through one
    apiserver need per-deployment sizing).

    Shape: ``{level: {"seats": int, "queueLimit": int}}``.  Levels merge
    ONTO :data:`DEFAULT_LEVELS`, so a document tuning one level keeps
    the defaults for the rest; new level names are allowed (schemas must
    route to them explicitly).  Validated: unknown per-level keys are
    rejected, ``seats`` must be >= 1 (a 0-seat level deadlocks every
    request routed to it), ``queueLimit`` >= 0, and the ``catch-all``
    level cannot be removed (classification falls back to it)."""
    levels: Dict[str, Tuple[int, int]] = dict(DEFAULT_LEVELS)
    for name, spec in (doc or {}).items():
        if not isinstance(spec, dict):
            raise ValueError(
                f"apfLevels[{name!r}] must be a mapping with "
                f"{sorted(_LEVEL_KEYS)}"
            )
        unknown = set(spec) - _LEVEL_KEYS
        if unknown:
            raise ValueError(
                f"apfLevels[{name!r}]: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_LEVEL_KEYS)})"
            )
        cur = levels.get(name, (0, 0))
        seats = int(spec.get("seats", cur[0]))
        qlen = int(spec.get("queueLimit", cur[1]))
        if seats < 1:
            raise ValueError(
                f"apfLevels[{name!r}]: seats must be >= 1 (a 0-seat "
                "level rejects every request routed to it)"
            )
        if qlen < 0:
            raise ValueError(f"apfLevels[{name!r}]: queueLimit must be >= 0")
        levels[name] = (seats, qlen)
    if "catch-all" not in levels:
        raise ValueError("apfLevels must keep the catch-all level")
    return levels


class APFGate:
    """The filter the server calls around every request
    (apf_filter.go Handle): classify -> acquire -> handle -> release."""

    def __init__(
        self,
        levels: Optional[Dict[str, Tuple[int, int]]] = None,
        schemas: Optional[List[FlowSchema]] = None,
        queue_wait_s: float = 5.0,
    ):
        self.levels = {
            name: PriorityLevel(name, seats, qlen)
            for name, (seats, qlen) in (levels or DEFAULT_LEVELS).items()
        }
        self.schemas = list(schemas or DEFAULT_SCHEMAS)
        self.queue_wait_s = queue_wait_s

    @classmethod
    def from_config(cls, source) -> "APFGate":
        """Build a gate from a config document: a dict, a YAML string,
        or a YAML file path.  Top-level keys: ``apfLevels`` (per-level
        seat/queue knobs, see :func:`levels_from_config`) and
        ``queueWaitSeconds``; unknown keys are rejected (the strict
        decoding posture the scheduler config takes)."""
        import os

        if isinstance(source, dict):
            doc = source
        else:
            import yaml

            text = source
            if isinstance(source, str) and os.path.exists(source):
                with open(source) as f:
                    text = f.read()
            doc = yaml.safe_load(text) or {}
        unknown = set(doc) - {"apfLevels", "queueWaitSeconds"}
        if unknown:
            raise ValueError(
                f"unknown APF configuration fields: {sorted(unknown)}"
            )
        return cls(
            levels=levels_from_config(doc.get("apfLevels")),
            queue_wait_s=float(doc.get("queueWaitSeconds", 5.0)),
        )

    def classify(self, subject: authmod.Subject, verb: str) -> PriorityLevel:
        for schema in self.schemas:
            if schema.matches(subject, verb) and schema.level in self.levels:
                return self.levels[schema.level]
        return self.levels["catch-all"]

    def acquire(
        self, subject: authmod.Subject, verb: str
    ) -> Optional[PriorityLevel]:
        """Seat for this request, or None → reply 429."""
        level = self.classify(subject, verb)
        if level.acquire(self.queue_wait_s):
            return level
        return None

    def metrics(self) -> str:
        """Prometheus text exposition of per-level state (the reference's
        apiserver_flowcontrol_* series reduced)."""
        lines = [
            "# TYPE apiserver_flowcontrol_current_inqueue_requests gauge",
        ]
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_inqueue_requests"
                f'{{priority_level="{lv.name}"}} {lv.queued}'
            )
        lines.append(
            "# TYPE apiserver_flowcontrol_current_executing_requests gauge"
        )
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_current_executing_requests"
                f'{{priority_level="{lv.name}"}} {lv.in_flight}'
            )
        lines.append("# TYPE apiserver_flowcontrol_rejected_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_rejected_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.rejected_total}'
            )
        lines.append("# TYPE apiserver_flowcontrol_dispatched_requests_total counter")
        for lv in self.levels.values():
            lines.append(
                "apiserver_flowcontrol_dispatched_requests_total"
                f'{{priority_level="{lv.name}"}} {lv.dispatched_total}'
            )
        return "\n".join(lines) + "\n"
