"""Generic dataclass ⇄ JSON codec for api.types objects.

The reference persists every object through a versioned codec into etcd
(storage/etcd3/store.go:106, runtime serializers); this is our
process-boundary serialization: type-tagged JSON with recursive
dataclass walking, decoding against the api.types namespace.  Used by
the store's append-only journal (crash-only durability) and any future
RPC surface.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from . import types as api

_TYPE_KEY = "__t"


def to_wire(obj: Any) -> Any:
    # dynamic kinds (CRD instances): kind travels in the document since
    # there is no dataclass to recover it from
    from .crd import DynamicObject

    if isinstance(obj, DynamicObject):
        return {
            _TYPE_KEY: "DynamicObject",
            "kind": obj.KIND,
            "meta": to_wire(obj.meta),
            "spec": to_wire(obj.spec),
            "status": to_wire(obj.status),
        }
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {_TYPE_KEY: type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = to_wire(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(doc: Any) -> Any:
    if isinstance(doc, dict):
        if _TYPE_KEY in doc:
            name = doc[_TYPE_KEY]
            if name == "DynamicObject":
                from .crd import DynamicObject

                return DynamicObject(
                    doc.get("kind", ""),
                    meta=from_wire(doc.get("meta")),
                    spec=from_wire(doc.get("spec") or {}),
                    status=from_wire(doc.get("status") or {}),
                )
            cls = getattr(api, name, None)
            if cls is None:
                # apiextensions dataclasses live beside, not in, types
                from . import crd as crdmod

                cls = getattr(crdmod, name, None)
            if cls is None or not dataclasses.is_dataclass(cls):
                raise ValueError(f"unknown wire type {name!r}")
            kwargs = {
                k: from_wire(v) for k, v in doc.items() if k != _TYPE_KEY
            }
            # tolerate fields added/removed across versions
            valid = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in kwargs.items() if k in valid})
        return {k: from_wire(v) for k, v in doc.items()}
    if isinstance(doc, list):
        return [from_wire(v) for v in doc]
    return doc
