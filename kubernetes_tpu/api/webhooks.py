"""Dynamic admission: webhook callouts + expression policies.

Reference: the apiserver's mutating/validating webhook plugins
(apiserver/pkg/admission/plugin/webhook — AdmissionReview POSTs with
failurePolicy semantics) and ValidatingAdmissionPolicy
(admission/plugin/policy/validating/plugin.go — CEL expressions over
`object`/`oldObject`).

Webhooks: configurations are API objects; on every matching write the
chain POSTs an AdmissionReview-ish JSON {operation, kind, object} to
the webhook URL.  Mutating responses return {"allowed": true, "patch":
{...}} with an RFC 7386 merge patch (the reference uses JSONPatch; the
merge dialect covers the defaulting/labeling cases a merge patch can
express and is what our PATCH verb already speaks — documented
divergence).  Validating responses return {"allowed": bool,
"status": {"message": ...}}.  failurePolicy=Fail turns call errors into
rejections; Ignore skips them.

Policies: CEL-style boolean expressions compiled to a SAFE evaluator —
the expression is parsed with Python's ast after translating CEL's
&&/||/! operators, and only a whitelisted node set (bool ops,
comparisons, attribute/index access on `object`/`oldObject`, arithmetic,
len/has/startsWith/endsWith/contains/size calls, literals) evaluates;
anything else is rejected at policy-admission time.  No attribute can
reach outside the admitted object's wire document, so a policy cannot
touch the process (the sandboxing property CEL provides the reference).
"""

from __future__ import annotations

import ast
import json
import operator
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from . import types as api
from .admission import AdmissionError

_CACHE_TTL = 0.5


class _Doc:
    """Dot-and-index access over a wire document (CEL's object view)."""

    def __init__(self, doc: Any):
        self._doc = doc

    def get(self, name: str) -> Any:
        if isinstance(self._doc, dict) and name in self._doc:
            return _wrap(self._doc[name])
        raise AdmissionError(f"no such field {name!r}")

    def has(self, name: str) -> bool:
        return isinstance(self._doc, dict) and name in self._doc


def _wrap(v: Any):
    return _Doc(v) if isinstance(v, dict) else v


def _unwrap(v: Any):
    return v._doc if isinstance(v, _Doc) else v


_CMP = {
    ast.Eq: operator.eq, ast.NotEq: operator.ne,
    ast.Lt: operator.lt, ast.LtE: operator.le,
    ast.Gt: operator.gt, ast.GtE: operator.ge,
    ast.In: lambda a, b: a in b, ast.NotIn: lambda a, b: a not in b,
}
_BIN = {
    ast.Add: operator.add, ast.Sub: operator.sub,
    ast.Mult: operator.mul, ast.Div: operator.truediv,
    ast.Mod: operator.mod,
}


def _translate_cel(source: str) -> str:
    """CEL's &&/||/! -> Python's and/or/not, OUTSIDE string literals —
    a naive str.replace would rewrite an operator inside a quoted value
    ('a&&b') and silently change the policy's meaning."""
    out = []
    i, n = 0, len(source)
    quote = None
    while i < n:
        ch = source[i]
        if quote is not None:
            out.append(ch)
            if ch == "\\" and i + 1 < n:
                out.append(source[i + 1])
                i += 2
                continue
            if ch == quote:
                quote = None
            i += 1
            continue
        if ch in "'\"":
            quote = ch
            out.append(ch)
            i += 1
            continue
        if source.startswith("&&", i):
            out.append(" and ")
            i += 2
            continue
        if source.startswith("||", i):
            out.append(" or ")
            i += 2
            continue
        if ch == "!" and not source.startswith("!=", i):
            out.append(" not ")
            i += 1
            continue
        out.append(ch)
        i += 1
    return "".join(out).strip()


class Expression:
    """One compiled policy expression."""

    def __init__(self, source: str):
        self.source = source
        py = _translate_cel(source)
        try:
            tree = ast.parse(py, mode="eval")
        except SyntaxError as e:
            raise AdmissionError(f"policy expression {source!r}: {e}") from None
        self._validate(tree.body)
        self._tree = tree.body

    # -- compile-time whitelist --------------------------------------------

    _ALLOWED = (
        ast.BoolOp, ast.UnaryOp, ast.Compare, ast.BinOp, ast.Attribute,
        ast.Subscript, ast.Name, ast.Constant, ast.Call, ast.And, ast.Or,
        ast.Not, ast.USub, ast.List, ast.Tuple, ast.IfExp,
        *(_CMP.keys()), *(_BIN.keys()),
    )
    _FUNCS = ("len", "size", "has", "startsWith", "endsWith", "contains")

    def _validate(self, node: ast.AST) -> None:
        if not isinstance(node, self._ALLOWED):
            raise AdmissionError(
                f"policy expression {self.source!r}: "
                f"{type(node).__name__} not allowed"
            )
        if isinstance(node, (ast.Attribute, ast.Name)):
            ident = node.attr if isinstance(node, ast.Attribute) else node.id
            if ident.startswith("_"):
                raise AdmissionError(
                    f"policy expression {self.source!r}: "
                    f"identifier {ident!r} not allowed"
                )
        if isinstance(node, ast.Call):
            fn = node.func
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            if name not in self._FUNCS:
                raise AdmissionError(
                    f"policy expression {self.source!r}: "
                    f"call to {name!r} not allowed"
                )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr_context, ast.operator,
                                  ast.boolop, ast.unaryop, ast.cmpop)):
                continue
            self._validate(child)

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, env: Dict[str, Any]) -> bool:
        return bool(_unwrap(self._eval(self._tree, env)))

    def _eval(self, node: ast.AST, env: Dict[str, Any]):
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            raise AdmissionError(
                f"policy expression {self.source!r}: unknown name {node.id!r}"
            )
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value, env)
            if isinstance(base, _Doc):
                return base.get(node.attr)
            raise AdmissionError(
                f"policy expression {self.source!r}: attribute access on "
                f"{type(base).__name__}"
            )
        if isinstance(node, ast.Subscript):
            base = _unwrap(self._eval(node.value, env))
            key = _unwrap(self._eval(node.slice, env))
            try:
                return _wrap(base[key])
            except (KeyError, IndexError, TypeError):
                raise AdmissionError(
                    f"policy expression {self.source!r}: no element {key!r}"
                ) from None
        if isinstance(node, ast.BoolOp):
            if isinstance(node.op, ast.And):
                return all(
                    _unwrap(self._eval(v, env)) for v in node.values
                )
            return any(_unwrap(self._eval(v, env)) for v in node.values)
        if isinstance(node, ast.UnaryOp):
            v = _unwrap(self._eval(node.operand, env))
            return (not v) if isinstance(node.op, ast.Not) else -v
        if isinstance(node, ast.Compare):
            left = _unwrap(self._eval(node.left, env))
            for op, right in zip(node.ops, node.comparators):
                r = _unwrap(self._eval(right, env))
                if not _CMP[type(op)](left, r):
                    return False
                left = r
            return True
        if isinstance(node, ast.BinOp):
            return _BIN[type(node.op)](
                _unwrap(self._eval(node.left, env)),
                _unwrap(self._eval(node.right, env)),
            )
        if isinstance(node, (ast.List, ast.Tuple)):
            return [_unwrap(self._eval(e, env)) for e in node.elts]
        if isinstance(node, ast.IfExp):
            return (
                self._eval(node.body, env)
                if _unwrap(self._eval(node.test, env))
                else self._eval(node.orelse, env)
            )
        if isinstance(node, ast.Call):
            fn = node.func
            args = [self._eval(a, env) for a in node.args]
            if isinstance(fn, ast.Name):
                name = fn.id
                if name in ("len", "size"):
                    return len(_unwrap(args[0]))
                if name == "has":
                    doc, field = args
                    return isinstance(doc, _Doc) and doc.has(_unwrap(field))
            else:  # method style: x.startsWith("p")
                recv = _unwrap(self._eval(fn.value, env))
                name = fn.attr
                if name == "startsWith":
                    return str(recv).startswith(_unwrap(args[0]))
                if name == "endsWith":
                    return str(recv).endswith(_unwrap(args[0]))
                if name == "contains":
                    return _unwrap(args[0]) in recv
                if name in ("len", "size"):
                    return len(recv)
            raise AdmissionError(
                f"policy expression {self.source!r}: bad call"
            )
        raise AdmissionError(
            f"policy expression {self.source!r}: "
            f"{type(node).__name__} unsupported"
        )


_compiled_cache: Dict[str, "Expression"] = {}


def _compiled(source: str) -> "Expression":
    """Compiled-expression cache: policies match every write on the hot
    path; re-parsing per admitted object would tax each Lease heartbeat
    and status update (the reference caches compiled CEL programs)."""
    e = _compiled_cache.get(source)
    if e is None:
        if len(_compiled_cache) >= 1024:
            _compiled_cache.clear()
        e = _compiled_cache[source] = Expression(source)
    return e


def _rule_matches(rules: List[api.WebhookRule], kind: str, op: str) -> bool:
    if not rules:
        return True
    for r in rules:
        if ("*" in r.kinds or kind in r.kinds) and (
            "*" in r.operations or op in r.operations
        ):
            return True
    return False


class _ConfigCache:
    """Per-store TTL cache of the registered configurations (one
    process can host several independent stores — tests, kubemark)."""

    def __init__(self):
        import weakref

        self._by_store = weakref.WeakKeyDictionary()

    def get(self, store) -> Tuple:
        now = time.monotonic()
        entry = self._by_store.get(store)
        if entry is None or now - entry[0] >= _CACHE_TTL:
            entry = (
                now,
                (
                    tuple(store.list("MutatingWebhookConfiguration")[0]),
                    tuple(store.list("ValidatingWebhookConfiguration")[0]),
                    tuple(store.list("ValidatingAdmissionPolicy")[0]),
                ),
            )
            self._by_store[store] = entry
        return entry[1]


_cache = _ConfigCache()


def _configs(store) -> Tuple:
    return _cache.get(store)


def _call_webhook(hook: api.Webhook, review: Dict[str, Any]) -> Dict[str, Any]:
    req = urllib.request.Request(
        hook.url,
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=hook.timeout_seconds) as resp:
        return json.loads(resp.read() or b"{}")


def _review(obj: Any, operation: str) -> Dict[str, Any]:
    from . import wire

    return {
        "operation": operation,
        "kind": getattr(obj, "KIND", ""),
        "object": wire.to_wire(obj),
    }


def _skip(obj: Any) -> bool:
    # admission on the admission machinery itself would recurse/bootstrap
    return getattr(obj, "KIND", "") in (
        "MutatingWebhookConfiguration",
        "ValidatingWebhookConfiguration",
        "ValidatingAdmissionPolicy",
        "Event",
    )


def mutating_webhooks(obj: Any, operation: str, store=None) -> None:
    """Mutator: POST to each matching mutating webhook, apply returned
    merge patches in order (webhook ordering = config name order)."""
    if store is None or _skip(obj):
        return
    configs, _, _ = _configs(store)
    if not configs:
        return
    from . import wire
    from .server import merge_patch

    kind = getattr(obj, "KIND", "")
    doc = None
    for cfg in sorted(configs, key=lambda c: c.meta.name):
        for hook in cfg.webhooks:
            if not _rule_matches(hook.rules, kind, operation):
                continue
            if doc is None:
                doc = wire.to_wire(obj)
            try:
                out = _call_webhook(
                    hook, {"operation": operation, "kind": kind, "object": doc}
                )
            except (urllib.error.URLError, OSError, ValueError) as e:
                if hook.failure_policy == "Fail":
                    raise AdmissionError(
                        f"webhook {hook.name}: {e}"
                    ) from None
                continue
            if not out.get("allowed", True):
                msg = (out.get("status") or {}).get("message", "denied")
                raise AdmissionError(f"webhook {hook.name}: {msg}")
            patch = out.get("patch")
            if patch:
                doc = merge_patch(doc, patch)
    if doc is not None:
        mutated = wire.from_wire(doc)
        fields = (
            obj.__dataclass_fields__
            if hasattr(obj, "__dataclass_fields__")
            else ("meta", "spec", "status")  # DynamicObject
        )
        for f in fields:
            setattr(obj, f, getattr(mutated, f))


mutating_webhooks.wants_store = True


def validating_webhooks(obj: Any, operation: str, store=None) -> None:
    if store is None or _skip(obj):
        return
    _, configs, _ = _configs(store)
    kind = getattr(obj, "KIND", "")
    for cfg in sorted(configs, key=lambda c: c.meta.name):
        for hook in cfg.webhooks:
            if not _rule_matches(hook.rules, kind, operation):
                continue
            try:
                out = _call_webhook(hook, _review(obj, operation))
            except (urllib.error.URLError, OSError, ValueError) as e:
                if hook.failure_policy == "Fail":
                    raise AdmissionError(
                        f"webhook {hook.name}: {e}"
                    ) from None
                continue
            if not out.get("allowed", True):
                msg = (out.get("status") or {}).get("message", "denied")
                raise AdmissionError(f"webhook {hook.name}: {msg}")


validating_webhooks.wants_store = True


def validating_policies(obj: Any, operation: str, store=None) -> None:
    """ValidatingAdmissionPolicy: every matching validation expression
    must evaluate true over the object's wire document."""
    if store is None or _skip(obj):
        return
    _, _, policies = _configs(store)
    if not policies:
        return
    from . import wire

    kind = getattr(obj, "KIND", "")
    env = {"object": _Doc(wire.to_wire(obj)), "true": True, "false": False}
    for policy in sorted(policies, key=lambda p: p.meta.name):
        if not _rule_matches([policy.spec.match], kind, operation):
            continue
        for v in policy.spec.validations:
            expr = _compiled(v.expression)
            ok = False
            try:
                ok = expr.evaluate(env)
            except AdmissionError:
                ok = False  # missing fields fail closed, like CEL errors
            if not ok:
                raise AdmissionError(
                    v.message
                    or f"policy {policy.meta.name}: "
                       f"{v.expression!r} evaluated false"
                )


validating_policies.wants_store = True


def validate_policy_object(obj: Any, operation: str) -> None:
    """Compile expressions at policy-admission time so a bad expression
    is rejected when the POLICY is written, not when workloads are."""
    if isinstance(obj, api.ValidatingAdmissionPolicy):
        for v in obj.spec.validations:
            Expression(v.expression)
