"""CRD-lite: CustomResourceDefinitions registering dynamic kinds at
runtime.

Reference: staging/src/k8s.io/apiextensions-apiserver (62.7k LoC).  The
load-bearing core for an in-process control plane is much smaller than
the reference's aggregation machinery, because our store, informers,
REST server, and watch streams are already kind-agnostic (they key on
the string `obj.KIND`):

  * CustomResourceDefinition — the API object declaring a new kind
    with an openAPI-ish structural schema
    (apiextensions/v1 CustomResourceDefinitionSpec reduced).
  * DynamicObject — the runtime representation of an instance of a
    dynamic kind (unstructured.Unstructured): meta + free-form
    spec/status dicts, serialized by the wire codec so instances
    journal, replay, and stream over REST like built-ins.
  * validate_custom_resource — admission validation of instances
    against their CRD's schema (the structural-schema validation
    pruned to: type, required, minimum/maximum, enum).

The PodGroup used by coscheduling (scheduler/coscheduling.py) is the
proving instance: install_podgroup_crd() + PodGroupDirectory drive gang
sizes from API objects instead of an out-of-band dict.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import types as api
from .admission import AdmissionError


@dataclass
class CRDNames:
    kind: str = ""
    plural: str = ""
    singular: str = ""


@dataclass
class CustomResourceDefinitionSpec:
    group: str = ""
    names: CRDNames = field(default_factory=CRDNames)
    scope: str = "Namespaced"  # Namespaced | Cluster
    # openAPI-ish structural schema for .spec:
    #   {"properties": {"minMember": {"type": "integer", "minimum": 1}},
    #    "required": ["minMember"]}
    schema: Dict[str, Any] = field(default_factory=dict)


@dataclass
class CustomResourceDefinition:
    meta: api.ObjectMeta = field(default_factory=api.ObjectMeta)
    spec: CustomResourceDefinitionSpec = field(
        default_factory=CustomResourceDefinitionSpec
    )

    KIND = "CustomResourceDefinition"


class DynamicObject:
    """An instance of a CRD-declared kind (unstructured.Unstructured).
    KIND is per-instance, so the kind-agnostic store/informers/REST
    machinery treats dynamic kinds exactly like built-ins."""

    def __init__(
        self,
        kind: str,
        meta: Optional[api.ObjectMeta] = None,
        spec: Optional[Dict[str, Any]] = None,
        status: Optional[Dict[str, Any]] = None,
    ):
        self.KIND = kind
        self.meta = meta or api.ObjectMeta()
        self.spec = dict(spec or {})
        self.status = dict(status or {})

    def __repr__(self) -> str:
        return (
            f"DynamicObject({self.KIND!r}, "
            f"{self.meta.namespace}/{self.meta.name})"
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, DynamicObject)
            and self.KIND == other.KIND
            and self.meta == other.meta
            and self.spec == other.spec
            and self.status == other.status
        )


# -- schema validation --------------------------------------------------------

_TYPES = {
    "string": str,
    "integer": int,
    "number": (int, float),
    "boolean": bool,
    "array": list,
    "object": dict,
}


def _validate_value(path: str, value: Any, schema: Dict[str, Any]) -> None:
    typ = schema.get("type")
    if typ:
        py = _TYPES.get(typ)
        if py is None:
            raise AdmissionError(f"{path}: unknown schema type {typ!r}")
        if typ == "integer" and isinstance(value, bool):
            raise AdmissionError(f"{path}: expected integer, got bool")
        if not isinstance(value, py):
            raise AdmissionError(
                f"{path}: expected {typ}, got {type(value).__name__}"
            )
    if "enum" in schema and value not in schema["enum"]:
        raise AdmissionError(
            f"{path}: {value!r} not one of {schema['enum']}"
        )
    if "minimum" in schema and isinstance(value, (int, float)):
        if value < schema["minimum"]:
            raise AdmissionError(
                f"{path}: {value} < minimum {schema['minimum']}"
            )
    if "maximum" in schema and isinstance(value, (int, float)):
        if value > schema["maximum"]:
            raise AdmissionError(
                f"{path}: {value} > maximum {schema['maximum']}"
            )
    if typ == "object" and "properties" in schema and isinstance(value, dict):
        _validate_object(path, value, schema)
    if typ == "array" and "items" in schema and isinstance(value, list):
        for i, item in enumerate(value):
            _validate_value(f"{path}[{i}]", item, schema["items"])


def _validate_object(path: str, doc: Dict[str, Any], schema: Dict[str, Any]) -> None:
    for req in schema.get("required", ()):
        if req not in doc:
            raise AdmissionError(f"{path}.{req}: required field missing")
    for name, sub in (schema.get("properties") or {}).items():
        if name in doc:
            _validate_value(f"{path}.{name}", doc[name], sub)


def crd_for_kind(store, kind: str) -> Optional[CustomResourceDefinition]:
    for crd in store.list("CustomResourceDefinition")[0]:
        if crd.spec.names.kind == kind:
            return crd
    return None


def validate_custom_resource(obj: Any, operation: str, store=None) -> None:
    """Admission: a DynamicObject must name a registered CRD and its
    spec must satisfy the CRD's structural schema."""
    if not isinstance(obj, DynamicObject) or store is None:
        return
    if operation == "DELETE":
        return
    crd = crd_for_kind(store, obj.KIND)
    if crd is None:
        raise AdmissionError(
            f"no CustomResourceDefinition registered for kind {obj.KIND!r}"
        )
    if crd.spec.schema:
        _validate_object("spec", obj.spec, crd.spec.schema)


validate_custom_resource.wants_store = True


def validate_crd(obj: Any, operation: str) -> None:
    if not isinstance(obj, CustomResourceDefinition):
        return
    if not obj.spec.names.kind:
        raise AdmissionError("crd: spec.names.kind is required")
    for typ in _walk_types(obj.spec.schema):
        if typ not in _TYPES:
            raise AdmissionError(f"crd: unknown schema type {typ!r}")


def _walk_types(schema: Dict[str, Any]):
    for sub in (schema.get("properties") or {}).values():
        if "type" in sub:
            yield sub["type"]
        yield from _walk_types(sub)
    if "items" in schema:
        if "type" in schema["items"]:
            yield schema["items"]["type"]
        yield from _walk_types(schema["items"])


# -- PodGroup: the proving instance ------------------------------------------


PODGROUP_CRD = CustomResourceDefinition(
    meta=api.ObjectMeta(name="podgroups.scheduling.x-k8s.io", namespace=""),
    spec=CustomResourceDefinitionSpec(
        group="scheduling.x-k8s.io",
        names=CRDNames(kind="PodGroup", plural="podgroups", singular="podgroup"),
        schema={
            "properties": {
                "minMember": {"type": "integer", "minimum": 1},
                "scheduleTimeoutSeconds": {"type": "number", "minimum": 0},
            },
            "required": ["minMember"],
        },
    ),
)


def install_podgroup_crd(store) -> None:
    try:
        store.create(PODGROUP_CRD)
    except Exception:  # AlreadyExists
        pass


def pod_group(name: str, min_member: int, namespace: str = "default",
              timeout_s: Optional[float] = None) -> DynamicObject:
    spec: Dict[str, Any] = {"minMember": min_member}
    if timeout_s is not None:
        spec["scheduleTimeoutSeconds"] = timeout_s
    return DynamicObject(
        "PodGroup",
        meta=api.ObjectMeta(name=name, namespace=namespace),
        spec=spec,
    )


class PodGroupDirectory:
    """Resolves gang sizes from PodGroup API objects for the
    coscheduling Permit plugin (the PodGroup minMember read the
    out-of-tree plugin does through its informer)."""

    def __init__(self, store):
        self.store = store

    def size_for(self, namespace: str, group: str) -> Optional[int]:
        try:
            pg = self.store.get("PodGroup", group, namespace)
        except KeyError:
            return None
        return pg.spec.get("minMember")

    def timeout_for(self, namespace: str, group: str) -> Optional[float]:
        try:
            pg = self.store.get("PodGroup", group, namespace)
        except KeyError:
            return None
        return pg.spec.get("scheduleTimeoutSeconds")
