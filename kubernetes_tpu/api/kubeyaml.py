"""Kubernetes-YAML → api.types converter for the perf harness (and any
other wire-compat surface).

Covers the object slice the scheduler_perf workloads use (reference
template files under test/integration/scheduler_perf/config/: pod
requests, labels, node/pod affinity, topology spread, tolerations,
priority, host ports; node allocatable/labels/taints).  Quantities parse
per apimachinery resource.Quantity suffixes (binary Ki..Ei, decimal
k..E, milli) — cpu normalizes to millicores, everything else to base
units (bytes for memory).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from . import types as api

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}


def parse_quantity(v: Any, *, cpu: bool = False) -> int:
    """'500m' → 500 (cpu) / 0.5 (non-cpu, rounded); '512Mi' → bytes;
    bare ints pass through (cpu ints are CORES in k8s — scaled to milli)."""
    if isinstance(v, (int, float)):
        return int(v * 1000) if cpu else int(v)
    s = str(v).strip()
    if s.endswith("m"):
        n = float(s[:-1])
        return int(n) if cpu else int(n / 1000)
    for suf, mult in _BINARY.items():
        if s.endswith(suf):
            base = float(s[: -len(suf)]) * mult
            return int(base * 1000) if cpu else int(base)
    for suf, mult in _DECIMAL.items():
        if s.endswith(suf):
            base = float(s[: -len(suf)]) * mult
            return int(base * 1000) if cpu else int(base)
    return int(float(s) * 1000) if cpu else int(float(s))


def _requests(d: Dict[str, Any]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for k, v in (d or {}).items():
        out[k] = parse_quantity(v, cpu=(k == api.CPU))
    return out


def _label_selector(d: Optional[Dict[str, Any]]) -> Optional[api.LabelSelector]:
    if d is None:
        return None
    exprs = [
        api.Requirement(
            key=e["key"], op=e["operator"], values=list(e.get("values") or [])
        )
        for e in d.get("matchExpressions") or []
    ]
    return api.LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}), match_expressions=exprs
    )


def _node_selector_term(d: Dict[str, Any]) -> api.NodeSelectorTerm:
    exprs = [
        api.Requirement(
            key=e["key"], op=e["operator"], values=list(e.get("values") or [])
        )
        for e in d.get("matchExpressions") or []
    ]
    return api.NodeSelectorTerm(match_expressions=exprs)


def _pod_affinity_term(d: Dict[str, Any]) -> api.PodAffinityTerm:
    return api.PodAffinityTerm(
        label_selector=_label_selector(d.get("labelSelector")),
        topology_key=d.get("topologyKey", api.LABEL_HOSTNAME),
        namespaces=list(d.get("namespaces") or []),
        match_label_keys=list(d.get("matchLabelKeys") or []),
    )


def _affinity(d: Optional[Dict[str, Any]]) -> Optional[api.Affinity]:
    if not d:
        return None
    aff = api.Affinity()
    na = d.get("nodeAffinity")
    if na:
        node_aff = api.NodeAffinity()
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        if req:
            node_aff.required = api.NodeSelector(
                terms=[_node_selector_term(t) for t in req.get("nodeSelectorTerms") or []]
            )
        for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            node_aff.preferred.append(
                api.PreferredSchedulingTerm(
                    weight=int(p.get("weight", 1)),
                    preference=_node_selector_term(p.get("preference") or {}),
                )
            )
        aff.node_affinity = node_aff
    for src, cls, attr in (
        ("podAffinity", api.PodAffinity, "pod_affinity"),
        ("podAntiAffinity", api.PodAntiAffinity, "pod_anti_affinity"),
    ):
        pa = d.get(src)
        if pa:
            obj = cls()
            for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
                obj.required.append(_pod_affinity_term(t))
            for p in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
                obj.preferred.append(
                    api.WeightedPodAffinityTerm(
                        weight=int(p.get("weight", 1)),
                        term=_pod_affinity_term(p.get("podAffinityTerm") or {}),
                    )
                )
            setattr(aff, attr, obj)
    return aff


def _probe(d: Optional[Dict[str, Any]]) -> Optional[api.Probe]:
    """core/v1 Probe timing fields (the action — exec/httpGet/tcpSocket —
    is carried out by the node agent's hollow runtime)."""
    if not d:
        return None
    return api.Probe(
        initial_delay_seconds=float(d.get("initialDelaySeconds", 0)),
        period_seconds=float(d.get("periodSeconds", 1)),
        failure_threshold=int(d.get("failureThreshold", 3)),
        success_threshold=int(d.get("successThreshold", 1)),
        timeout_seconds=float(d.get("timeoutSeconds", 1)),
    )


def pod_from_dict(d: Dict[str, Any]) -> api.Pod:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    pod = api.Pod(
        meta=api.ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels") or {}),
        )
    )
    containers: List[api.Container] = []
    for c in spec.get("containers") or []:
        cont = api.Container(
            name=c.get("name", "c"),
            image=c.get("image", ""),
            requests=_requests((c.get("resources") or {}).get("requests")),
            limits=_requests((c.get("resources") or {}).get("limits")),
        )
        cont.readiness_probe = _probe(c.get("readinessProbe"))
        cont.liveness_probe = _probe(c.get("livenessProbe"))
        cont.startup_probe = _probe(c.get("startupProbe"))
        for p in c.get("ports") or []:
            cont.ports.append(
                api.ContainerPort(
                    name=p.get("name", ""),
                    container_port=int(p.get("containerPort", 0)),
                    host_port=int(p.get("hostPort", 0)),
                    protocol=p.get("protocol", "TCP"),
                    host_ip=p.get("hostIP", ""),
                )
            )
        containers.append(cont)
    pod.spec.containers = containers or [api.Container()]
    pod.spec.node_name = spec.get("nodeName", "")
    pod.spec.node_selector = dict(spec.get("nodeSelector") or {})
    pod.spec.affinity = _affinity(spec.get("affinity"))
    pod.spec.priority = int(spec.get("priority", 0))
    if spec.get("preemptionPolicy"):
        pod.spec.preemption_policy = spec["preemptionPolicy"]
    if spec.get("schedulerName"):
        pod.spec.scheduler_name = spec["schedulerName"]
    pod.spec.scheduling_gates = [
        g["name"] for g in spec.get("schedulingGates") or []
    ]
    for t in spec.get("tolerations") or []:
        pod.spec.tolerations.append(
            api.Toleration(
                key=t.get("key", ""),
                op=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
        )
    for c in spec.get("topologySpreadConstraints") or []:
        pod.spec.topology_spread_constraints.append(
            api.TopologySpreadConstraint(
                max_skew=int(c.get("maxSkew", 1)),
                topology_key=c.get("topologyKey", api.LABEL_ZONE),
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=_label_selector(c.get("labelSelector")),
                min_domains=c.get("minDomains"),
                match_label_keys=list(c.get("matchLabelKeys") or []),
            )
        )
    for v in spec.get("volumes") or []:
        pvc = (v.get("persistentVolumeClaim") or {}).get("claimName")
        if pvc:
            pod.spec.volumes.append(
                api.Volume(name=v.get("name", ""), persistent_volume_claim=pvc)
            )
    pod.spec.resource_claims = [
        rc.get("resourceClaimName") or rc.get("name", "")
        for rc in spec.get("resourceClaims") or []
    ]
    return pod


def node_from_dict(d: Dict[str, Any]) -> api.Node:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    node = api.Node(
        meta=api.ObjectMeta(
            name=meta.get("name", ""),
            namespace="",
            labels=dict(meta.get("labels") or {}),
        )
    )
    node.meta.labels.setdefault(api.LABEL_HOSTNAME, node.meta.name)
    alloc = status.get("allocatable") or status.get("capacity") or {}
    node.status.allocatable = {
        k: parse_quantity(v, cpu=(k == api.CPU)) for k, v in alloc.items()
    }
    node.status.capacity = dict(node.status.allocatable)
    node.spec.unschedulable = bool(spec.get("unschedulable", False))
    for t in spec.get("taints") or []:
        node.spec.taints.append(
            api.Taint(
                key=t.get("key", ""),
                value=t.get("value", ""),
                effect=t.get("effect", api.NO_SCHEDULE),
            )
        )
    return node


def _pod_template_from_dict(d: Dict[str, Any]) -> api.PodTemplateSpec:
    meta = d.get("metadata") or {}
    pod = pod_from_dict({"spec": d.get("spec") or {}})
    return api.PodTemplateSpec(
        meta=api.ObjectMeta(name="", labels=dict(meta.get("labels") or {})),
        spec=pod.spec,
    )


def deployment_from_dict(d: Dict[str, Any]) -> api.Deployment:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return api.Deployment(
        meta=api.ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels") or {}),
        ),
        spec=api.DeploymentSpec(
            replicas=int(spec.get("replicas", 1)),
            selector=_label_selector(spec.get("selector")) or api.LabelSelector(),
            template=_pod_template_from_dict(spec.get("template") or {}),
        ),
    )


def _job_spec_from_dict(spec: Dict[str, Any]) -> api.JobSpec:
    return api.JobSpec(
        parallelism=int(spec.get("parallelism", 1)),
        completions=(
            int(spec["completions"]) if "completions" in spec else 1
        ),
        template=_pod_template_from_dict(spec.get("template") or {}),
        backoff_limit=int(spec.get("backoffLimit", 6)),
    )


def job_from_dict(d: Dict[str, Any]) -> api.Job:
    meta = d.get("metadata") or {}
    return api.Job(
        meta=api.ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels") or {}),
        ),
        spec=_job_spec_from_dict(d.get("spec") or {}),
    )


def _meta_from_dict(d: Dict[str, Any], namespace="default") -> api.ObjectMeta:
    meta = d.get("metadata") or {}
    return api.ObjectMeta(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", namespace),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
    )


def statefulset_from_dict(d: Dict[str, Any]) -> api.StatefulSet:
    spec = d.get("spec") or {}
    return api.StatefulSet(
        meta=_meta_from_dict(d),
        spec=api.StatefulSetSpec(
            replicas=int(spec.get("replicas", 1)),
            selector=_label_selector(spec.get("selector")) or api.LabelSelector(),
            template=_pod_template_from_dict(spec.get("template") or {}),
            service_name=spec.get("serviceName", ""),
            pod_management_policy=spec.get("podManagementPolicy", "OrderedReady"),
            volume_claim_templates=[
                pvc_from_dict(t) for t in spec.get("volumeClaimTemplates") or []
            ],
        ),
    )


def daemonset_from_dict(d: Dict[str, Any]) -> api.DaemonSet:
    spec = d.get("spec") or {}
    return api.DaemonSet(
        meta=_meta_from_dict(d),
        spec=api.DaemonSetSpec(
            selector=_label_selector(spec.get("selector")) or api.LabelSelector(),
            template=_pod_template_from_dict(spec.get("template") or {}),
        ),
    )


def cronjob_from_dict(d: Dict[str, Any]) -> api.CronJob:
    spec = d.get("spec") or {}
    job_tpl = (spec.get("jobTemplate") or {}).get("spec") or {}
    return api.CronJob(
        meta=_meta_from_dict(d),
        spec=api.CronJobSpec(
            schedule=spec.get("schedule", "* * * * *"),
            suspend=bool(spec.get("suspend", False)),
            concurrency_policy=spec.get("concurrencyPolicy", "Allow"),
            starting_deadline_seconds=(
                float(spec["startingDeadlineSeconds"])
                if "startingDeadlineSeconds" in spec else None
            ),
            job_template=_job_spec_from_dict(job_tpl),
        ),
    )


def pvc_from_dict(d: Dict[str, Any]) -> api.PersistentVolumeClaim:
    spec = d.get("spec") or {}
    storage = parse_quantity(
        ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
    )
    return api.PersistentVolumeClaim(
        meta=_meta_from_dict(d),
        spec=api.PersistentVolumeClaimSpec(
            access_modes=list(spec.get("accessModes") or []),
            storage_class_name=spec.get("storageClassName", ""),
            resources={api.STORAGE: storage} if storage else {},
            volume_name=spec.get("volumeName", ""),
        ),
    )


def pv_from_dict(d: Dict[str, Any]) -> api.PersistentVolume:
    spec = d.get("spec") or {}
    affinity = None
    na = (spec.get("nodeAffinity") or {}).get("required")
    if na:
        affinity = api.NodeSelector(
            terms=[
                _node_selector_term(t)
                for t in na.get("nodeSelectorTerms") or []
            ]
        )
    storage = parse_quantity((spec.get("capacity") or {}).get("storage", 0))
    csi = spec.get("csi") or {}
    return api.PersistentVolume(
        meta=_meta_from_dict(d, namespace=""),
        spec=api.PersistentVolumeSpec(
            capacity={api.STORAGE: storage} if storage else {},
            access_modes=list(spec.get("accessModes") or []),
            storage_class_name=spec.get("storageClassName", ""),
            node_affinity=affinity,
            driver=csi.get("driver", ""),
            reclaim_policy=spec.get(
                "persistentVolumeReclaimPolicy", "Retain"
            ),
        ),
    )


def storageclass_from_dict(d: Dict[str, Any]) -> api.StorageClass:
    topo = None
    allowed = d.get("allowedTopologies")
    if allowed:
        terms = []
        for entry in allowed:
            exprs = [
                api.Requirement(
                    e.get("key", ""), api.OP_IN, list(e.get("values") or [])
                )
                for e in entry.get("matchLabelExpressions") or []
            ]
            terms.append(api.NodeSelectorTerm(match_expressions=exprs))
        topo = api.NodeSelector(terms=terms)
    return api.StorageClass(
        meta=_meta_from_dict(d, namespace=""),
        provisioner=d.get("provisioner", ""),
        volume_binding_mode=d.get("volumeBindingMode", api.VOLUME_BINDING_IMMEDIATE),
        allowed_topologies=topo,
    )


def pdb_from_dict(d: Dict[str, Any]) -> api.PodDisruptionBudget:
    spec = d.get("spec") or {}
    return api.PodDisruptionBudget(
        meta=_meta_from_dict(d),
        spec=api.PodDisruptionBudgetSpec(
            selector=_label_selector(spec.get("selector")),
            min_available=(
                int(spec["minAvailable"]) if "minAvailable" in spec else None
            ),
            max_unavailable=(
                int(spec["maxUnavailable"])
                if "maxUnavailable" in spec else None
            ),
        ),
    )


def namespace_from_dict(d: Dict[str, Any]) -> api.Namespace:
    return api.Namespace(meta=_meta_from_dict(d, namespace=""))


def resourceclaim_from_dict(d: Dict[str, Any]) -> api.ResourceClaim:
    spec = d.get("spec") or {}
    return api.ResourceClaim(
        meta=_meta_from_dict(d),
        spec=api.ResourceClaimSpec(
            device_class_name=spec.get("deviceClassName", ""),
            count=int(spec.get("count", 1)),
        ),
    )


def deviceclass_from_dict(d: Dict[str, Any]) -> api.DeviceClass:
    return api.DeviceClass(
        meta=_meta_from_dict(d, namespace=""),
        driver=(d.get("spec") or {}).get("driver", d.get("driver", "")),
    )


# kind -> converter, the CLI's `create -f` dispatch table
def service_from_dict(d: Dict[str, Any]) -> api.Service:
    """core/v1 Service (types.go:5517): selector + ports + clusterIP."""
    spec = d.get("spec") or {}
    ports = []
    for p in spec.get("ports") or []:
        tp = p.get("targetPort", 0)
        ports.append(
            api.ServicePort(
                name=p.get("name", ""),
                protocol=p.get("protocol", "TCP"),
                port=int(p.get("port", 0)),
                target_port=int(tp) if isinstance(tp, int) else 0,
                target_port_name=tp if isinstance(tp, str) else "",
                node_port=int(p.get("nodePort", 0)),
            )
        )
    return api.Service(
        meta=_meta_from_dict(d),
        spec=api.ServiceSpec(
            selector=dict(spec.get("selector") or {}),
            ports=ports,
            cluster_ip=spec.get("clusterIP", ""),
            type=spec.get("type", "ClusterIP"),
            external_name=spec.get("externalName", ""),
            session_affinity=spec.get("sessionAffinity", "None"),
            publish_not_ready_addresses=bool(
                spec.get("publishNotReadyAddresses", False)
            ),
        ),
    )


def configmap_from_dict(d: Dict[str, Any]) -> api.ConfigMap:
    return api.ConfigMap(
        meta=_meta_from_dict(d),
        data={k: str(v) for k, v in (d.get("data") or {}).items()},
        binary_data=dict(d.get("binaryData") or {}),
        immutable=bool(d.get("immutable", False)),
    )


def secret_from_dict(d: Dict[str, Any]) -> api.Secret:
    return api.Secret(
        meta=_meta_from_dict(d),
        type=d.get("type", "Opaque"),
        data=dict(d.get("data") or {}),
        string_data={
            k: str(v) for k, v in (d.get("stringData") or {}).items()
        },
        immutable=bool(d.get("immutable", False)),
    )


CONVERTERS = {
    "Service": service_from_dict,
    "ConfigMap": configmap_from_dict,
    "Secret": secret_from_dict,
    "Node": node_from_dict,
    "Pod": pod_from_dict,
    "Deployment": deployment_from_dict,
    "Job": job_from_dict,
    "StatefulSet": statefulset_from_dict,
    "DaemonSet": daemonset_from_dict,
    "CronJob": cronjob_from_dict,
    "PersistentVolume": pv_from_dict,
    "PersistentVolumeClaim": pvc_from_dict,
    "StorageClass": storageclass_from_dict,
    "PodDisruptionBudget": pdb_from_dict,
    "Namespace": namespace_from_dict,
    "ResourceClaim": resourceclaim_from_dict,
    "DeviceClass": deviceclass_from_dict,
}
