"""Core API object model.

The Python-native equivalent of the reference's versioned API types
(reference: staging/src/k8s.io/api/core/v1/types.go and
pkg/apis/core/types.go).  Only the fields the control plane and scheduler
actually consume are modelled; everything is a plain dataclass so objects
are cheap to construct in tests and benchmarks (the reference's builder
wrappers, pkg/scheduler/testing/wrappers.go, have an equivalent in
kubernetes_tpu.testing.wrappers).

Conventions:
  * cpu is always integer milli-cores, memory/ephemeral-storage integer
    bytes, every other resource an integer count (the canonical units the
    reference's resource.Quantity MilliValue()/Value() calls produce).
  * labels/annotations are plain dicts.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Resource names (reference: staging/src/k8s.io/api/core/v1/types.go ResourceName)
# ---------------------------------------------------------------------------

CPU = "cpu"                      # milli-cores
MEMORY = "memory"                # bytes
EPHEMERAL_STORAGE = "ephemeral-storage"  # bytes
PODS = "pods"                    # count

# Default requests applied for *scoring only* when a pod declares none
# (reference: pkg/scheduler/util/pod_resources.go:33-36).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

# Taint effects (reference: api/core/v1/types.go TaintEffect)
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"
TAINT_EFFECTS = (NO_SCHEDULE, PREFER_NO_SCHEDULE, NO_EXECUTE)

# Well-known taint applied to cordoned nodes
# (reference: staging/src/k8s.io/api/core/v1/well_known_taints.go).
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"

# Well-known labels
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"

# TPU slice-topology node labels (the GKE tpu-topology label family,
# normalized): a node that is one device of a multi-host TPU slice
# carries its slice (pool) name, the slice's torus extent "XxYxZ", its
# own coordinates "x,y,z" within the slice, and (optionally) a core
# index on the host.  ops/schema.py encodes them into the cluster
# tensors (slice_id / torus_coords / slice_dims / slice_pos);
# ops/slices.py carves gangs out of them.
LABEL_TPU_SLICE = "tpu.kubernetes.io/slice"
LABEL_TPU_TOPOLOGY = "tpu.kubernetes.io/topology"
LABEL_TPU_COORDS = "tpu.kubernetes.io/coords"
LABEL_TPU_CORE = "tpu.kubernetes.io/core"


def parse_topology(text) -> Optional[Tuple[int, int, int]]:
    """Parse an "AxBxC" torus-extent string (1 or 2 axes are padded
    with trailing 1s: "8" -> (8,1,1), "4x2" -> (4,2,1)).  Returns None
    for anything unparseable or non-positive — callers treat that as
    'no declared topology', never an error (one malformed label must
    not sink an encode)."""
    if not text or not isinstance(text, str):
        return None
    parts = text.lower().split("x")
    if not 1 <= len(parts) <= 3:
        return None
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        return None
    if any(d <= 0 for d in dims):
        return None
    return tuple(dims + [1] * (3 - len(dims)))


def parse_coords(text) -> Optional[Tuple[int, int, int]]:
    """Parse an "x,y,z" in-slice coordinate string (missing trailing
    axes read 0).  None for unparseable/negative values."""
    if not text or not isinstance(text, str):
        return None
    parts = text.split(",")
    if not 1 <= len(parts) <= 3:
        return None
    try:
        coords = [int(p) for p in parts]
    except ValueError:
        return None
    if any(c < 0 for c in coords):
        return None
    return tuple(coords + [0] * (3 - len(parts)))

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------


@dataclass
class ObjectMeta:
    """reference: staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go ObjectMeta."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    owner_references: List["OwnerReference"] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


# ---------------------------------------------------------------------------
# Selectors / affinity (reference: api/core/v1/types.go NodeSelector et al.)
# ---------------------------------------------------------------------------

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"
OP_EQUAL = "Equal"  # toleration operator


@dataclass
class Requirement:
    """One match expression: NodeSelectorRequirement / LabelSelectorRequirement."""

    key: str
    op: str = OP_IN
    values: List[str] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        """Label-set semantics (reference: apimachinery/pkg/labels/selector.go
        Requirement.Matches — NotIn/DoesNotExist match when the key is absent)."""
        present = self.key in labels
        if self.op == OP_IN:
            return present and labels[self.key] in self.values
        if self.op == OP_NOT_IN:
            return (not present) or labels[self.key] not in self.values
        if self.op == OP_EXISTS:
            return present
        if self.op == OP_DOES_NOT_EXIST:
            return not present
        if self.op in (OP_GT, OP_LT):
            # Both the label value and the bound must parse as integers;
            # otherwise the requirement doesn't match (labels.Requirement
            # semantics: ParseInt failure => no match).
            if not present:
                return False
            lv = _parse_int(labels[self.key])
            bound = _parse_int(self.values[0]) if self.values else None
            if lv is None or bound is None:
                return False
            return lv > bound if self.op == OP_GT else lv < bound
        raise ValueError(f"unknown operator {self.op}")


def _parse_int(s: str) -> Optional[int]:
    try:
        return int(s)
    except ValueError:
        return None


@dataclass
class NodeSelectorTerm:
    """Expressions are ANDed (reference: v1.NodeSelectorTerm)."""

    match_expressions: List[Requirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(r.matches(labels) for r in self.match_expressions)


@dataclass
class NodeSelector:
    """Terms are ORed (reference: v1.NodeSelector)."""

    terms: List[NodeSelectorTerm] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return any(t.matches(labels) for t in self.terms)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class LabelSelector:
    """reference: metav1.LabelSelector — match_labels ANDed with expressions."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[Requirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)

    def requirements(self) -> List[Requirement]:
        """Canonical AND-of-requirements form."""
        reqs = [Requirement(k, OP_IN, [v]) for k, v in sorted(self.match_labels.items())]
        reqs.extend(self.match_expressions)
        return reqs


def and_selectors(
    a: Optional["NodeSelector"], b: Optional["NodeSelector"]
) -> Optional["NodeSelector"]:
    """AND of two OR-of-AND NodeSelectors: the term cross product (the
    same distribution GetRequiredNodeAffinity applies to nodeSelector +
    affinity)."""
    if a is None:
        return b
    if b is None:
        return a
    return NodeSelector(
        terms=[
            NodeSelectorTerm(
                match_expressions=list(ta.match_expressions)
                + list(tb.match_expressions)
            )
            for ta in a.terms
            for tb in b.terms
        ]
    )


@dataclass
class PodAffinityTerm:
    """reference: v1.PodAffinityTerm."""

    label_selector: Optional[LabelSelector] = None
    topology_key: str = LABEL_HOSTNAME
    namespaces: List[str] = field(default_factory=list)  # empty => pod's own ns
    # namespace_selector needs Namespace objects (not modelled); encode
    # raises when set rather than silently ignoring it.
    namespace_selector: Optional[LabelSelector] = None
    # match_label_keys fold the *incoming pod's* label values into the
    # selector at schedule time (interpodaffinity PreFilter since 1.29);
    # the encoder implements this merge.
    match_label_keys: List[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    """reference: v1.TopologySpreadConstraint."""

    max_skew: int = 1
    topology_key: str = LABEL_ZONE
    when_unsatisfiable: str = "DoNotSchedule"  # or "ScheduleAnyway"
    label_selector: Optional[LabelSelector] = None
    # When fewer eligible domains than min_domains exist, global minimum
    # is treated as 0 (filtering.go minMatchNum); DoNotSchedule only.
    min_domains: Optional[int] = None
    # Pod label values at these keys merge into the selector at schedule
    # time (PreFilter); the encoder implements this merge.
    match_label_keys: List[str] = field(default_factory=list)
    # NodeInclusionPolicies: only the reference defaults are implemented
    # (Honor affinity, Ignore taints); encode raises on other values.
    node_affinity_policy: str = "Honor"   # Honor | Ignore
    node_taints_policy: str = "Ignore"    # Honor | Ignore


# ---------------------------------------------------------------------------
# Taints / tolerations
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass
class Toleration:
    """reference: v1.Toleration.ToleratesTaint (api/core/v1/toleration.go)."""

    key: str = ""                 # empty key + Exists tolerates everything
    op: str = OP_EXISTS           # Exists | Equal
    value: str = ""
    effect: str = ""              # empty effect matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if not self.key:
            return self.op == OP_EXISTS
        if self.op == OP_EXISTS:
            return True
        return self.value == taint.value


def tolerations_tolerate_taint(tols: List[Toleration], taint: Taint) -> bool:
    return any(t.tolerates(taint) for t in tols)


# ---------------------------------------------------------------------------
# Pods
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    name: str = ""                # named port (Service targetPort refs)
    container_port: int = 0
    host_port: int = 0            # 0 => no host port claim
    protocol: str = "TCP"
    host_ip: str = ""             # "" or "0.0.0.0" => wildcard


@dataclass
class Probe:
    """core/v1 Probe timing envelope (types.go Probe).  The probe
    ACTION (exec/http/tcp) is carried out by the node agent's runtime;
    the hollow runtime resolves outcomes from agent annotations so
    tests and kubemark can script failures (agent.py)."""

    initial_delay_seconds: float = 0.0
    period_seconds: float = 1.0
    failure_threshold: int = 3
    success_threshold: int = 1
    timeout_seconds: float = 1.0


@dataclass
class Container:
    name: str = "c"
    image: str = ""
    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)
    readiness_probe: Optional[Probe] = None
    liveness_probe: Optional[Probe] = None
    startup_probe: Optional[Probe] = None


@dataclass
class PodSpec:
    node_name: str = ""           # set at bind time
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, int] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    priority: int = 0
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduler_name: str = "default-scheduler"
    # Gang/coscheduling group: pods sharing a group name schedule
    # all-or-nothing in the joint batched solve (the out-of-tree
    # coscheduling PodGroup pattern; no in-tree reference counterpart).
    scheduling_group: Optional[str] = None
    # Declared gang size (the PodGroup minMember analogue).  When set,
    # the scheduling queue stages arriving members and releases the gang
    # to the active tier only once this many are present, so a gang is
    # never solved (and hence never partially bound) before it is whole.
    scheduling_group_size: Optional[int] = None
    scheduling_gates: List[str] = field(default_factory=list)
    # Requested TPU carve-out shape "AxBxC" (api.parse_topology): the
    # pod — or, for a gang, every member of its scheduling_group — asks
    # to be placed as a contiguous axis-aligned sub-cuboid of one TPU
    # slice (ops/slices.py).  Empty = no topology request.
    tpu_topology: str = ""
    restart_policy: str = "Always"
    termination_grace_period_seconds: int = 30
    service_account: str = ""  # defaulted to "default" at admission
    volumes: List["Volume"] = field(default_factory=list)
    # ResourceClaim names (pod namespace) this pod consumes — the
    # pod.spec.resourceClaims reference (DRA)
    resource_claims: List[str] = field(default_factory=list)


@dataclass
class PodStatus:
    phase: str = "Pending"        # Pending | Running | Succeeded | Failed
    conditions: List[Dict[str, Any]] = field(default_factory=list)
    nominated_node_name: str = ""
    pod_ip: str = ""              # set by the node agent once running
    host_ip: str = ""
    # per-container restart counts, by container name (node agent v1);
    # the containerStatuses[].restartCount aggregate
    restart_counts: Dict[str, int] = field(default_factory=dict)


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    # -- derived ---------------------------------------------------------

    def resource_requests(self) -> Dict[str, int]:
        """Effective pod request: sum of containers, elementwise max with the
        largest init container, plus overhead
        (reference: pkg/api/v1/resource/helpers.go PodRequests)."""
        total: Dict[str, int] = {}
        for c in self.spec.containers:
            for k, v in c.requests.items():
                total[k] = total.get(k, 0) + v
        for ic in self.spec.init_containers:
            for k, v in ic.requests.items():
                if v > total.get(k, 0):
                    total[k] = v
        for k, v in self.spec.overhead.items():
            total[k] = total.get(k, 0) + v
        return total

    def nonzero_requests(self) -> Tuple[int, int]:
        """(milli_cpu, memory) with scoring defaults applied
        (reference: pkg/scheduler/util/pod_resources.go GetNonzeroRequests)."""
        req = self.resource_requests()
        return (
            req.get(CPU, DEFAULT_MILLI_CPU_REQUEST),
            req.get(MEMORY, DEFAULT_MEMORY_REQUEST),
        )

    def host_ports(self) -> List[Tuple[str, str, int]]:
        """(protocol, host_ip, port) triples claimed by this pod."""
        out = []
        for c in self.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append((p.protocol, p.host_ip or "0.0.0.0", p.host_port))
        return out

    def required_node_selector(self) -> Optional[NodeSelector]:
        """Merge .spec.node_selector and required node affinity into one
        NodeSelector in CNF-ish form.  node_selector entries are ANDed into
        every term (reference semantics: both must match —
        component-helpers/scheduling/corev1/nodeaffinity.GetRequiredNodeAffinity)."""
        ns_reqs = [Requirement(k, OP_IN, [v]) for k, v in sorted(self.spec.node_selector.items())]
        aff = self.spec.affinity.node_affinity if self.spec.affinity else None
        req_sel = aff.required if aff else None
        if req_sel is None or not req_sel.terms:
            if not ns_reqs:
                return None
            return NodeSelector(terms=[NodeSelectorTerm(match_expressions=ns_reqs)])
        terms = [
            NodeSelectorTerm(match_expressions=ns_reqs + list(t.match_expressions))
            for t in req_sel.terms
        ]
        return NodeSelector(terms=terms)

    def preferred_node_affinity(self) -> List[PreferredSchedulingTerm]:
        aff = self.spec.affinity.node_affinity if self.spec.affinity else None
        return list(aff.preferred) if aff else []


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    provider_id: str = ""


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class NodeStatus:
    allocatable: Dict[str, int] = field(default_factory=dict)
    capacity: Dict[str, int] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"

    def effective_taints(self) -> List[Taint]:
        """Spec taints plus the synthetic unschedulable taint for cordoned
        nodes (the reference's NodeUnschedulable plugin consults the spec
        flag but honours tolerations of node.kubernetes.io/unschedulable —
        pkg/scheduler/framework/plugins/nodeunschedulable/node_unschedulable.go:60-76;
        modelling it as a taint gives identical semantics in one code path)."""
        taints = list(self.spec.taints)
        if self.spec.unschedulable:
            t = Taint(TAINT_NODE_UNSCHEDULABLE, "", NO_SCHEDULE)
            if t not in taints:
                taints.append(t)
        return taints


@dataclass
class NamespaceStatus:
    phase: str = "Active"  # Active | Terminating


@dataclass
class Namespace:
    """core/v1 Namespace: the unit of multi-tenancy; deleting one reaps
    its objects (namespace lifecycle controller)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)

    KIND = "Namespace"


# ---------------------------------------------------------------------------
# Policy APIs (reference: staging/src/k8s.io/api/policy/v1/types.go
# PodDisruptionBudget) — consumed by preemption's victim ranking and
# maintained by the disruption controller.
# ---------------------------------------------------------------------------


@dataclass
class PodDisruptionBudgetSpec:
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None     # at least this many healthy
    max_unavailable: Optional[int] = None   # at most this many disrupted


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(
        default_factory=PodDisruptionBudgetSpec
    )
    status: PodDisruptionBudgetStatus = field(
        default_factory=PodDisruptionBudgetStatus
    )

    KIND = "PodDisruptionBudget"

    def matches(self, pod: "Pod") -> bool:
        if pod.meta.namespace != self.meta.namespace:
            return False
        sel = self.spec.selector
        return sel is not None and sel.matches(pod.meta.labels)


# ---------------------------------------------------------------------------
# Storage APIs (reference: staging/src/k8s.io/api/core/v1/types.go
# PersistentVolume/PersistentVolumeClaim, storage/v1/types.go
# StorageClass) — the slice VolumeBinding schedules against.
# ---------------------------------------------------------------------------

STORAGE = "storage"                       # PVC resource request key
VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"
PV_AVAILABLE = "Available"
PV_BOUND = "Bound"
PV_RELEASED = "Released"
PVC_PENDING = "Pending"
PVC_BOUND = "Bound"
# node-allocatable key prefix for attach limits (the reference models
# CSI attach limits as node-published countable resources —
# nodevolumelimits/csi.go GetVolumeLimitKey)
ATTACH_LIMIT_PREFIX = "attachable-volumes-"


def attach_limit_resource(driver: str) -> str:
    return ATTACH_LIMIT_PREFIX + driver


@dataclass
class Volume:
    """Pod volume: only the PVC source is modelled (the scheduling-
    relevant one; core/v1/types.go Volume has ~30 sources)."""

    name: str = ""
    persistent_volume_claim: Optional[str] = None  # claim name in pod ns


@dataclass
class PersistentVolumeSpec:
    capacity: Dict[str, int] = field(default_factory=dict)  # {storage: bytes}
    access_modes: List[str] = field(default_factory=list)
    storage_class_name: str = ""
    # topology constraint: node must satisfy this to mount the volume
    # (core/v1 VolumeNodeAffinity.required)
    node_affinity: Optional[NodeSelector] = None
    claim_ref: Optional[str] = None       # "namespace/name" of bound claim
    claim_uid: str = ""                   # that claim's uid: a deleted-and-
    # recreated same-name PVC must NOT silently inherit the volume
    # (pv_controller.go checks claimRef.UID for exactly this)
    driver: str = ""                      # CSI driver (attach-limit bucket)
    # Retain | Delete | Recycle (core/v1 PersistentVolumeReclaimPolicy;
    # acted on by the PV controller when the claim goes away)
    reclaim_policy: str = "Retain"


@dataclass
class PersistentVolumeStatus:
    phase: str = PV_AVAILABLE


@dataclass
class PersistentVolume:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(
        default_factory=PersistentVolumeStatus
    )

    KIND = "PersistentVolume"

    def storage(self) -> int:
        return int(self.spec.capacity.get(STORAGE, 0))


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    storage_class_name: str = ""
    resources: Dict[str, int] = field(default_factory=dict)  # {storage: bytes}
    volume_name: str = ""                 # set when bound to a PV


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = PVC_PENDING


@dataclass
class PersistentVolumeClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(
        default_factory=PersistentVolumeClaimSpec
    )
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )

    KIND = "PersistentVolumeClaim"

    def requested_storage(self) -> int:
        return int(self.spec.resources.get(STORAGE, 0))


@dataclass
class StorageClass:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = VOLUME_BINDING_IMMEDIATE
    # restrict dynamic provisioning to these topologies (storage/v1
    # StorageClass.allowedTopologies, as OR-of-AND selector terms)
    allowed_topologies: Optional[NodeSelector] = None

    KIND = "StorageClass"


# ---------------------------------------------------------------------------
# Dynamic resource allocation (reference: resource.k8s.io ResourceClaim /
# DeviceClass, scheduled by plugins/dynamicresources/dynamicresources.go)
# — device claims as first-class objects with allocation lifecycle.
# ---------------------------------------------------------------------------


def device_resource(class_name: str) -> str:
    """The node-allocatable resource name carrying a device class's
    per-node capacity (the devicemanager-published countable-resource
    convention)."""
    return f"devices/{class_name}"


@dataclass
class DeviceClass:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    driver: str = ""

    KIND = "DeviceClass"


@dataclass
class ResourceClaimSpec:
    device_class_name: str = ""
    count: int = 1                 # devices requested from the class
    # Topology-shaped claim: request an "AxBxC" contiguous carve-out of
    # one TPU slice instead of `count` loose devices.  Allocation
    # records the carve-out (status.carveout) and every consumer is
    # pinned INSIDE it via slice/coord label selector terms — matched
    # in the batched filter, not host Python.
    topology: str = ""


@dataclass
class ResourceClaimStatus:
    phase: str = "Pending"         # Pending | Allocated
    allocated_node: str = ""       # set at allocation (Reserve/PreBind)
    # the consumer pod (ns/name) whose resource accounting carries the
    # claim's device count — keeps usage stable across the pod's
    # lifetime while sharers add only the co-location pin
    carrier: str = ""
    # topology-shaped allocation record: "slice=<name>;lo=x,y,z;shape=AxBxC"
    # (scheduler/deviceclaims.py format_carveout) — the carved sub-cuboid
    # consumers are pinned inside
    carveout: str = ""


@dataclass
class ResourceClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceClaimSpec = field(default_factory=ResourceClaimSpec)
    status: ResourceClaimStatus = field(default_factory=ResourceClaimStatus)

    KIND = "ResourceClaim"


# ---------------------------------------------------------------------------
# Workload APIs (reference: staging/src/k8s.io/api/apps/v1/types.go
# ReplicaSet/Deployment, batch/v1/types.go Job) — the slice the workload
# controllers reconcile.
# ---------------------------------------------------------------------------


@dataclass
class PodTemplateSpec:
    """v1.PodTemplateSpec: metadata (labels) + spec stamped onto pods."""

    meta: ObjectMeta = field(default_factory=lambda: ObjectMeta(name=""))
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)

    KIND = "ReplicaSet"


@dataclass
class DeploymentStrategy:
    # "RollingUpdate" steps the new ReplicaSet up and old ones down under
    # the surge/unavailable bounds (pkg/controller/deployment/rolling.go);
    # "Recreate" drains old revisions fully before scaling the new one.
    type: str = "RollingUpdate"
    # absolute counts (the reference also accepts percentages; validation
    # rejects 0/0 — ours falls back to max_unavailable=1 in that case)
    max_surge: int = 1
    max_unavailable: int = 0


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    KIND = "Deployment"


@dataclass
class ObjectReference:
    """core/v1 ObjectReference — the involved object of an Event."""

    kind: str = ""
    name: str = ""
    namespace: str = "default"
    uid: str = ""


@dataclass
class Event:
    """core/v1 Event, the slice the scheduler's EventRecorder emits
    (schedule_one.go:1003 Eventf; aggregated by count like
    client-go's correlator)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"          # Normal | Warning
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    source_component: str = ""

    KIND = "Event"


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 LeaseSpec — the leader-election record."""

    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


@dataclass
class Lease:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)

    KIND = "Lease"


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: Optional[int] = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    backoff_limit: int = 6
    # ttlafterfinished controller: delete the Job (and its pods via GC)
    # this many seconds after it finishes (batch/v1 TTLSecondsAfterFinished)
    ttl_seconds_after_finished: Optional[float] = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    completion_time: Optional[float] = None


@dataclass
class Job:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    KIND = "Job"


@dataclass
class StatefulSetSpec:
    """apps/v1 StatefulSetSpec: ordered, identity-stable replicas with
    per-replica volume claims."""

    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    # one PVC per (template, ordinal): claim "<tpl>-<set>-<i>"
    volume_claim_templates: List["PersistentVolumeClaim"] = field(
        default_factory=list
    )
    pod_management_policy: str = "OrderedReady"  # or "Parallel"


@dataclass
class StatefulSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class StatefulSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)

    KIND = "StatefulSet"


@dataclass
class DaemonSetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSetStatus:
    desired_number_scheduled: int = 0
    current_number_scheduled: int = 0
    number_ready: int = 0


@dataclass
class DaemonSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)

    KIND = "DaemonSet"


@dataclass
class CronJobSpec:
    schedule: str = "* * * * *"       # standard 5-field cron
    job_template: JobSpec = field(default_factory=JobSpec)
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[float] = None
    # batch/v1 spec.timeZone: None = the controller's local time (the
    # reference's default, DST caveats included); "UTC"/"Etc/UTC" pins
    # evaluation to UTC, immune to DST double-fire/skip
    time_zone: Optional[str] = None


@dataclass
class CronJobStatus:
    last_schedule_time: Optional[float] = None
    active: List[str] = field(default_factory=list)  # job names


@dataclass
class CronJob:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)

    KIND = "CronJob"


# ---------------------------------------------------------------------------
# Services & endpoints (reference: staging/src/k8s.io/api/core/v1/types.go:5517
# Service, :6088 Endpoints; staging/src/k8s.io/api/discovery/v1/types.go
# EndpointSlice).  A Service names a virtual IP + port set; the
# endpointslice controller materialises "what backs this VIP" from the
# ready pods matching the selector.
# ---------------------------------------------------------------------------


LABEL_SERVICE_NAME = "kubernetes.io/service-name"  # discovery/v1 well-known


@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    # target port on the backend pods; 0 means same as `port`.  Named
    # targetPorts (string form) resolve against container port names at
    # slice-build time, like the reference's findPort
    # (pkg/api/v1/pod/util.go FindPort).
    target_port: int = 0
    target_port_name: str = ""
    node_port: int = 0


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""   # allocated at admission ("" = allocate; "None" = headless)
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    external_name: str = ""
    session_affinity: str = "None"  # None | ClientIP
    publish_not_ready_addresses: bool = False


@dataclass
class LoadBalancerIngress:
    ip: str = ""
    hostname: str = ""


@dataclass
class ServiceStatus:
    load_balancer: List[LoadBalancerIngress] = field(default_factory=list)


@dataclass
class Service:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)

    KIND = "Service"


@dataclass
class EndpointConditions:
    ready: bool = True
    serving: bool = True
    terminating: bool = False


@dataclass
class Endpoint:
    """discovery/v1 Endpoint: one backend of a slice."""

    addresses: List[str] = field(default_factory=list)
    conditions: EndpointConditions = field(default_factory=EndpointConditions)
    node_name: str = ""
    target_ref_kind: str = "Pod"
    target_ref_name: str = ""
    zone: str = ""


@dataclass
class EndpointPort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0


@dataclass
class EndpointSlice:
    """discovery/v1 EndpointSlice: a bounded chunk (<=100 endpoints by
    default) of a Service's backends, labeled kubernetes.io/service-name.
    Slicing bounds the write amplification of large services: one pod's
    readiness flip rewrites one slice, not the whole endpoint set."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List[Endpoint] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)

    KIND = "EndpointSlice"


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_ref_name: str = ""


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints:
    """core/v1 Endpoints (legacy aggregate view; kubectl get endpoints).
    Maintained alongside slices by the endpoints controller
    (pkg/controller/endpoint/endpoints_controller.go)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)

    KIND = "Endpoints"


# ---------------------------------------------------------------------------
# Autoscaling + quota + identity (reference: autoscaling/v1 types.go
# HorizontalPodAutoscaler; core/v1 ResourceQuota :6392, ServiceAccount
# :5190; metrics.k8s.io PodMetrics).
# ---------------------------------------------------------------------------


@dataclass
class ScaleTargetRef:
    kind: str = "Deployment"
    name: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    min_replicas: int = 1
    max_replicas: int = 10
    # autoscaling/v1 shape: average CPU utilization across pods as a
    # percentage of their requests
    target_cpu_utilization_percentage: int = 80


@dataclass
class HorizontalPodAutoscalerStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    last_scale_time: Optional[float] = None


@dataclass
class HorizontalPodAutoscaler:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalPodAutoscalerSpec = field(
        default_factory=HorizontalPodAutoscalerSpec
    )
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus
    )

    KIND = "HorizontalPodAutoscaler"


@dataclass
class PodMetrics:
    """metrics.k8s.io PodMetrics reduced: the node agent reports each
    running pod's usage (hollow runtime: scripted via the
    agent.kubernetes.io/cpu-usage annotation, else ~60% of request)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    usage: Dict[str, int] = field(default_factory=dict)  # {CPU: millicores}
    window_seconds: float = 10.0
    timestamp: float = 0.0

    KIND = "PodMetrics"


@dataclass
class ResourceQuotaSpec:
    # hard limits by resource name: "pods", CPU ("cpu"), MEMORY
    hard: Dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    hard: Dict[str, int] = field(default_factory=dict)
    used: Dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceQuota:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)

    KIND = "ResourceQuota"


@dataclass
class ServiceAccount:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[str] = field(default_factory=list)

    KIND = "ServiceAccount"


# ---------------------------------------------------------------------------
# Config & secrets (core/v1 ConfigMap :5789, Secret :5561): plain keyed
# payloads workloads mount/reference; Secrets carry an opaque type tag
# and base64-on-the-wire data semantics are the client's concern here.
# ---------------------------------------------------------------------------


@dataclass
class ConfigMap:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    binary_data: Dict[str, str] = field(default_factory=dict)  # b64
    immutable: bool = False

    KIND = "ConfigMap"


@dataclass
class Secret:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Opaque"
    data: Dict[str, str] = field(default_factory=dict)  # b64 values
    string_data: Dict[str, str] = field(default_factory=dict)
    immutable: bool = False

    KIND = "Secret"


# ---------------------------------------------------------------------------
# Dynamic admission (reference: admissionregistration.k8s.io/v1 —
# Mutating/ValidatingWebhookConfiguration, ValidatingAdmissionPolicy).
# Webhooks are HTTP callouts on the write path; policies are in-process
# expression checks (the CEL ValidatingAdmissionPolicy family).
# ---------------------------------------------------------------------------


@dataclass
class WebhookRule:
    operations: List[str] = field(default_factory=lambda: ["*"])  # CREATE/UPDATE
    kinds: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class Webhook:
    name: str = ""
    url: str = ""                      # clientConfig.url
    rules: List[WebhookRule] = field(default_factory=list)
    failure_policy: str = "Fail"       # Fail | Ignore
    timeout_seconds: float = 10.0


@dataclass
class MutatingWebhookConfiguration:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)

    KIND = "MutatingWebhookConfiguration"


@dataclass
class ValidatingWebhookConfiguration:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)

    KIND = "ValidatingWebhookConfiguration"


@dataclass
class PolicyValidation:
    expression: str = ""   # CEL-style over `object` / `oldObject`
    message: str = ""


@dataclass
class ValidatingAdmissionPolicySpec:
    match: WebhookRule = field(default_factory=WebhookRule)
    validations: List[PolicyValidation] = field(default_factory=list)


@dataclass
class ValidatingAdmissionPolicy:
    """ValidatingAdmissionPolicy folded with its binding (our policies
    apply cluster-wide to their match rule — the binding indirection is
    a multi-tenancy refinement this control plane doesn't need yet)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ValidatingAdmissionPolicySpec = field(
        default_factory=ValidatingAdmissionPolicySpec
    )

    KIND = "ValidatingAdmissionPolicy"


# ---------------------------------------------------------------------------
# RBAC (reference: staging/src/k8s.io/api/rbac/v1/types.go; evaluated by
# plugin/pkg/auth/authorizer/rbac/rbac.go:75).  Role/RoleBinding are
# namespace-scoped grants; ClusterRole/ClusterRoleBinding are
# cluster-wide.  A RoleBinding may reference a ClusterRole to grant its
# rules within the binding's namespace only.
# ---------------------------------------------------------------------------


@dataclass
class PolicyRule:
    verbs: List[str] = field(default_factory=lambda: ["*"])
    resources: List[str] = field(default_factory=lambda: ["*"])  # kinds


@dataclass
class RoleRef:
    kind: str = "Role"  # Role | ClusterRole
    name: str = ""


@dataclass
class RbacSubject:
    kind: str = "User"  # User | Group
    name: str = ""


@dataclass
class Role:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)

    KIND = "Role"


@dataclass
class ClusterRole:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)

    KIND = "ClusterRole"


@dataclass
class RoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RbacSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    KIND = "RoleBinding"


@dataclass
class ClusterRoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RbacSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    KIND = "ClusterRoleBinding"


def pod_is_ready(pod: "Pod") -> bool:
    """The Ready condition when the node agent reports one, else the
    Running-phase fallback (hollow kubelets flip phase without
    conditions) — podutil.IsPodReady."""
    for c in pod.status.conditions:
        if c.get("type") == "Ready":
            return c.get("status") in (True, "True")
    return pod.status.phase == "Running"


# Kinds that live outside any namespace (the reference's
# resource-scope machinery, apimachinery RESTScope): the store
# normalizes their namespace to "" on every path so callers using the
# "default" convenience still find them, and namespace sweeps skip them.
CLUSTER_SCOPED_KINDS = frozenset({
    "Node", "PersistentVolume", "StorageClass", "Namespace",
    "CustomResourceDefinition", "ClusterRole", "ClusterRoleBinding",
    "DeviceClass", "MutatingWebhookConfiguration",
    "ValidatingWebhookConfiguration", "ValidatingAdmissionPolicy",
})


def clone(obj):
    """Deep copy an API object (the reference's generated DeepCopy)."""
    return dataclasses.replace(
        obj,
        **{
            f.name: _deep(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        },
    )


def _deep(v):
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return clone(v)
    if isinstance(v, dict):
        return {k: _deep(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_deep(x) for x in v]
    return v
