"""API layer: object model (types), versioned in-memory store with watch
streams (store) — the single-process collapse of etcd + apiserver +
apimachinery (SURVEY.md layers 1-6)."""

from . import types
from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    Event,
    Expired,
    NotFound,
    Store,
    Watch,
)

__all__ = [
    "types", "Store", "Watch", "Event",
    "ADDED", "MODIFIED", "DELETED",
    "NotFound", "AlreadyExists", "Conflict", "Expired",
]
