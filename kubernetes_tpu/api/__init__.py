"""API layer: object model, versioned in-memory store, watch streams."""
