"""Journal frame codec: one line, one CRC pass per commit sub-wave.

The per-line journal (`store._encode_record`) serializes and checksums
every record independently — a 1k-pod bind wave pays 1k `json.dumps` +
1k `zlib.crc32` calls and hands the journal 1k separate lines.  A frame
collapses the whole sub-wave into ONE line::

    {"f": 1, "w": <wave id>, "recs": [<record>, ...], "crc": <crc32>}

with a single serialization pass and a single crc32 over the crc-less
body — the trailer splice is the same shape as the per-record codec, so
replay's "parse, pop crc, re-serialize, compare" check covers frames
with no second code path.  A frame IS a wave: it carries the wave id,
needs no terminator record, and replay applies it atomically (a torn
frame fails the line parse or the CRC and is dropped whole, exactly the
PR 8 wave-atomicity contract).  Frames interleave freely with legacy
per-line records — each is still one journal line.

Unlike legacy lines, a frame with a MISSING crc is rejected: the
crc-less acceptance in `store._record_crc_ok` exists only for journals
written before the CRC trailer landed, and no such journal can contain
a frame.

The splice + checksum hot path is optionally served by the `_hostplane`
C extension (native/hostplane.c, built by `make native-ext`); the pure
Python implementation below is the contract and stays the fallback —
both produce byte-identical lines.
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Dict, List, Optional, Tuple

try:  # optional C extension; pure Python below is the reference.
    # HOSTPLANE_DISABLE=1 forces the fallback (make test-journal runs
    # the journal suite in both modes).
    import os as _os

    if _os.environ.get("HOSTPLANE_DISABLE"):
        _hostplane = None
    else:
        import _hostplane  # type: ignore
except ImportError:  # pragma: no cover - depends on build environment
    _hostplane = None

FRAME_KEY = "f"
FRAME_VERSION = 1


def native_available() -> bool:
    return _hostplane is not None


def crc_line(s: str) -> str:
    """Append the CRC trailer to a serialized JSON object and terminate
    the line: ``{...}`` -> ``{..., "crc": N}\\n``.  Byte-compatible with
    store._encode_record's trailer."""
    if _hostplane is not None:
        return _hostplane.crc_line(s.encode()).decode()
    return '%s, "crc": %d}\n' % (s[:-1], zlib.crc32(s.encode()))


def encode_frame(wid: int, recs: List[Dict[str, Any]]) -> str:
    """One journal line for a whole sub-wave: single json.dumps pass,
    single crc32 pass."""
    return crc_line(json.dumps({FRAME_KEY: FRAME_VERSION, "w": wid,
                                "recs": recs}))


def is_frame(rec: Dict[str, Any]) -> bool:
    """True when a parsed (crc-popped) journal record is a frame."""
    return bool(rec.get(FRAME_KEY)) and isinstance(rec.get("recs"), list)


def frame_crc_ok(rec: Dict[str, Any], crc: Optional[int]) -> bool:
    """Frames REQUIRE their crc — the legacy crc-less acceptance is an
    upgrade path for pre-CRC journals, which predate framing."""
    if crc is None:
        return False
    return zlib.crc32(json.dumps(rec).encode()) == crc


def length_prefix(payload: bytes) -> bytes:
    """4-byte big-endian length header + payload: the proto transport's
    wire framing (api/protoserver, native/proto_client.cpp)."""
    if _hostplane is not None:
        return _hostplane.length_prefix(payload)
    return len(payload).to_bytes(4, "big") + payload


def split_length_prefixed(buf: bytes) -> Tuple[List[bytes], bytes]:
    """Split a byte stream into complete length-prefixed payloads plus
    the unconsumed tail (partial header or partial payload)."""
    out: List[bytes] = []
    off = 0
    n = len(buf)
    while n - off >= 4:
        ln = int.from_bytes(buf[off:off + 4], "big")
        if n - off - 4 < ln:
            break
        out.append(buf[off + 4:off + 4 + ln])
        off += 4 + ln
    return out, buf[off:]
