"""JAX kernels: tensor schema, filter masks, score kernels, assignment solves."""

# Compiled executables must survive the process: scheduling code is
# "ready at binary start" in the reference (compiled Go); ours is ready
# at second process start via the persistent jax compilation cache (set
# KUBERNETES_TPU_NO_COMPILE_CACHE=1 to opt out).  Enabled here — the
# compute root every solver path imports — rather than in the package
# __init__, so api/client/CLI consumers never pay the jax import.
from ..utils import compilecache as _compilecache

_compilecache.enable()
