"""JAX kernels: tensor schema, filter masks, score kernels, assignment solves."""
