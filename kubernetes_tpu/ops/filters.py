"""Fused feasibility kernels — the Filter extension point as boolean masks.

Replaces the reference's chunked 16-goroutine per-node Filter loop
(pkg/scheduler/schedule_one.go:574-660, framework/parallelize) with one
vectorized pass over the node axis.  Covered plugins and their reference
counterparts:

  NodeResourcesFit     fitsRequest, noderesources/fit.go:421-480
  NodeName             nodename/node_name.go:52-72
  NodeUnschedulable    nodeunschedulable/node_unschedulable.go (as the
                       synthetic unschedulable taint, see api.types.Node)
  TaintToleration      tainttoleration/taint_toleration.go Filter
  NodeAffinity         nodeaffinity/node_affinity.go Filter (required terms)
  NodePorts            nodeports/node_ports.go Filter

All functions are pure and jit/vmap/shard_map-friendly: no data-dependent
shapes, node axis last so it shards cleanly over a device mesh.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .schema import (
    OP_NEG,
    OP_POS,
    TOPO_ANY_VALUE,
    ClusterTensors,
    PodBatch,
    PreferredTable,
    SelectorTable,
)

_PAD_ID = -1  # empty id slot in expr_ids

# Taint effect rows (schema.EFFECT_INDEX)
_NO_SCHEDULE = 0
_PREFER_NO_SCHEDULE = 1
_NO_EXECUTE = 2


class PodView(NamedTuple):
    """One pod's slices out of a PodBatch (works under tracing)."""

    valid: jnp.ndarray        # bool[]
    req: jnp.ndarray          # f32[R]
    nonzero_req: jnp.ndarray  # f32[R]
    name_id: jnp.ndarray      # i32[]
    sel_idx: jnp.ndarray      # i32[]
    tol_bits: jnp.ndarray     # u32[3, TW]
    tol_all: jnp.ndarray      # bool[3]
    port_bits: jnp.ndarray    # u32[PW]
    pref_idx: jnp.ndarray     # i32[MT]
    pref_weight: jnp.ndarray  # f32[MT]


def pod_view(pods: PodBatch, i) -> PodView:
    return PodView(
        valid=pods.valid[i],
        req=pods.req[i],
        nonzero_req=pods.nonzero_req[i],
        name_id=pods.name_id[i],
        sel_idx=pods.sel_idx[i],
        tol_bits=pods.tol_bits[:, i, :],
        tol_all=pods.tol_all[:, i],
        port_bits=pods.port_bits[i],
        pref_idx=pods.pref_idx[i],
        pref_weight=pods.pref_weight[i],
    )


def _test_bits(label_bits: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Presence of each id in each node's bitset.

    label_bits: u32[N, W]; ids: i32[...]; returns bool[N, ...].
    """
    w = label_bits.shape[-1]
    word = jnp.clip(ids >> 5, 0, w - 1)
    bit = (ids & 31).astype(jnp.uint32)
    words = label_bits[:, word]                       # u32[N, ...]
    present = (words >> bit) & jnp.uint32(1)
    return (present != 0) & (ids >= 0)


def match_terms(
    cluster: ClusterTensors,
    expr_ids: jnp.ndarray,
    expr_op: jnp.ndarray,
    expr_slot: jnp.ndarray,
) -> jnp.ndarray:
    """AND-of-expressions term matching.

    expr_ids: i32[..., E, K], expr_op/expr_slot: i32[..., E] ->
    bool[..., N] with the node axis appended last.  Implements label-set
    requirement semantics (apimachinery/pkg/labels/selector.go
    Requirement.Matches): OP_POS is satisfied when any expanded id is
    present, OP_NEG when none is — which makes NotIn/DoesNotExist match
    key-absent nodes for free.

    Two id domains per expression (schema.DOMAIN_LABELS): the shared label
    bitset, or one topology slot of topo_ids (hostname/zone/region), where
    presence is value-id equality and TOPO_ANY_VALUE means 'key present'.
    """
    n = cluster.label_bits.shape[0]
    tk = cluster.topo_ids.shape[1]

    in_labels = _test_bits(cluster.label_bits, expr_ids)     # bool[N, ..., E, K]

    if tk > 0:
        slot = jnp.clip(expr_slot, 0, tk - 1)                # i32[..., E]
        topo_val = cluster.topo_ids[:, slot]                 # i32[N, ..., E]
        ids = expr_ids                                       # i32[..., E, K]
        in_topo = (topo_val[..., None] == ids) | (
            (ids == TOPO_ANY_VALUE) & (topo_val[..., None] >= 0)
        )
        in_topo = in_topo & (ids != _PAD_ID)
        present = jnp.where(
            (expr_slot >= 0)[..., None], in_topo, in_labels
        )                                                    # bool[N, ..., E, K]
    else:
        present = in_labels
    any_present = present.any(axis=-1)                       # bool[N, ..., E]
    op = jnp.broadcast_to(expr_op, any_present.shape)
    sat = jnp.where(
        op == OP_POS, any_present, jnp.where(op == OP_NEG, ~any_present, True)
    )
    all_sat = sat.all(axis=-1)                               # bool[N, ...]
    return jnp.moveaxis(all_sat, 0, -1)                      # bool[..., N]


def selector_match(cluster: ClusterTensors, sel: SelectorTable) -> jnp.ndarray:
    """Match mask for every distinct required selector: bool[S, N].

    Terms are ORed (v1.NodeSelector semantics).  Computed once per batch —
    the payoff of deduplicating selectors in the SnapshotBuilder.
    """
    term_ok = match_terms(cluster, sel.expr_ids, sel.expr_op, sel.expr_slot)  # [S, T, N]
    return (term_ok & sel.term_valid[:, :, None]).any(axis=1)                 # [S, N]


def preferred_match(cluster: ClusterTensors, pref: PreferredTable) -> jnp.ndarray:
    """Match mask for every distinct preferred term: bool[F, N]."""
    ok = match_terms(cluster, pref.expr_ids, pref.expr_op, pref.expr_slot)    # [F, N]
    return ok & pref.valid[:, None]


def fits_resources(cluster: ClusterTensors, pod: PodView) -> jnp.ndarray:
    """NodeResourcesFit: requested + pod <= allocatable, but only for
    resources the pod actually requests (fit.go:430-470 skips
    podRequest == 0; the pods-count row is always 1 so the per-pod
    capacity check rides the same comparison)."""
    return (
        (pod.req[None, :] <= 0)
        | (cluster.requested + pod.req[None, :] <= cluster.allocatable)
    ).all(axis=-1)


def ports_free(cluster: ClusterTensors, pod: PodView) -> jnp.ndarray:
    """NodePorts: claimed host ports must be free on the node."""
    return ~((cluster.port_bits & pod.port_bits[None, :]).any(axis=-1))


def static_feasible_for_pod(
    cluster: ClusterTensors, pod: PodView, sel_match: jnp.ndarray
) -> jnp.ndarray:
    """The placement-independent Filter slice for one pod: bool[N].
    NodeName + TaintToleration + NodeAffinity + node validity — everything
    that depends only on labels/taints/names, which placements never
    change.  The solver hoists this out of its scan per pod *class*
    (schema.PodBatch.class_id); resources (fits_resources) and ports
    (ports_free, when pods claim ports) stay dynamic."""
    n = cluster.allocatable.shape[0]

    # NodeName
    name_ok = (pod.name_id == -1) | (cluster.name_id == pod.name_id)

    # TaintToleration over NoSchedule / NoExecute (PreferNoSchedule only
    # affects scoring).  Untolerated taint present => infeasible.
    def effect_ok(e: int) -> jnp.ndarray:
        untolerated = (
            cluster.taint_bits[e] & ~pod.tol_bits[e][None, :]
        ).any(axis=-1)
        return pod.tol_all[e] | ~untolerated

    taints_ok = effect_ok(_NO_SCHEDULE) & effect_ok(_NO_EXECUTE)

    # NodeAffinity / nodeSelector
    sel_ok = jnp.where(
        pod.sel_idx < 0,
        jnp.ones(n, dtype=bool),
        sel_match[jnp.clip(pod.sel_idx, 0, sel_match.shape[0] - 1)],
    )

    return cluster.node_valid & pod.valid & name_ok & taints_ok & sel_ok


def feasible_for_pod(
    cluster: ClusterTensors, pod: PodView, sel_match: jnp.ndarray
) -> jnp.ndarray:
    """The fused Filter chain for one pod against every node: bool[N].

    sel_match is the precomputed [S, N] selector mask from selector_match().
    """
    return (
        static_feasible_for_pod(cluster, pod, sel_match)
        & fits_resources(cluster, pod)
        & ports_free(cluster, pod)
    )


def feasible_batch(
    cluster: ClusterTensors,
    pods: PodBatch,
    sel: SelectorTable,
) -> jnp.ndarray:
    """Filter the whole batch at once: bool[P, N].

    This is the embarrassingly-parallel variant (no inter-pod interaction);
    the greedy solve in ops.assign re-evaluates per step instead, because
    placements change `requested`.
    """
    cluster, pods, sel = jax.tree.map(jnp.asarray, (cluster, pods, sel))
    sm = selector_match(cluster, sel)
    p = pods.req.shape[0]

    def one(i):
        return feasible_for_pod(cluster, pod_view(pods, i), sm)

    return jax.vmap(one)(jnp.arange(p))
