"""Dense tensor schema for cluster state + the snapshot builder.

This is the tensorization of the reference scheduler's per-node bookkeeping
(`framework.NodeInfo`, pkg/scheduler/framework/types.go:542-602) and of the
per-pod scheduling spec.  Everything the Filter/Score kernels consume lives
in statically-shaped arrays:

  ClusterTensors   one row per node: resource vectors + packed bitsets
  PodBatch         one row per pending pod
  SelectorTable    deduplicated required-node-affinity selectors (pods in a
                   real batch overwhelmingly share selectors — a Deployment's
                   pods are identical — so match masks are computed once per
                   distinct selector, [S, N], then gathered per pod)
  PreferredTable   deduplicated preferred scheduling terms for scoring

String state (labels, taints, ports, names, topology values) is interned
exactly via vocabularies (kubernetes_tpu.utils.vocab) and represented as
uint32 bitsets; selector expressions are expanded host-side into explicit
id sets, turning all matching on device into bit tests.  `Exists`/`NotIn`
operators expand against the *current* vocabulary, which is why pod-side
tables are rebuilt per batch while node-side bitsets persist.

Shapes are padded to power-of-two buckets (utils.vocab.pad_dim) so repeated
solves at similar scale hit the XLA compile cache.
"""

from __future__ import annotations

import heapq
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

# f32 represents integers exactly up to 2^24.  The score kernels form
# `quantity * 100` products (ops/scores.py), so any allocatable value
# above this threshold can drift Least/MostAllocated floors by ±1 vs the
# reference's int64 math.  Validated at node encode; see _check_f32_exact.
F32_EXACT_LIMIT = float(1 << 24) / 100.0

from ..api import types as api
from ..utils import vocab as vb

# Resource axis layout: fixed head + discovered scalar resources.
RESOURCE_CPU = 0          # milli-cores
RESOURCE_MEMORY = 1       # bytes
RESOURCE_EPH = 2          # bytes
RESOURCE_PODS = 3         # pod-count capacity (AllowedPodNumber in the
                          # reference's Resource struct, types.go:593-602)
FIXED_RESOURCES = (api.CPU, api.MEMORY, api.EPHEMERAL_STORAGE, api.PODS)

# Taint-effect axis
EFFECT_INDEX = {api.NO_SCHEDULE: 0, api.PREFER_NO_SCHEDULE: 1, api.NO_EXECUTE: 2}

# Device resource units.  Byte-denominated resources are carried in MiB so
# every realistic quantity (and the products `quantity * 100` the scorers
# form) stays inside float32's exact-integer range (2^24): 64 GiB -> 65536.
# cpu stays in milli-cores, counts stay counts.  This keeps the f32 score
# kernels bit-faithful to the reference's int64 math for MiB-aligned
# requests, which is what real specs use.
DEVICE_UNIT_DIVISOR = {api.MEMORY: 1 << 20, api.EPHEMERAL_STORAGE: 1 << 20}

# Selector expression ops on device
OP_PAD = 0   # slot unused: contributes True
OP_POS = 1   # satisfied iff any listed id present on the node
OP_NEG = 2   # satisfied iff no listed id present on the node

# Expression domains.  Labels that are unique-per-node (hostname) or
# enumerable-per-key (zone, region) live in topo_ids[N, TK] as dense value
# ids rather than in the shared label bitset — a 50k-node cluster would
# otherwise need 50k bits of hostname vocabulary on every node.  Selector
# expressions over those keys evaluate against the topo slot; everything
# else evaluates against the label bitset.
DOMAIN_LABELS = -1          # expr_slot value meaning "label bitset domain"
TOPO_ANY_VALUE = -2         # id meaning "key present with any value" (Exists)


class ClusterTensors(NamedTuple):
    """Per-node state. N = padded node count, R = resource axis,
    LW/TW/PW = label/taint/port bitset words, TK = tracked topology keys."""

    allocatable: np.ndarray        # f32[N, R]
    requested: np.ndarray          # f32[N, R]   actual requests (BalancedAllocation)
    nonzero_requested: np.ndarray  # f32[N, R]   with scoring defaults (LeastAllocated)
    node_valid: np.ndarray         # bool[N]
    name_id: np.ndarray            # i32[N]
    label_bits: np.ndarray         # u32[N, LW]
    taint_bits: np.ndarray         # u32[3, N, TW]  effect-major
    port_bits: np.ndarray          # u32[N, PW]
    topo_ids: np.ndarray           # i32[N, TK]  per-key value id, -1 absent
    image_bits: np.ndarray         # u32[N, IW]  images present on the node
    # TPU slice topology (api.LABEL_TPU_* node labels; ops/slices.py):
    slice_id: np.ndarray           # i32[N]  slice/pool membership, -1 none
    torus_coords: np.ndarray       # i32[N, 4]  in-slice (x, y, z, core), -1 absent
    slice_dims: np.ndarray         # i32[N, 3]  owning slice's torus extent, 0 absent
    slice_pos: np.ndarray          # i32[N]  linear in-slice position, -1 absent


class SelectorTable(NamedTuple):
    """S distinct required-node selectors in OR-of-AND form."""

    expr_ids: np.ndarray   # i32[S, T, E, K]  expanded ids, -1 pad
    expr_op: np.ndarray    # i32[S, T, E]     OP_PAD/OP_POS/OP_NEG
    expr_slot: np.ndarray  # i32[S, T, E]     DOMAIN_LABELS or topo slot
    term_valid: np.ndarray  # bool[S, T]


class PreferredTable(NamedTuple):
    """F distinct preferred NodeSelectorTerms (AND of expressions)."""

    expr_ids: np.ndarray   # i32[F, E, K]
    expr_op: np.ndarray    # i32[F, E]
    expr_slot: np.ndarray  # i32[F, E]
    valid: np.ndarray      # bool[F]


class SpreadTable(NamedTuple):
    """C distinct topology-spread constraint instances (constraint spec +
    owner namespace/selector/key-set, since eligibility is owner-scoped).
    Z = padded max topology-value vocabulary size.

    Counting state lives as per-node match vectors ([C, N]); the solver
    scatter-adds them into per-topology-value counts on device (the
    tensorization of preFilterState.TpPairToMatchNum,
    podtopologyspread/filtering.go + scoring.go)."""

    valid: np.ndarray         # bool[C]
    slot: np.ndarray          # i32[C]   topology-key slot in topo_ids
    max_skew: np.ndarray      # f32[C]
    min_domains: np.ndarray   # f32[C]   0 = unset (filtering.go minMatchNum)
    hard: np.ndarray          # bool[C]  DoNotSchedule (filter) vs ScheduleAnyway (score)
    owner_sel_idx: np.ndarray  # i32[C]  owner pod's SelectorTable row, -1 none
    owner_keys: np.ndarray    # bool[C, TK] topology keys the owner's constraints use
    node_matches: np.ndarray  # f32[C, N] bound pods on node n matching constraint c
    pod_matches: np.ndarray   # bool[P, C] pending pod p matches c's selector+namespace
    pod_idx: np.ndarray       # i32[P, MC] constraint rows per pod, -1 pad


class TermTable(NamedTuple):
    """T distinct inter-pod (anti-)affinity terms: batch pods' required
    affinity + anti-affinity terms, plus bound pods' anti-affinity terms
    (needed for the existing-pods-anti-affinity direction,
    interpodaffinity/filtering.go:306-366).

    counts_match[t, v] (# pods whose labels+ns match term t in topology v)
    and counts_owner[t, v] (# pods *carrying* t as an anti-affinity term)
    are assembled on device from the per-node vectors below and updated
    in-scan as the solver places pods."""

    valid: np.ndarray            # bool[T]
    slot: np.ndarray             # i32[T]   topology-key slot
    node_matches: np.ndarray     # f32[T, N] bound pods on n matching term t
    node_owners: np.ndarray      # f32[T, N] bound pods on n owning anti-term t
    matches_incoming: np.ndarray  # u32[P, ceil(T/32)] packed: pod p matches term t
                                  # (bit t%32 of word t//32 — transfer-
                                  # efficient; unpack on device as needed)
    aff_idx: np.ndarray          # i32[P, MA] pod's required affinity terms
    anti_idx: np.ndarray         # i32[P, MA] pod's required anti-affinity terms
    self_match_all: np.ndarray   # bool[P] pod matches all its own affinity terms


class PodBatch(NamedTuple):
    """Per-pending-pod state. P = padded batch size, MT = preferred slots.

    class_id/class_rep: pods are grouped into *static equivalence classes*
    — pods whose placement-independent state (node name, selector,
    tolerations, ports, preferred terms) is byte-identical.  Real batches
    overwhelmingly collapse (a Deployment's replicas are one class), so
    the solver hoists static feasibility and raw score rows out of its
    scan as [C, N] tables instead of [P, N].  class_rep[c] is the index of
    one representative pod of class c (-1 pad).

    The class axis FACTORIZES (joint = spec × constraint): class_id is
    the joint axis (distinct (spec, constraint-identity) pairs — what the
    auction's tie machinery needs), while the expensive per-row kernels
    depend on only one factor each: static feasibility / resource fit /
    raw scores on the SPEC factor (spec_rep, typically a handful of
    rows), spread / inter-pod filters on the CONSTRAINT factor
    (cons_rep, one row per distinct service-shaped constraint set).
    joint_spec/joint_cons map each joint class to its factors, so the
    joint-axis combine is pure gathers + elementwise — 200 services × 5
    pod shapes costs 205 heavy rows, not 1000."""

    valid: np.ndarray        # bool[P]
    req: np.ndarray          # f32[P, R]
    nonzero_req: np.ndarray  # f32[P, R]
    name_id: np.ndarray      # i32[P]  -1 none, -2 names an unknown node
    sel_idx: np.ndarray      # i32[P]  -1 no required selector
    tol_bits: np.ndarray     # u32[3, P, TW]
    tol_all: np.ndarray      # bool[3, P]
    port_bits: np.ndarray    # u32[P, PW]
    pref_idx: np.ndarray     # i32[P, MT]  rows of PreferredTable, -1 pad
    pref_weight: np.ndarray  # f32[P, MT]
    class_id: np.ndarray     # i32[P]  joint equivalence class per pod
    class_rep: np.ndarray    # i32[C]  representative pod index, -1 pad
    priority: np.ndarray     # f32[P]  pod priority (queuesort order)
    group_id: np.ndarray     # i32[P]  gang/coscheduling group, -1 none
    pod_shape: np.ndarray    # i32[P, 3]  requested carve-out extent, 0 none
    spec_rep: np.ndarray     # i32[Cs] representative pod per spec class
    joint_spec: np.ndarray   # i32[C]  spec class of each joint class
    cons_rep: np.ndarray     # i32[Cc] representative pod per constraint class
    joint_cons: np.ndarray   # i32[C]  constraint class of each joint class


class PrefPodTable(NamedTuple):
    """Preferred inter-pod (anti-)affinity — the SCORING half of the
    O(pods²) pairwise family (interpodaffinity/scoring.go), tensorized as
    deduplicated term rows with per-node match data:

      node_counts[u, n]   bound pods matching row u ON node n (prep
                          domain-sums it over n's topology value) — the
                          incoming-pod's-terms direction
      owner_weight[u, n]  Σ signed weights of bound pods on node n whose
                          OWN term is row u (preferred terms carry their
                          weight, required affinity terms carry
                          hardPodAffinityWeight) — the existing-pods'-
                          terms direction, applied when the incoming pod
                          matches the row
      matches_incoming[i, u]  pending pod i matches row u's selector
      pod_idx/pod_weight[i, j]  pending pod i's own preferred rows with
                          signed weights (anti ⇒ negative)
    """

    valid: np.ndarray            # bool[U]
    slot: np.ndarray             # i32[U] topology-key slot
    node_counts: np.ndarray      # f32[U, N]
    owner_weight: np.ndarray     # f32[U, N]
    matches_incoming: np.ndarray  # bool[P, U]
    pod_idx: np.ndarray          # i32[P, MA] -1 pad
    pod_weight: np.ndarray       # f32[P, MA] signed


class ImageTable(NamedTuple):
    """ImageLocality inputs (imagelocality/image_locality.go): interned
    image sizes and each pending pod's image ids; presence rides
    ClusterTensors.image_bits."""

    sizes: np.ndarray         # f32[I_pad] bytes (0 = unknown image)
    pod_ids: np.ndarray       # i32[P, MI] -1 pad
    n_containers: np.ndarray  # f32[P] image-bearing containers (incl init)


class Snapshot(NamedTuple):
    cluster: ClusterTensors
    pods: PodBatch
    selectors: SelectorTable
    preferred: PreferredTable
    spread: SpreadTable
    terms: TermTable
    prefpod: PrefPodTable
    images: ImageTable


def num_groups(snapshot: Snapshot) -> int:  # graftlint: disable=purity -- host-side prep on the pre-transfer snapshot
    """Static gang-group count for this batch (0 = no gangs).  The one
    source of truth for the group-id convention (-1 = ungrouped, dense
    ids from 0): both solvers' all-or-nothing post-passes key off it."""
    return int(np.asarray(snapshot.pods.group_id).max()) + 1


@dataclass
class SnapshotLimits:
    """Static capacities.  All are *caps*, checked at encode time with a
    clear OverflowError; raise them (new executable) when a workload
    exceeds them."""

    max_terms: int = 4          # T: NodeSelectorTerms per selector
    max_exprs: int = 8          # E: expressions per term (incl. node_selector)
    max_ids_per_expr: int = 16  # K: expanded ids per expression
    max_preferred: int = 4      # MT: preferred terms per pod
    max_spread_per_pod: int = 4  # MC: topology spread constraints per pod
    max_pod_terms: int = 4      # MA: required (anti-)affinity terms per pod
    # scoring weight of bound pods' REQUIRED affinity terms in the
    # preferred-interpod score (apis/config HardPodAffinityWeight default)
    hard_pod_affinity_weight: float = 1.0
    label_capacity: int = 4096
    image_capacity: int = 512   # distinct container images tracked
    max_pod_images: int = 8     # container images per pod (ImageLocality)
    taint_capacity: int = 256
    port_capacity: int = 2048
    topology_keys: Tuple[str, ...] = (api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)
    min_nodes: int = 8
    min_pods: int = 8
    # largest per-axis torus extent a slice may declare
    # (api.LABEL_TPU_TOPOLOGY) — bounds the ops/slices.py value-space
    # grid at [S, D, D, D]; an over-cap label raises at encode
    max_slice_dim: int = 16

    @property
    def label_words(self) -> int:
        return vb.words_for(self.label_capacity)

    @property
    def taint_words(self) -> int:
        return vb.words_for(self.taint_capacity)

    @property
    def port_words(self) -> int:
        return vb.words_for(self.port_capacity)

    @property
    def image_words(self) -> int:
        return vb.words_for(self.image_capacity)


@dataclass
class SnapshotMeta:
    """Host-side sidecar of a Snapshot: real counts and decode tables,
    plus the routing statics the dispatcher needs (derived from the HOST
    arrays at encode time — probing a device-resident snapshot costs one
    tunnel round-trip per array)."""

    num_nodes: int
    num_pods: int
    node_names: List[str]
    resource_names: List[str]
    limits: SnapshotLimits
    topo_z: int = 1  # padded max topology-value vocab size (the Z axis)
    # routing statics (filled by TPUBatchScheduler.encode_pending; None
    # means "recompute from the snapshot")
    features: Optional[object] = None      # assign.FeatureFlags
    topo_split: Optional[tuple] = None     # (z_spread, z_terms)
    n_groups: Optional[int] = None
    tie_k: Optional[int] = None
    # solve-route statics derived at encode time while the arrays are
    # host-resident: the chosen solver route and, for wavefront-routed
    # batches, the host-planned wave partition (assign.WavePlan)
    route: Optional[str] = None
    wave_plan: Optional[object] = None
    # persistent content-signature ids of this batch's selector /
    # preferred table rows (SnapshotBuilder._stable_id): batch-local
    # row INDICES are not comparable across batches, these are — the
    # PartialsCache keys pod classes on them (models/partials.py)
    sel_stable: Tuple[int, ...] = ()
    pref_stable: Tuple[int, ...] = ()
    # warm-start per-class statics gathered from the device-resident
    # PartialsCache (ops.partials.ClassStatics; set by
    # TPUBatchScheduler.encode_pending, consumed by _dispatch — None
    # means cold: the solver recomputes class_statics in-program)
    statics: Optional[object] = None
    # (mirror EpochStamp, partials EpochStamp) pair recorded when
    # `statics` was gathered — consumed by the GRAFTLINT_COHERENCE
    # auditor's dispatch-time cross-resident audit (analysis/epochs.py);
    # None when the solve is cold or the auditor is disarmed
    coherence_stamp: Optional[tuple] = None

    def node_name(self, idx: int) -> Optional[str]:
        if 0 <= idx < self.num_nodes:
            return self.node_names[idx]
        return None


class SnapshotBuilder:
    """Encodes api.Node / api.Pod objects into Snapshot tensors.

    Vocabularies are append-only and owned by the builder, so successive
    snapshots from the same builder keep node bitsets comparable.  For
    O(changed) per-batch encode, pair with ClusterState (the incremental
    analogue of the reference cache's generation-tracked UpdateSnapshot,
    pkg/scheduler/internal/cache/cache.go:185) and build_from_state().
    """

    def __init__(self, limits: Optional[SnapshotLimits] = None):
        self.limits = limits or SnapshotLimits()
        self.label_vocab = vb.PairVocab()
        self.taint_vocab = vb.PairVocab()
        self.port_vocab = vb.Vocab()
        self.name_vocab = vb.Vocab()
        # image name -> id (capped; images beyond image_capacity are
        # ignored for scoring rather than erroring — locality is a
        # best-effort score, not a correctness constraint)
        self.image_vocab = vb.Vocab()
        self.image_sizes: Dict[int, float] = {}
        self.topo_vocabs: Dict[str, vb.Vocab] = {
            k: vb.Vocab() for k in self.limits.topology_keys
        }
        # slice/pool names (api.LABEL_TPU_SLICE) -> dense slice ids for
        # ClusterTensors.slice_id; append-only like every other vocab
        self.slice_vocab = vb.Vocab()
        # persistent selector/preferred signature registry: a content
        # signature's id is stable across batches (append-only), so
        # consumers keying on selector CONTENT (the PartialsCache's
        # class signatures) survive the per-batch table rebuild
        self._sig_registry: Dict[tuple, int] = {}
        # (sel row -> stable id, pref row -> stable id) of the most
        # recent _build_pods — read under the same cache lock by
        # build/build_from_state into SnapshotMeta
        self._last_stable: Tuple[tuple, tuple] = ((), ())
        # label/topology keys any encoded requirement has ever expanded
        # against (append-only).  Expansion results depend on the CURRENT
        # id set under the requirement's key (_expand_requirement), so a
        # consumer caching expanded rows (the PartialsCache) goes stale
        # exactly when one of THESE keys gains ids — not when an
        # unreferenced vocab entry (e.g. a new node's hostname pair)
        # lands.  expansion_watermark() is the cache's flush key.
        self._expansion_keys: set = set()
        self.scalar_resources: List[str] = []
        self._scalar_index: Dict[str, int] = {}
        # Optional per-pod requirement hook: (pod) -> (extra required
        # NodeSelector | None, extra scalar requests).  The VolumeBinding
        # integration point: volume topology becomes selector terms and
        # attach limits become scalar resources, so the device kernels
        # need no volume-specific code (scheduler/volumebinding.py).
        self.pod_transform = None
        # Optional per-pod carve-out shape hook: (pod) -> (a, b, c) or
        # None.  The device-claims integration point: an unallocated
        # topology-shaped ResourceClaim gives its prospective carrier a
        # carve-out shape (scheduler/deviceclaims.py pod_shape) on top
        # of any pod.spec.tpu_topology request.
        self.pod_shape_hook = None
        # Columnar fast path for build_from_state: persistent cross-batch
        # spec-row store + vectorized batch assembly
        # (_build_pods_columnar).  The per-object _build_pods stays the
        # parity oracle — flip this off to force it.
        self.columnar = True
        self._spec_store = _PodSpecStore()

    def _transform(self, pod: api.Pod):
        if self.pod_transform is None:
            return None, None
        return self.pod_transform(pod)

    def _stable_id(self, sig: tuple) -> int:
        """Append-only id of a content signature (selector / preferred
        term) — stable for the builder's lifetime, unlike the per-batch
        dedup table indices."""
        i = self._sig_registry.get(sig)
        if i is None:
            i = self._sig_registry[sig] = len(self._sig_registry)
        return i

    def expansion_watermark(self) -> tuple:
        """Per-key id counts for every label/topology key some encoded
        requirement has expanded against — the exact staleness key for
        consumers caching expanded selector/preferred rows (the
        PartialsCache).  Grows only when (a) a referenced key gains ids
        (its Exists/In/NotIn/Gt/Lt expansions may now differ) or (b) a
        new key becomes referenced; vocab growth under UNREFERENCED keys
        — e.g. the hostname pair every autoscaled node interns — leaves
        the watermark unchanged, so sustained node churn does not flush
        warm caches."""
        parts = []
        for key in sorted(self._expansion_keys):
            voc = self.topo_vocabs.get(key)
            if voc is not None:
                parts.append((key, len(voc)))
            else:
                parts.append(
                    (key, len(self.label_vocab.ids_for_key(key)))
                )
        return tuple(parts)

    def pod_carveout_shape(self, pod: api.Pod) -> Tuple[int, int, int]:
        """The pod's requested carve-out extent: pod.spec.tpu_topology,
        else the shape hook's answer (topology-shaped device claims),
        else (0, 0, 0) — the one derivation encode and policy surfaces
        share."""
        shape = api.parse_topology(pod.spec.tpu_topology)
        if shape is None and self.pod_shape_hook is not None:
            shape = self.pod_shape_hook(pod)
        if shape is None:
            return (0, 0, 0)
        if max(shape) > self.limits.max_slice_dim:
            raise OverflowError(
                f"pod {pod.meta.name!r}: carve-out extent {shape} exceeds "
                f"max_slice_dim={self.limits.max_slice_dim}"
            )
        return tuple(int(d) for d in shape)

    def effective_requests(self, pod: api.Pod) -> Dict[str, int]:
        """resource_requests plus the transform's extra scalar requests
        (e.g. attach-limit counts) — the request dict every encode and
        usage-accounting path must agree on."""
        req = pod.resource_requests()
        _sel, extra = self._transform(pod)
        if extra:
            req = dict(req)
            for k, v in extra.items():
                req[k] = req.get(k, 0) + v
        return req

    # -- resource axis ----------------------------------------------------

    @property
    def resource_names(self) -> List[str]:
        return list(FIXED_RESOURCES) + self.scalar_resources

    def _resource_index(self, name: str, grow: bool) -> Optional[int]:
        try:
            return FIXED_RESOURCES.index(name)
        except ValueError:
            pass
        idx = self._scalar_index.get(name)
        if idx is None and grow:
            idx = len(FIXED_RESOURCES) + len(self.scalar_resources)
            self._scalar_index[name] = idx
            self.scalar_resources.append(name)
        return idx

    def _resource_vector(self, requests: Dict[str, int], r: int, grow: bool = True) -> np.ndarray:
        out = np.zeros(r, dtype=np.float32)
        for name, val in requests.items():
            idx = self._resource_index(name, grow)
            if idx is not None and idx < r:
                out[idx] = float(val) / DEVICE_UNIT_DIVISOR.get(name, 1)
        return out

    # -- vocab interning ---------------------------------------------------

    def _intern_node_strings(self, nodes: Sequence[api.Node]) -> None:
        # One bulk intern_many per vocabulary instead of a per-string
        # call inside the node loop: the id SET interned is identical,
        # and everything downstream that matters is set-membership (the
        # pod-side Exists/NotIn/toleration expansions), so the slight
        # id-assignment reordering vs the per-string loop is invisible
        # within a builder.
        topo = self.topo_vocabs
        names: List[str] = []
        pairs: List[Tuple[str, str]] = []
        taints: List[Tuple[str, str]] = []
        for node in nodes:
            names.append(node.meta.name)
            for k, v in node.meta.labels.items():
                if k in topo:
                    topo[k].intern(v)
                else:
                    pairs.append((k, v))
            for t in node.effective_taints():
                taints.append((t.key, t.value))
            for img in node.status.images:
                self._intern_image(img.names, img.size_bytes)
        self.name_vocab.intern_many(names)
        self.label_vocab.intern_many(pairs)
        self.taint_vocab.intern_many(taints)

    @staticmethod
    def _normalize_image(name: str) -> str:
        """normalizedImageName (imagelocality/image_locality.go): an
        untagged, undigested name means ':latest'."""
        tail = name.rsplit("/", 1)[-1]
        if ":" not in tail and "@" not in tail:
            return name + ":latest"
        return name

    def _intern_image(self, names, size_bytes: float = 0.0) -> int:
        """Intern an image under ALL its (normalized) names — tags and
        digests alias one id; returns the id or -1 when the vocabulary is
        full."""
        if not names:
            return -1
        names = [self._normalize_image(n) for n in names]
        known = [self.image_vocab.get(n) for n in names]
        ident = next((i for i in known if i >= 0), -1)
        if ident < 0:
            if len(self.image_vocab) >= self.limits.image_capacity:
                return -1
            ident = self.image_vocab.intern(names[0])
        for n in names:
            self.image_vocab.alias(n, ident)
        if size_bytes:
            self.image_sizes[ident] = max(
                self.image_sizes.get(ident, 0.0), float(size_bytes)
            )
        return ident

    def _image_row(self, node: api.Node, row: np.ndarray) -> None:
        row[:] = 0
        for img in node.status.images:
            ident = self._intern_image(img.names, img.size_bytes)
            if ident >= 0:
                vb.set_bit(row, ident)

    def image_table(self, pods: Sequence[api.Pod], p_dim: int) -> ImageTable:
        mi = self.limits.max_pod_images
        ids = np.full((p_dim, mi), -1, dtype=np.int32)
        n_containers = np.zeros(p_dim, dtype=np.float32)
        for i, pod in enumerate(pods):
            imgs = [
                c.image
                for c in pod.spec.init_containers + pod.spec.containers
                if c.image
            ]
            if len(imgs) > mi:
                raise OverflowError(
                    f"pod has {len(imgs)} container images, exceeding "
                    f"max_pod_images={mi}"
                )
            # the reference scales maxThreshold by the pod's TOTAL
            # image-bearing container count, known to the cluster or not
            n_containers[i] = len(imgs)
            for j, name in enumerate(imgs):
                ids[i, j] = self.image_vocab.get(self._normalize_image(name))
        i_pad = vb.pad_dim(max(len(self.image_vocab), 1), 1)
        sizes = np.zeros(i_pad, dtype=np.float32)
        for ident, sz in self.image_sizes.items():
            if ident < i_pad:
                sizes[ident] = sz
        return ImageTable(sizes=sizes, pod_ids=ids, n_containers=n_containers)

    # -- selector expansion ------------------------------------------------

    def _expand_requirement(self, r: api.Requirement) -> Tuple[int, int, List[int]]:
        """Return (op, domain slot, expanded ids).  Expansion is exact
        against the current vocabulary: a value no node carries simply
        yields no id, which under OP_POS means 'matches nowhere' — precisely
        the reference semantics of an In clause naming an absent value.

        Expressions over topology keys evaluate against topo_ids[:, slot]
        (see DOMAIN_LABELS); everything else against the label bitset."""
        self._expansion_keys.add(r.key)
        try:
            slot = self.limits.topology_keys.index(r.key)
            voc = self.topo_vocabs[r.key]

            def lookup(v: str) -> int:
                return voc.get(v)

            def all_ids() -> List[int]:
                return [TOPO_ANY_VALUE]

            def value_of(i: int) -> str:
                return voc.item(i)

            id_range = range(len(voc))
        except ValueError:
            slot = DOMAIN_LABELS
            voc = None

            def lookup(v: str) -> int:
                return self.label_vocab.get((r.key, v))

            def all_ids() -> List[int]:
                return self.label_vocab.ids_for_key(r.key)

            def value_of(i: int) -> str:
                return self.label_vocab.item(i)[1]

            id_range = self.label_vocab.ids_for_key(r.key)

        if r.op == api.OP_IN:
            ids = [lookup(v) for v in r.values]
            return OP_POS, slot, [i for i in ids if i >= 0]
        if r.op == api.OP_NOT_IN:
            ids = [lookup(v) for v in r.values]
            return OP_NEG, slot, [i for i in ids if i >= 0]
        if r.op == api.OP_EXISTS:
            return OP_POS, slot, all_ids()
        if r.op == api.OP_DOES_NOT_EXIST:
            return OP_NEG, slot, all_ids()
        if r.op in (api.OP_GT, api.OP_LT):
            # Gt/Lt compare integer label values; expand exactly against the
            # known value set for the key (the vocab holds every value
            # present in the cluster, so this stays exact).  An unparseable
            # bound means the requirement matches nothing (not an encode
            # failure — one malformed spec must not sink the whole batch).
            ids: List[int] = []
            try:
                bound = int(r.values[0]) if r.values else None
            except ValueError:
                bound = None
            if bound is None:
                return OP_POS, slot, ids
            for i in id_range:
                try:
                    num = int(value_of(i))
                except ValueError:
                    continue
                if (r.op == api.OP_GT and num > bound) or (r.op == api.OP_LT and num < bound):
                    ids.append(i)
            return OP_POS, slot, ids
        raise ValueError(f"unsupported selector operator {r.op}")

    def _encode_term(
        self, exprs: Sequence[api.Requirement], e_cap: int, k_cap: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if len(exprs) > e_cap:
            raise OverflowError(
                f"{len(exprs)} expressions in one term exceed max_exprs={e_cap}"
            )
        ids = np.full((e_cap, k_cap), -1, dtype=np.int32)
        ops = np.zeros(e_cap, dtype=np.int32)
        slots = np.full(e_cap, DOMAIN_LABELS, dtype=np.int32)
        for j, r in enumerate(exprs):
            op, slot, expanded = self._expand_requirement(r)
            ops[j] = op
            slots[j] = slot
            ids[j] = vb.pad_ids(expanded, k_cap)
        return ids, ops, slots

    # -- pod pieces --------------------------------------------------------

    def _encode_tolerations(
        self, tols: Sequence[api.Toleration]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Expand tolerations into per-effect tolerated-taint bitsets.
        Matching semantics follow v1.Toleration.ToleratesTaint
        (api/core/v1/toleration.go): empty effect spans all effects, empty
        key + Exists tolerates everything, Exists-with-key tolerates every
        value of the key."""
        lim = self.limits
        bits = np.zeros((3, lim.taint_words), dtype=np.uint32)
        tol_all = np.zeros(3, dtype=bool)
        for t in tols:
            effects = range(3) if not t.effect else [EFFECT_INDEX[t.effect]]
            if not t.key:
                if t.op == api.OP_EXISTS:
                    for e in effects:
                        tol_all[e] = True
                continue
            if t.op == api.OP_EXISTS:
                ids = self.taint_vocab.ids_for_key(t.key)
            else:
                i = self.taint_vocab.get((t.key, t.value))
                ids = [i] if i >= 0 else []
            for e in effects:
                for i in ids:
                    vb.set_bit(bits[e], i)
        return bits, tol_all

    def _encode_ports(self, ports: Sequence[Tuple[str, str, int]]) -> np.ndarray:
        """Intern (protocol, port) claims.  Host-IP specificity is folded to
        the wildcard (conservative: two pods claiming the same port on
        *different* specific IPs are treated as conflicting; the reference's
        exact rule is nodeports/node_ports.go:130-150).  Exact-IP support
        rides the host-side fallback once needed."""
        bits = np.zeros(self.limits.port_words, dtype=np.uint32)
        for proto, _ip, port in ports:
            vb.set_bit(bits, self.port_vocab.intern((proto, port)))
        return bits

    # -- build -------------------------------------------------------------

    def build(
        self,
        nodes: Sequence[api.Node],
        pending_pods: Sequence[api.Pod],
        bound_pods: Sequence[api.Pod] = (),
        num_nodes_hint: int = 0,
        num_pods_hint: int = 0,
    ) -> Tuple[Snapshot, SnapshotMeta]:
        lim = self.limits

        # Interning order matters: node strings first, so pod-side
        # Exists/NotIn expansions and toleration expansions see every pair
        # present in the cluster.
        self._intern_node_strings(nodes)
        for p in bound_pods:
            self._resource_vector(self.effective_requests(p), 0, grow=True)
        for p in pending_pods:
            self._resource_vector(self.effective_requests(p), 0, grow=True)

        r = len(self.resource_names)
        n = vb.pad_dim(max(len(nodes), num_nodes_hint), lim.min_nodes)
        p_dim = vb.pad_dim(max(len(pending_pods), num_pods_hint), lim.min_pods)

        index_by_name = {nd.meta.name: i for i, nd in enumerate(nodes)}
        cluster = self._build_cluster(nodes, bound_pods, n, r, index_by_name)
        pods, sel, pref, sel_index = self._build_pods(pending_pods, p_dim, r)
        bound_by_node = [
            (p, index_by_name[p.spec.node_name])
            for p in bound_pods
            if p.spec.node_name in index_by_name
        ]
        spread, terms, prefpod = self._build_constraints(
            pending_pods, bound_by_node, sel_index, n, p_dim
        )
        images = self.image_table(pending_pods, p_dim)
        pods = _refine_classes(pods, spread, terms, prefpod, images)
        meta = SnapshotMeta(
            num_nodes=len(nodes),
            num_pods=len(pending_pods),
            node_names=[nd.meta.name for nd in nodes],
            resource_names=self.resource_names,
            limits=lim,
            topo_z=self._topo_z(),
        )
        meta.sel_stable, meta.pref_stable = self._last_stable
        return Snapshot(
            cluster, pods, sel, pref, spread, terms, prefpod, images
        ), meta

    def _topo_z(self) -> int:
        return vb.pad_dim(
            max([len(v) for v in self.topo_vocabs.values()] or [1]), 1
        )

    def build_from_state(
        self,
        state: "ClusterState",
        pending_pods: Sequence[api.Pod],
        num_pods_hint: int = 0,
    ) -> Tuple[Snapshot, SnapshotMeta]:
        """Per-batch encode against an incremental ClusterState: only the
        pending pods (and their constraint tables) are encoded; cluster
        tensors are O(1) views of the state's arrays.  The incremental
        UpdateSnapshot analogue (cache.go:185-260) — per-batch cost is
        O(pending + changed), not O(cluster)."""
        if state.builder is not self:
            raise ValueError("state was built by a different SnapshotBuilder")
        # one effective-requests derivation per pod for the whole build:
        # the intern pass here and the columnar signature pass reuse it
        eff_list = [self.effective_requests(p) for p in pending_pods]
        for eff in eff_list:
            self._resource_vector(eff, 0, grow=True)
        state.ensure_resources()
        r = len(self.resource_names)
        cluster = state.tensors()
        n = cluster.allocatable.shape[0]
        p_dim = vb.pad_dim(
            max(len(pending_pods), num_pods_hint), self.limits.min_pods
        )
        pods, sel, pref, sel_index = (
            self._build_pods_columnar(pending_pods, p_dim, r, eff_list)
            if self.columnar
            else self._build_pods(pending_pods, p_dim, r)
        )
        spread, terms, prefpod = self._build_constraints(
            pending_pods, state.bound_pods(), sel_index, n, p_dim
        )
        images = self.image_table(pending_pods, p_dim)
        pods = _refine_classes(pods, spread, terms, prefpod, images)
        meta = SnapshotMeta(
            num_nodes=state._high,
            num_pods=len(pending_pods),
            node_names=list(state.node_names),
            resource_names=self.resource_names,
            limits=self.limits,
            topo_z=self._topo_z(),
        )
        meta.sel_stable, meta.pref_stable = self._last_stable
        return Snapshot(
            cluster, pods, sel, pref, spread, terms, prefpod, images
        ), meta

    def _build_cluster(
        self,
        nodes: Sequence[api.Node],
        bound_pods: Sequence[api.Pod],
        n: int,
        r: int,
        index_by_name: Dict[str, int],
    ) -> ClusterTensors:
        lim = self.limits
        alloc = np.zeros((n, r), dtype=np.float32)
        requested = np.zeros((n, r), dtype=np.float32)
        nonzero = np.zeros((n, r), dtype=np.float32)
        valid = np.zeros(n, dtype=bool)
        name_id = np.full(n, -1, dtype=np.int32)
        label_bits = np.zeros((n, lim.label_words), dtype=np.uint32)
        taint_bits = np.zeros((3, n, lim.taint_words), dtype=np.uint32)
        port_bits = np.zeros((n, lim.port_words), dtype=np.uint32)
        topo_ids = np.full((n, len(lim.topology_keys)), -1, dtype=np.int32)
        image_bits = np.zeros((n, lim.image_words), dtype=np.uint32)
        slice_id = np.full(n, -1, dtype=np.int32)
        torus_coords = np.full((n, 4), -1, dtype=np.int32)
        slice_dims = np.zeros((n, 3), dtype=np.int32)
        slice_pos = np.full(n, -1, dtype=np.int32)

        for i, node in enumerate(nodes):
            self._write_node_row(
                node, i, valid, name_id, alloc, label_bits, taint_bits,
                topo_ids, image_bits, slice_id, torus_coords, slice_dims,
                slice_pos,
            )

        for pod in bound_pods:
            i = index_by_name.get(pod.spec.node_name)
            if i is None:
                continue
            req, nz, ports = self.pod_usage(pod, r)
            requested[i] += req
            nonzero[i] += nz
            port_bits[i] |= ports

        return ClusterTensors(
            allocatable=alloc,
            requested=requested,
            nonzero_requested=nonzero,
            node_valid=valid,
            name_id=name_id,
            label_bits=label_bits,
            taint_bits=taint_bits,
            port_bits=port_bits,
            topo_ids=topo_ids,
            image_bits=image_bits,
            slice_id=slice_id,
            torus_coords=torus_coords,
            slice_dims=slice_dims,
            slice_pos=slice_pos,
        )

    def _slice_row(self, node: api.Node) -> Tuple[int, tuple, tuple, int]:
        """(slice id, (x, y, z, core), (dx, dy, dz), linear position) of
        a node's TPU slice-topology labels, or the absent sentinel row.
        Malformed coordinate/topology labels degrade to 'no topology'
        (a bad label must not sink the encode); an over-cap extent
        raises — the grid capacity is a static limit like every other
        SnapshotLimits cap."""
        absent = (-1, (-1, -1, -1, -1), (0, 0, 0), -1)
        labels = node.meta.labels
        name = labels.get(api.LABEL_TPU_SLICE)
        if not name:
            return absent
        dims = api.parse_topology(labels.get(api.LABEL_TPU_TOPOLOGY))
        coords = api.parse_coords(labels.get(api.LABEL_TPU_COORDS))
        if dims is None or coords is None:
            return absent
        if max(dims) > self.limits.max_slice_dim:
            raise OverflowError(
                f"node {node.meta.name!r}: slice extent {dims} exceeds "
                f"max_slice_dim={self.limits.max_slice_dim}"
            )
        if any(c >= d for c, d in zip(coords, dims)):
            return absent  # coordinates outside the declared extent
        try:
            core = int(labels.get(api.LABEL_TPU_CORE, "0"))
        except ValueError:
            core = 0
        sid = self.slice_vocab.intern(name)
        x, y, z = coords
        dx, dy, _dz = dims
        pos = x + dx * (y + dy * z)
        return sid, (x, y, z, core), dims, pos

    def _write_node_row(
        self,
        node: api.Node,
        i: int,
        valid: np.ndarray,
        name_id: np.ndarray,
        alloc: np.ndarray,
        label_bits: np.ndarray,
        taint_bits: np.ndarray,
        topo_ids: np.ndarray,
        image_bits: Optional[np.ndarray] = None,
        slice_id: Optional[np.ndarray] = None,
        torus_coords: Optional[np.ndarray] = None,
        slice_dims: Optional[np.ndarray] = None,
        slice_pos: Optional[np.ndarray] = None,
    ) -> None:
        """Encode one node's static state into row i of the given arrays.
        Interns the node's strings first, so it is safe for incremental
        adds (ClusterState) as well as bulk builds."""
        self._intern_node_strings((node,))
        r = alloc.shape[1]
        valid[i] = True
        name_id[i] = self.name_vocab.get(node.meta.name)
        alloc[i] = self._resource_vector(node.status.allocatable, r, grow=False)
        self._check_f32_exact(node.meta.name, alloc[i])
        label_bits[i] = 0
        for k, v in node.meta.labels.items():
            if k in self.topo_vocabs:
                continue
            vb.set_bit(label_bits[i], self.label_vocab.get((k, v)))
        taint_bits[:, i, :] = 0
        for t in node.effective_taints():
            vb.set_bit(
                taint_bits[EFFECT_INDEX[t.effect], i],
                self.taint_vocab.get((t.key, t.value)),
            )
        topo_ids[i] = -1
        for j, key in enumerate(self.limits.topology_keys):
            val = node.meta.labels.get(key)
            if val is not None:
                topo_ids[i, j] = self.topo_vocabs[key].get(val)
        if image_bits is not None:
            self._image_row(node, image_bits[i])
        if slice_id is not None:
            sid, coords, dims, pos = self._slice_row(node)
            slice_id[i] = sid
            torus_coords[i] = coords
            slice_dims[i] = dims
            slice_pos[i] = pos

    def _check_f32_exact(
        self, name: str, row: np.ndarray, kind: str = "node"
    ) -> None:
        """Warn (once per builder) when an encoded resource value exceeds
        the f32 exact-integer envelope: score floors may drift ±1 vs the
        reference's int64 math (the `* 100 < 2^24` claim in ops/scores.py
        is only guaranteed inside this range).

        Fired at EVERY encode site that feeds the score kernels'
        `quantity * 100` products (a tensor-contract audit item): node
        allocatable (_write_node_row), pending-pod request rows
        (_build_pods — the `cap - req` / `req * 100` numerators), and
        bound/assumed pod usage (pod_usage — accumulated requested
        state)."""
        if getattr(self, "_f32_warned", False):
            return
        over = row[row > F32_EXACT_LIMIT]
        if over.size:
            self._f32_warned = True
            warnings.warn(
                f"{kind} {name!r}: encoded resource value {over.max():.0f} "
                f"(device units) exceeds {F32_EXACT_LIMIT:.0f}; "
                "Least/MostAllocated scores may differ from the reference "
                "by ±1 here (f32 exactness envelope)",
                stacklevel=3,
            )

    def pod_usage(
        self, pod: api.Pod, r: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(requested, nonzero_requested, port_bits) contribution of one
        bound/assumed pod — the NodeInfo.AddPod accumulation
        (framework/types.go AddPodInfo).  Callers intern new scalar
        resources (and widen arrays) before calling; unknown resources
        here would be dropped, so grow=False keeps the axis stable."""
        req = self._resource_vector(self.effective_requests(pod), r, grow=False)
        req[RESOURCE_PODS] = 1.0
        self._check_f32_exact(pod.meta.name, req, kind="pod")
        nz = req.copy()
        nz_cpu, nz_mem = pod.nonzero_requests()
        nz[RESOURCE_CPU] = nz_cpu
        nz[RESOURCE_MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
        return req, nz, self._encode_ports(pod.host_ports())

    def _build_pods(
        self, pods: Sequence[api.Pod], p_dim: int, r: int
    ) -> Tuple[PodBatch, SelectorTable, PreferredTable, Dict[tuple, int]]:
        lim = self.limits
        t_cap, e_cap, k_cap, mt = (
            lim.max_terms, lim.max_exprs, lim.max_ids_per_expr, lim.max_preferred,
        )

        req = np.zeros((p_dim, r), dtype=np.float32)
        nonzero = np.zeros((p_dim, r), dtype=np.float32)
        valid = np.zeros(p_dim, dtype=bool)
        name_id = np.full(p_dim, -1, dtype=np.int32)
        sel_idx = np.full(p_dim, -1, dtype=np.int32)
        tol_bits = np.zeros((3, p_dim, lim.taint_words), dtype=np.uint32)
        tol_all = np.zeros((3, p_dim), dtype=bool)
        port_bits = np.zeros((p_dim, lim.port_words), dtype=np.uint32)
        pref_idx = np.full((p_dim, mt), -1, dtype=np.int32)
        pref_weight = np.zeros((p_dim, mt), dtype=np.float32)
        priority = np.zeros(p_dim, dtype=np.float32)
        group_id = np.full(p_dim, -1, dtype=np.int32)
        pod_shape = np.zeros((p_dim, 3), dtype=np.int32)
        group_index: Dict[str, int] = {}

        # Dedup tables keyed by canonical signatures.
        sel_rows: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        sel_index: Dict[tuple, int] = {}
        pref_rows: List[Tuple[np.ndarray, np.ndarray]] = []
        pref_index: Dict[tuple, int] = {}

        # Spec-row cache: real batches repeat a few hundred distinct specs
        # across tens of thousands of pods (every replica of a workload is
        # byte-identical up to its name), so the heavy per-pod encode —
        # resource vectors, toleration bitsets, selector/preferred
        # interning — runs once per distinct spec and every repeat is one
        # dict hit + row copy.  The key (_spec_signature) walks exactly
        # the fields the rows are derived from.
        spec_cache: Dict[tuple, tuple] = {}

        for i, pod in enumerate(pods):
            valid[i] = True
            priority[i] = float(pod.spec.priority)
            shape = self.pod_carveout_shape(pod)
            pod_shape[i] = shape
            if pod.spec.scheduling_group:
                group_id[i] = group_index.setdefault(
                    pod.spec.scheduling_group, len(group_index)
                )
            extra_sel, extra_req = self._transform(pod)
            key = self._spec_signature(pod, extra_sel, shape)
            cached = spec_cache.get(key)
            if cached is not None:
                (req[i], nonzero[i], name_id[i], sel_idx[i],
                 tol_bits[:, i, :], tol_all[:, i], port_bits[i],
                 pref_idx[i], pref_weight[i]) = cached
                continue
            rv = self._resource_vector(
                self.effective_requests(pod), r, grow=False
            )
            rv[RESOURCE_PODS] = 1.0
            self._check_f32_exact(pod.meta.name, rv, kind="pod")
            req[i] = rv
            nz = rv.copy()
            nz_cpu, nz_mem = pod.nonzero_requests()
            nz[RESOURCE_CPU] = nz_cpu
            nz[RESOURCE_MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
            nonzero[i] = nz

            if pod.spec.node_name:
                nid = self.name_vocab.get(pod.spec.node_name)
                name_id[i] = nid if nid >= 0 else -2

            selector = pod.required_node_selector()
            if extra_sel is not None:
                selector = api.and_selectors(selector, extra_sel)
            if selector is not None:
                sig = _selector_signature(selector)
                idx = sel_index.get(sig)
                if idx is None:
                    idx = len(sel_rows)
                    sel_index[sig] = idx
                    sel_rows.append(self._encode_selector(selector, t_cap, e_cap, k_cap))
                sel_idx[i] = idx

            bits, tall = self._encode_tolerations(pod.spec.tolerations)
            tol_bits[:, i, :] = bits
            tol_all[:, i] = tall
            port_bits[i] = self._encode_ports(pod.host_ports())

            preferred = pod.preferred_node_affinity()
            if len(preferred) > mt:
                raise OverflowError(
                    f"{len(preferred)} preferred terms exceed max_preferred={mt}"
                )
            for j, pt in enumerate(preferred):
                sig = _term_signature(pt.preference)
                idx = pref_index.get(sig)
                if idx is None:
                    idx = len(pref_rows)
                    pref_index[sig] = idx
                    pref_rows.append(
                        self._encode_term(pt.preference.match_expressions, e_cap, k_cap)
                    )
                pref_idx[i, j] = idx
                pref_weight[i, j] = float(pt.weight)
            spec_cache[key] = (
                req[i].copy(), nonzero[i].copy(), name_id[i], sel_idx[i],
                tol_bits[:, i, :].copy(), tol_all[:, i].copy(),
                port_bits[i].copy(), pref_idx[i].copy(), pref_weight[i].copy(),
            )

        sel = _fill_selector_table(sel_rows, t_cap, e_cap, k_cap)
        pref = _fill_preferred_table(pref_rows, e_cap, k_cap)

        # stable content-signature ids for this batch's dedup rows (the
        # PartialsCache's cross-batch class keys; see _stable_id)
        sel_sigs: List[tuple] = [()] * len(sel_rows)
        for sig, idx in sel_index.items():
            sel_sigs[idx] = sig
        pref_sigs: List[tuple] = [()] * len(pref_rows)
        for sig, idx in pref_index.items():
            pref_sigs[idx] = sig
        self._last_stable = (
            tuple(self._stable_id(("sel", s)) for s in sel_sigs),
            tuple(self._stable_id(("pref", s)) for s in pref_sigs),
        )

        class_id, class_rep = _pod_classes(
            valid, name_id, sel_idx, tol_bits, tol_all, port_bits,
            pref_idx, pref_weight, req, nonzero, pod_shape,
        )
        batch = PodBatch(
            valid=valid,
            req=req,
            nonzero_req=nonzero,
            name_id=name_id,
            sel_idx=sel_idx,
            tol_bits=tol_bits,
            tol_all=tol_all,
            port_bits=port_bits,
            pref_idx=pref_idx,
            pref_weight=pref_weight,
            class_id=class_id,
            class_rep=class_rep,
            priority=priority,
            group_id=group_id,
            pod_shape=pod_shape,
            # unrefined: joint == spec, one trivial constraint class
            spec_rep=class_rep,
            joint_spec=np.arange(class_rep.shape[0], dtype=np.int32),
            cons_rep=np.zeros(1, dtype=np.int32),
            joint_cons=np.zeros(class_rep.shape[0], dtype=np.int32),
        )
        return batch, sel, pref, sel_index

    def _spec_signature(
        self, pod: api.Pod, extra_sel, shape: Tuple[int, int, int],
        eff: Optional[Dict[str, int]] = None,
    ) -> tuple:
        """The spec-row identity: exactly the fields a pod's encoded row
        is derived from.  Shared by the per-batch cache (_build_pods) and
        the persistent columnar store (_build_pods_columnar) — keying on
        the SOURCE strings, not vocab ids, so a key stays valid across
        vocabulary growth and the store's staleness gates re-derive the
        id-dependent columns.  `eff` is an optional precomputed
        effective_requests(pod) (pure) to avoid re-deriving it."""
        spec = pod.spec
        aff = spec.affinity
        na = aff.node_affinity if aff else None
        if eff is None:
            eff = self.effective_requests(pod)
        return (
            tuple(sorted(eff.items())),
            tuple(pod.nonzero_requests()),
            spec.node_name,
            tuple(sorted(spec.node_selector.items())),
            tuple(
                (t.key, t.op, t.value, t.effect) for t in spec.tolerations
            ),
            tuple(sorted(pod.host_ports())),
            _selector_signature(na.required) if na and na.required else None,
            tuple(
                (pt.weight, _term_signature(pt.preference))
                for pt in (na.preferred if na else ())
            ),
            # transform output (e.g. volume topology): pods with the
            # same spec but different claims must not share a row
            _selector_signature(extra_sel) if extra_sel else None,
            # carve-out shape (spec.tpu_topology or the shape hook):
            # shaped and unshaped pods must not share a row
            shape,
        )

    def _build_pods_columnar(
        self, pods: Sequence[api.Pod], p_dim: int, r: int,
        eff_list: Optional[Sequence[Dict[str, int]]] = None,
    ) -> Tuple[PodBatch, SelectorTable, PreferredTable, Dict[tuple, int]]:
        """Columnar twin of _build_pods, bit-identical by construction.

        The Python loop below touches only the per-POD fields (validity,
        priority, group, carve-out shape, spec-key lookup); everything
        per-SPEC comes out of the persistent _PodSpecStore as column
        blocks, so a warm batch assembles its arrays with a handful of
        fancy-index gathers — O(P) dict hits + O(distinct specs) encodes
        instead of P x fields attribute walks.  The per-object
        _build_pods stays byte-for-byte the parity oracle
        (tests/test_encoder_parity.py)."""
        lim = self.limits
        mt = lim.max_preferred
        store = self._spec_store
        store.sync(self, r)
        npods = len(pods)

        valid = np.zeros(p_dim, dtype=bool)
        priority = np.zeros(p_dim, dtype=np.float32)
        group_id = np.full(p_dim, -1, dtype=np.int32)
        pod_shape = np.zeros((p_dim, 3), dtype=np.int32)
        group_index: Dict[str, int] = {}
        rows = np.zeros(npods, dtype=np.int32)
        row_of = store.rows
        for i, pod in enumerate(pods):
            valid[i] = True
            priority[i] = float(pod.spec.priority)
            shape = self.pod_carveout_shape(pod)
            pod_shape[i] = shape
            if pod.spec.scheduling_group:
                group_id[i] = group_index.setdefault(
                    pod.spec.scheduling_group, len(group_index)
                )
            extra_sel, _extra_req = self._transform(pod)
            eff = eff_list[i] if eff_list is not None else None
            key = self._spec_signature(pod, extra_sel, shape, eff)
            row = row_of.get(key)
            if row is None:
                row = store.encode_row(self, pod, extra_sel, key, r, eff)
            rows[i] = row

        req = np.zeros((p_dim, r), dtype=np.float32)
        nonzero = np.zeros((p_dim, r), dtype=np.float32)
        name_id = np.full(p_dim, -1, dtype=np.int32)
        tol_bits = np.zeros((3, p_dim, lim.taint_words), dtype=np.uint32)
        tol_all = np.zeros((3, p_dim), dtype=bool)
        port_bits = np.zeros((p_dim, lim.port_words), dtype=np.uint32)
        pref_weight = np.zeros((p_dim, mt), dtype=np.float32)
        sel_idx = np.full(p_dim, -1, dtype=np.int32)
        pref_idx = np.full((p_dim, mt), -1, dtype=np.int32)

        if npods:
            # the columnar gathers: one fancy-index per field
            req[:npods] = store.req[rows, :r]
            nonzero[:npods] = store.nonzero[rows, :r]
            name_id[:npods] = store.name_id[rows]
            tol_bits[:, :npods, :] = store.tol_bits[:, rows, :]
            tol_all[:, :npods] = store.tol_all[:, rows]
            port_bits[:npods] = store.port_bits[rows]
            pref_weight[:npods] = store.pref_weight[rows]
            sel_order, sel_remap = _first_encounter(store.sel_lid[rows])
            sel_idx[:npods] = sel_remap
            pref_order, pref_remap = _first_encounter(
                store.pref_lid[rows].ravel()
            )
            pref_idx[:npods] = pref_remap.reshape(npods, mt)
        else:
            sel_order, pref_order = [], []

        sel = _fill_selector_table(
            [store.sel_encoding(self, lid) for lid in sel_order],
            lim.max_terms, lim.max_exprs, lim.max_ids_per_expr,
        )
        pref = _fill_preferred_table(
            [store.pref_encoding(self, lid) for lid in pref_order],
            lim.max_exprs, lim.max_ids_per_expr,
        )
        sel_index = {store.sel_sigs[lid]: j for j, lid in enumerate(sel_order)}
        self._last_stable = (
            tuple(
                self._stable_id(("sel", store.sel_sigs[lid]))
                for lid in sel_order
            ),
            tuple(
                self._stable_id(("pref", store.pref_sigs[lid]))
                for lid in pref_order
            ),
        )
        store.finish(self)

        class_id, class_rep = _pod_classes(
            valid, name_id, sel_idx, tol_bits, tol_all, port_bits,
            pref_idx, pref_weight, req, nonzero, pod_shape,
        )
        batch = PodBatch(
            valid=valid,
            req=req,
            nonzero_req=nonzero,
            name_id=name_id,
            sel_idx=sel_idx,
            tol_bits=tol_bits,
            tol_all=tol_all,
            port_bits=port_bits,
            pref_idx=pref_idx,
            pref_weight=pref_weight,
            class_id=class_id,
            class_rep=class_rep,
            priority=priority,
            group_id=group_id,
            pod_shape=pod_shape,
            # unrefined: joint == spec, one trivial constraint class
            spec_rep=class_rep,
            joint_spec=np.arange(class_rep.shape[0], dtype=np.int32),
            cons_rep=np.zeros(1, dtype=np.int32),
            joint_cons=np.zeros(class_rep.shape[0], dtype=np.int32),
        )
        return batch, sel, pref, sel_index

    def _topo_slot(self, key: str) -> int:
        try:
            return self.limits.topology_keys.index(key)
        except ValueError:
            raise OverflowError(
                f"topology key {key!r} is not tracked; add it to "
                "SnapshotLimits.topology_keys"
            ) from None

    def _build_constraints(
        self,
        pods: Sequence[api.Pod],
        bound_by_node: Sequence[Tuple[api.Pod, int]],
        sel_index: Dict[tuple, int],
        n: int,
        p_dim: int,
    ) -> Tuple[SpreadTable, TermTable]:
        lim = self.limits
        tk = len(lim.topology_keys)
        mc, ma = lim.max_spread_per_pod, lim.max_pod_terms

        # Distinct (namespace, labels) signatures across bound + pending
        # pods.  Constraint rows match against SIGNATURES (a few hundred)
        # instead of pods (tens of thousands): real clusters have far
        # fewer label shapes than pods, and the naive rows x pods Python
        # loop was the encode bottleneck at 10k-pod batches (2M+
        # LabelSelector.matches calls per batch).
        sig_of: Dict[tuple, int] = {}
        distinct_sigs: List[Tuple[str, Dict[str, str]]] = []

        def sig_id(pod: api.Pod) -> int:
            key = (pod.meta.namespace, tuple(sorted(pod.meta.labels.items())))
            idx = sig_of.get(key)
            if idx is None:
                idx = len(distinct_sigs)
                sig_of[key] = idx
                distinct_sigs.append((pod.meta.namespace, pod.meta.labels))
            return idx

        bound_sig = np.fromiter(
            (sig_id(q) for q, _ in bound_by_node), np.int32, len(bound_by_node)
        )
        bound_node = np.fromiter(
            (ni for _, ni in bound_by_node), np.int32, len(bound_by_node)
        )
        pend_sig = np.fromiter((sig_id(q) for q in pods), np.int32, len(pods))

        def match_sigs(sel: api.LabelSelector, namespaces) -> np.ndarray:
            """bool[n_sigs]: which distinct signatures the row matches.
            `namespaces` is a container or a single owner namespace."""
            ns_set = (
                namespaces if isinstance(namespaces, tuple) else (namespaces,)
            )
            return np.fromiter(
                (
                    ns in ns_set and sel.matches(labels)
                    for ns, labels in distinct_sigs
                ),
                bool,
                len(distinct_sigs),
            )

        # ---- topology spread constraints --------------------------------
        # A constraint instance is owner-scoped: eligibility honours the
        # owner's node selector/affinity and requires every topology key of
        # *all* the owner's constraints (filtering.go PreFilter).
        spread_rows: List[tuple] = []  # (api constraint, sel, owner_ns, owner_sel, keys)
        spread_index: Dict[tuple, int] = {}
        pod_spread_idx = np.full((p_dim, mc), -1, dtype=np.int32)
        for i, pod in enumerate(pods):
            cons = pod.spec.topology_spread_constraints
            if not cons:
                continue
            if len(cons) > mc:
                raise OverflowError(
                    f"{len(cons)} spread constraints exceed max_spread_per_pod={mc}"
                )
            owner_sel = pod.required_node_selector()
            owner_sel_row = (
                sel_index[_selector_signature(owner_sel)] if owner_sel else -1
            )
            keys = tuple(sorted({c.topology_key for c in cons}))
            for j, c in enumerate(cons):
                if c.node_affinity_policy != "Honor" or c.node_taints_policy != "Ignore":
                    raise OverflowError(
                        "nodeInclusionPolicies other than the defaults "
                        "(Honor affinity / Ignore taints) are not implemented; "
                        f"got affinity={c.node_affinity_policy!r} "
                        f"taints={c.node_taints_policy!r}"
                    )
                sel = _merge_match_label_keys(
                    c.label_selector, c.match_label_keys, pod.meta.labels
                )
                sig = (
                    c.topology_key,
                    c.max_skew,
                    c.min_domains,
                    c.when_unsatisfiable,
                    _label_selector_signature(sel),
                    pod.meta.namespace,
                    owner_sel_row,
                    keys,
                )
                idx = spread_index.get(sig)
                if idx is None:
                    idx = len(spread_rows)
                    spread_index[sig] = idx
                    spread_rows.append((c, sel, pod.meta.namespace, owner_sel_row, keys))
                pod_spread_idx[i, j] = idx

        c_dim = vb.pad_constraint_dim(len(spread_rows))
        spread = SpreadTable(
            valid=np.zeros(c_dim, dtype=bool),
            slot=np.zeros(c_dim, dtype=np.int32),
            max_skew=np.ones(c_dim, dtype=np.float32),
            min_domains=np.zeros(c_dim, dtype=np.float32),
            hard=np.zeros(c_dim, dtype=bool),
            owner_sel_idx=np.full(c_dim, -1, dtype=np.int32),
            owner_keys=np.zeros((c_dim, tk), dtype=bool),
            node_matches=np.zeros((c_dim, n), dtype=np.float32),
            pod_matches=np.zeros((p_dim, c_dim), dtype=bool),
            pod_idx=pod_spread_idx,
        )
        for ci, (c, sel, owner_ns, owner_sel_row, keys) in enumerate(spread_rows):
            spread.valid[ci] = True
            spread.slot[ci] = self._topo_slot(c.topology_key)
            spread.max_skew[ci] = float(c.max_skew)
            spread.min_domains[ci] = float(c.min_domains or 0)
            spread.hard[ci] = c.when_unsatisfiable == "DoNotSchedule"
            spread.owner_sel_idx[ci] = owner_sel_row
            for k in keys:
                spread.owner_keys[ci, self._topo_slot(k)] = True
            match = match_sigs(sel, owner_ns)
            if len(bound_sig):
                m = match[bound_sig]
                np.add.at(spread.node_matches[ci], bound_node[m], 1.0)
            if len(pend_sig):
                spread.pod_matches[: len(pods), ci] = match[pend_sig]

        # ---- inter-pod (anti-)affinity terms ----------------------------
        # A row is (topology_key slot, effective selector, namespaces);
        # match_label_keys are merged into the selector per owning pod
        # (interpodaffinity PreFilter's mergeAffinityTermsPerPod).
        term_rows: List[Tuple[str, api.LabelSelector, Tuple[str, ...]]] = []
        term_index: Dict[tuple, int] = {}

        def intern_term(term: api.PodAffinityTerm, owner: api.Pod) -> int:
            return _intern_pod_term(term_rows, term_index, term, owner)

        def pod_terms(pod: api.Pod) -> Tuple[List[api.PodAffinityTerm], List[api.PodAffinityTerm]]:
            aff = pod.spec.affinity
            a = aff.pod_affinity.required if aff and aff.pod_affinity else []
            b = aff.pod_anti_affinity.required if aff and aff.pod_anti_affinity else []
            return list(a), list(b)

        aff_idx = np.full((p_dim, ma), -1, dtype=np.int32)
        anti_idx = np.full((p_dim, ma), -1, dtype=np.int32)
        for i, pod in enumerate(pods):
            aff_terms, anti_terms = pod_terms(pod)
            if len(aff_terms) > ma or len(anti_terms) > ma:
                raise OverflowError(
                    f"pod has {len(aff_terms)}/{len(anti_terms)} (anti-)affinity "
                    f"terms, exceeding max_pod_terms={ma}"
                )
            for j, t in enumerate(aff_terms):
                aff_idx[i, j] = intern_term(t, pod)
            for j, t in enumerate(anti_terms):
                anti_idx[i, j] = intern_term(t, pod)
        # Bound pods' anti-affinity terms participate in the
        # existing-pods-anti-affinity direction even if no pending pod
        # carries them.  A BOUND pod with an unsupported field must not
        # poison every future batch encode (it was admitted by someone
        # else); its term is skipped, unlike pending pods which raise.
        bound_anti: List[Tuple[int, int]] = []  # (term row, node index)
        for q, ni in bound_by_node:
            _, anti_terms = pod_terms(q)
            for t in anti_terms:
                try:
                    bound_anti.append((intern_term(t, q), ni))
                except OverflowError:
                    pass

        t_dim = vb.pad_constraint_dim(len(term_rows))
        t_words = (t_dim + 31) // 32
        terms = TermTable(
            valid=np.zeros(t_dim, dtype=bool),
            slot=np.zeros(t_dim, dtype=np.int32),
            node_matches=np.zeros((t_dim, n), dtype=np.float32),
            node_owners=np.zeros((t_dim, n), dtype=np.float32),
            matches_incoming=np.zeros((p_dim, t_words), dtype=np.uint32),
            aff_idx=aff_idx,
            anti_idx=anti_idx,
            self_match_all=np.zeros(p_dim, dtype=bool),
        )

        for ti, (topo_key, sel, namespaces) in enumerate(term_rows):
            terms.valid[ti] = True
            terms.slot[ti] = self._topo_slot(topo_key)
            match = match_sigs(sel, namespaces)
            if len(bound_sig):
                m = match[bound_sig]
                np.add.at(terms.node_matches[ti], bound_node[m], 1.0)
            if len(pend_sig):
                terms.matches_incoming[: len(pods), ti // 32] |= (
                    match[pend_sig].astype(np.uint32) << np.uint32(ti % 32)
                )
        for ti, ni in bound_anti:
            terms.node_owners[ti, ni] += 1.0

        def row_matches(sel: api.LabelSelector, namespaces, pod: api.Pod) -> bool:
            return pod.meta.namespace in namespaces and sel.matches(pod.meta.labels)

        for i, pod in enumerate(pods):
            aff_terms, _ = pod_terms(pod)
            terms.self_match_all[i] = bool(aff_terms) and all(
                row_matches(
                    _merge_match_label_keys(
                        t.label_selector, t.match_label_keys, pod.meta.labels
                    ),
                    tuple(t.namespaces or [pod.meta.namespace]),
                    pod,
                )
                for t in aff_terms
            )

        prefpod = self._build_prefpod(
            pods, bound_by_node, n, p_dim, match_sigs, bound_sig, bound_node,
            pend_sig,
        )
        return spread, terms, prefpod

    def _build_prefpod(
        self, pods, bound_by_node, n, p_dim, match_sigs, bound_sig,
        bound_node, pend_sig,
    ) -> PrefPodTable:
        """Preferred inter-pod affinity rows (see PrefPodTable).  Rows
        from both directions share one table: incoming pods' preferred
        terms need node_counts; bound pods' preferred/required-affinity
        terms need owner_weight + matches_incoming."""
        lim = self.limits
        ma = lim.max_pod_terms
        rows: List[Tuple[str, api.LabelSelector, Tuple[str, ...]]] = []
        index: Dict[tuple, int] = {}

        def intern(term: api.PodAffinityTerm, owner: api.Pod) -> int:
            return _intern_pod_term(rows, index, term, owner)

        def signed_terms(pod: api.Pod):
            aff = pod.spec.affinity
            out = []
            if aff and aff.pod_affinity:
                out += [(w.weight, w.term) for w in aff.pod_affinity.preferred]
            if aff and aff.pod_anti_affinity:
                out += [
                    (-w.weight, w.term) for w in aff.pod_anti_affinity.preferred
                ]
            return out

        pod_idx = np.full((p_dim, ma), -1, dtype=np.int32)
        pod_weight = np.zeros((p_dim, ma), dtype=np.float32)
        for i, pod in enumerate(pods):
            st = signed_terms(pod)
            if len(st) > ma:
                raise OverflowError(
                    f"pod has {len(st)} preferred (anti-)affinity terms, "
                    f"exceeding max_pod_terms={ma}"
                )
            for j, (w, t) in enumerate(st):
                pod_idx[i, j] = intern(t, pod)
                pod_weight[i, j] = float(w)

        # owner direction: bound pods' preferred terms (signed weight) and
        # REQUIRED affinity terms (hardPodAffinityWeight — scoring.go
        # processExistingPod's hard-affinity contribution).  Unsupported
        # fields on BOUND pods skip the term instead of poisoning every
        # batch encode (pending pods still raise).
        owner_entries: List[Tuple[int, int, float]] = []  # (row, node, w)
        for q, ni in bound_by_node:
            for w, t in signed_terms(q):
                try:
                    owner_entries.append((intern(t, q), ni, float(w)))
                except OverflowError:
                    pass
            aff = q.spec.affinity
            for t in (aff.pod_affinity.required if aff and aff.pod_affinity else []):
                try:
                    owner_entries.append(
                        (intern(t, q), ni, float(lim.hard_pod_affinity_weight))
                    )
                except OverflowError:
                    pass

        u_dim = vb.pad_constraint_dim(len(rows))
        table = PrefPodTable(
            valid=np.zeros(u_dim, dtype=bool),
            slot=np.zeros(u_dim, dtype=np.int32),
            node_counts=np.zeros((u_dim, n), dtype=np.float32),
            owner_weight=np.zeros((u_dim, n), dtype=np.float32),
            matches_incoming=np.zeros((p_dim, u_dim), dtype=bool),
            pod_idx=pod_idx,
            pod_weight=pod_weight,
        )
        for ui, (topo_key, sel, namespaces) in enumerate(rows):
            table.valid[ui] = True
            table.slot[ui] = self._topo_slot(topo_key)
            match = match_sigs(sel, namespaces)
            if len(bound_sig):
                m = match[bound_sig]
                np.add.at(table.node_counts[ui], bound_node[m], 1.0)
            if len(pend_sig):
                table.matches_incoming[: len(pods), ui] = match[pend_sig]
        for ui, ni, w in owner_entries:
            table.owner_weight[ui, ni] += w
        return table

    def _encode_selector(
        self, selector: api.NodeSelector, t_cap: int, e_cap: int, k_cap: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        if len(selector.terms) > t_cap:
            raise OverflowError(
                f"{len(selector.terms)} selector terms exceed max_terms={t_cap}"
            )
        ids = np.full((t_cap, e_cap, k_cap), -1, dtype=np.int32)
        ops = np.zeros((t_cap, e_cap), dtype=np.int32)
        slots = np.full((t_cap, e_cap), DOMAIN_LABELS, dtype=np.int32)
        term_valid = np.zeros(t_cap, dtype=bool)
        for t, term in enumerate(selector.terms):
            term_valid[t] = True
            ids[t], ops[t], slots[t] = self._encode_term(term.match_expressions, e_cap, k_cap)
        return ids, ops, slots, term_valid


class _PodSpecStore:
    """Persistent cross-batch spec-row store: the columnar half of the
    host plane (_build_pods_columnar).

    _build_pods' per-batch spec cache already collapses repeated specs
    inside ONE batch; this store makes the collapse survive across
    batches and keeps the encoded rows as COLUMN blocks, so a batch
    whose specs are warm assembles its PodBatch with a handful of numpy
    fancy-index gathers instead of P x fields Python attribute walks.
    Each distinct spec (keyed by the same 10-field signature the
    per-batch cache walks) is encoded ONCE via the per-object helpers —
    the per-object path stays the parity oracle, and the gathered rows
    are byte-identical to what it would re-encode.

    Cached rows go stale exactly three ways, each re-checked in sync()
    before every batch (vocabularies are append-only, so a length /
    watermark comparison is an exact staleness test):

    * resource-axis growth — new columns are resources no cached spec
      requested (all of a spec's resources are interned at its encode
      time), so req/nonzero zero-widen exactly;
    * name_vocab growth — rows encoded "named but unknown" (-2) may now
      resolve;
    * taint_vocab growth — toleration expansions may cover new taints,
      so rows with nonempty tolerations re-encode;
    * label/topology growth under a REFERENCED key (the
      expansion_watermark) — cached selector/preferred row ENCODINGS
      drop and re-encode lazily; signatures and source objects stay.

    Selector/preferred contents are held as store-local ids (sel_lid /
    pref_lid columns) so the per-batch dense table indices fall out of
    one _first_encounter pass per table.
    """

    _GROW = 64

    def __init__(self) -> None:
        self.rows: Dict[tuple, int] = {}
        self.count = 0
        self.cap = 0
        self.r = 0
        # column blocks [cap, ...] (tol_bits is [3, cap, W])
        self.req = np.zeros((0, 0), dtype=np.float32)
        self.nonzero = np.zeros((0, 0), dtype=np.float32)
        self.name_id = np.zeros(0, dtype=np.int32)
        self.tol_bits = np.zeros((3, 0, 0), dtype=np.uint32)
        self.tol_all = np.zeros((3, 0), dtype=bool)
        self.port_bits = np.zeros((0, 0), dtype=np.uint32)
        self.sel_lid = np.zeros(0, dtype=np.int32)      # -1 = no selector
        self.pref_lid = np.zeros((0, 0), dtype=np.int32)  # -1 pad
        self.pref_weight = np.zeros((0, 0), dtype=np.float32)
        # store-local selector/preferred id spaces: signature, source
        # object (for lazy re-encode), cached encoding (None = stale)
        self.sel_sigs: List[tuple] = []
        self.sel_objs: List[object] = []
        self.sel_enc: List[Optional[tuple]] = []
        self._sel_by_sig: Dict[tuple, int] = {}
        self.pref_sigs: List[tuple] = []
        self.pref_objs: List[object] = []
        self.pref_enc: List[Optional[tuple]] = []
        self._pref_by_sig: Dict[tuple, int] = {}
        # staleness gates
        self._unresolved: Dict[int, str] = {}   # row -> node_name (-2 rows)
        self._tol_rows: Dict[int, tuple] = {}   # row -> tolerations
        self._name_len = 0
        self._taint_len = 0
        self._wm: Optional[tuple] = None

    # -- staleness ---------------------------------------------------------

    def sync(self, b: "SnapshotBuilder", r: int) -> None:
        """Bring cached rows up to date with the builder's vocabularies
        before a batch.  Exactness argument per gate is in the class
        docstring."""
        if r > self.r:
            pad = ((0, 0), (0, r - self.r))
            self.req = np.pad(self.req, pad)
            self.nonzero = np.pad(self.nonzero, pad)
            self.r = r
        if len(b.name_vocab) != self._name_len:
            for row, nm in list(self._unresolved.items()):
                nid = b.name_vocab.get(nm)
                if nid >= 0:
                    self.name_id[row] = nid
                    del self._unresolved[row]
            self._name_len = len(b.name_vocab)
        if len(b.taint_vocab) != self._taint_len:
            for row, tols in self._tol_rows.items():
                bits, tall = b._encode_tolerations(tols)
                self.tol_bits[:, row, :] = bits
                self.tol_all[:, row] = tall
            self._taint_len = len(b.taint_vocab)
        wm = b.expansion_watermark()
        if wm != self._wm:
            self.sel_enc = [None] * len(self.sel_enc)
            self.pref_enc = [None] * len(self.pref_enc)
            self._wm = wm

    def finish(self, b: "SnapshotBuilder") -> None:
        """Refresh the watermark AFTER a batch's encodes: new selectors
        may have referenced new keys (watermark grows without any cached
        encoding going stale)."""
        self._wm = b.expansion_watermark()

    # -- row encode (miss path: per-object helpers, once per spec) ---------

    def _ensure_capacity(self, b: "SnapshotBuilder") -> None:
        if self.count < self.cap:
            return
        lim = b.limits
        new_cap = max(self.cap * 2, self._GROW)
        grown = new_cap - self.cap

        def widen(a: np.ndarray, axis: int) -> np.ndarray:
            pad = [(0, 0)] * a.ndim
            pad[axis] = (0, grown)
            return np.pad(a, pad)

        if self.cap == 0:
            self.req = np.zeros((new_cap, self.r), dtype=np.float32)
            self.nonzero = np.zeros((new_cap, self.r), dtype=np.float32)
            self.name_id = np.full(new_cap, -1, dtype=np.int32)
            self.tol_bits = np.zeros(
                (3, new_cap, lim.taint_words), dtype=np.uint32
            )
            self.tol_all = np.zeros((3, new_cap), dtype=bool)
            self.port_bits = np.zeros(
                (new_cap, lim.port_words), dtype=np.uint32
            )
            self.sel_lid = np.full(new_cap, -1, dtype=np.int32)
            self.pref_lid = np.full(
                (new_cap, lim.max_preferred), -1, dtype=np.int32
            )
            self.pref_weight = np.zeros(
                (new_cap, lim.max_preferred), dtype=np.float32
            )
        else:
            self.req = widen(self.req, 0)
            self.nonzero = widen(self.nonzero, 0)
            self.name_id = np.concatenate(
                [self.name_id, np.full(grown, -1, dtype=np.int32)]
            )
            self.tol_bits = widen(self.tol_bits, 1)
            self.tol_all = widen(self.tol_all, 1)
            self.port_bits = widen(self.port_bits, 0)
            self.sel_lid = np.concatenate(
                [self.sel_lid, np.full(grown, -1, dtype=np.int32)]
            )
            self.pref_lid = np.concatenate(
                [self.pref_lid,
                 np.full((grown, self.pref_lid.shape[1]), -1, dtype=np.int32)]
            )
            self.pref_weight = widen(self.pref_weight, 0)
        self.cap = new_cap

    def _sel_local(self, sig: tuple, selector) -> int:
        lid = self._sel_by_sig.get(sig)
        if lid is None:
            lid = len(self.sel_sigs)
            self._sel_by_sig[sig] = lid
            self.sel_sigs.append(sig)
            self.sel_objs.append(selector)
            self.sel_enc.append(None)
        return lid

    def _pref_local(self, sig: tuple, term) -> int:
        lid = self._pref_by_sig.get(sig)
        if lid is None:
            lid = len(self.pref_sigs)
            self._pref_by_sig[sig] = lid
            self.pref_sigs.append(sig)
            self.pref_objs.append(term)
            self.pref_enc.append(None)
        return lid

    def encode_row(
        self, b: "SnapshotBuilder", pod: api.Pod, extra_sel, key: tuple,
        r: int, eff=None,
    ) -> int:
        """Encode one distinct spec into the next column row via the
        per-object helpers (the oracle's exact code paths)."""
        self._ensure_capacity(b)
        row = self.count
        mt = b.limits.max_preferred

        if eff is None:
            eff = b.effective_requests(pod)
        rv = b._resource_vector(eff, r, grow=False)
        rv[RESOURCE_PODS] = 1.0
        b._check_f32_exact(pod.meta.name, rv, kind="pod")
        self.req[row] = rv
        nz = rv.copy()
        nz_cpu, nz_mem = pod.nonzero_requests()
        nz[RESOURCE_CPU] = nz_cpu
        nz[RESOURCE_MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
        self.nonzero[row] = nz

        nid = -1
        if pod.spec.node_name:
            got = b.name_vocab.get(pod.spec.node_name)
            nid = got if got >= 0 else -2
            if nid == -2:
                self._unresolved[row] = pod.spec.node_name
        self.name_id[row] = nid

        selector = pod.required_node_selector()
        if extra_sel is not None:
            selector = api.and_selectors(selector, extra_sel)
        self.sel_lid[row] = (
            self._sel_local(_selector_signature(selector), selector)
            if selector is not None else -1
        )

        bits, tall = b._encode_tolerations(pod.spec.tolerations)
        self.tol_bits[:, row, :] = bits
        self.tol_all[:, row] = tall
        if pod.spec.tolerations:
            self._tol_rows[row] = tuple(pod.spec.tolerations)
        self.port_bits[row] = b._encode_ports(pod.host_ports())

        preferred = pod.preferred_node_affinity()
        if len(preferred) > mt:
            raise OverflowError(
                f"{len(preferred)} preferred terms exceed max_preferred={mt}"
            )
        for j, pt in enumerate(preferred):
            self.pref_lid[row, j] = self._pref_local(
                _term_signature(pt.preference), pt.preference
            )
            self.pref_weight[row, j] = float(pt.weight)

        self.rows[key] = row
        self.count += 1
        return row

    # -- lazy (re-)encode of dedup-table rows ------------------------------

    def sel_encoding(self, b: "SnapshotBuilder", lid: int) -> tuple:
        enc = self.sel_enc[lid]
        if enc is None:
            lim = b.limits
            enc = b._encode_selector(
                self.sel_objs[lid], lim.max_terms, lim.max_exprs,
                lim.max_ids_per_expr,
            )
            self.sel_enc[lid] = enc
        return enc

    def pref_encoding(self, b: "SnapshotBuilder", lid: int) -> tuple:
        enc = self.pref_enc[lid]
        if enc is None:
            lim = b.limits
            enc = b._encode_term(
                self.pref_objs[lid].match_expressions, lim.max_exprs,
                lim.max_ids_per_expr,
            )
            self.pref_enc[lid] = enc
        return enc


class ClusterState:
    """Incremental cluster-tensor store — the tensorization of the
    reference scheduler cache's generation-tracked node bookkeeping with
    incremental UpdateSnapshot (internal/cache/cache.go:57-260,
    snapshot.go).  Node add/update/remove and pod add/remove each touch
    one row of preallocated arrays; tensors() is O(1) array slicing, so
    per-batch snapshot cost is proportional to what changed since the
    last batch, not to cluster size.

    The scheduler cache's assume/forget protocol maps to add_pod /
    remove_pod: an assumed pod's resources are added immediately and
    subtracted again on Forget (cache.go AssumePod/ForgetPod); expiry
    policy lives in the host cache (kubernetes_tpu.scheduler), not here.

    ELASTIC NODE AXIS (docs/scheduler_loop.md "Elastic node axis"):
    backing-array identity and device-axis identity are split.  A
    host-side `_grow` preserves row indices, so it is NOT a struct
    event — new rows are just dirty rows for the mirror's delta-scatter
    path.  `struct_generation` moves only for genuine identity changes
    (resource-axis widening; `force_struct_event`).  The padded bucket
    `tensors()` exposes follows a grow-eager / shrink-lazy hysteresis:
    it rises the moment `_high` crosses a power-of-two boundary, and
    falls only after occupancy has sat below the lower bucket for
    `bucket_shrink_dwell` consecutive snapshot generations — so
    autoscaler oscillation around a boundary never flip-flops compile
    keys or resident-array shapes in either direction.
    """

    # class defaults for the elastic-axis knobs (overridden per instance
    # by FrameworkRegistry from SchedulerConfiguration):
    #   node_axis_headroom     backing-capacity growth factor on realloc
    #                          (rounded up to the next power of two);
    #   bucket_shrink_dwell    snapshot generations occupancy must sit
    #                          below the lower pad bucket before the
    #                          exposed bucket shrinks;
    #   compaction_batch_rows  max rows a single _maybe_compact
    #                          invocation relocates (amortized trigger —
    #                          a 10k-node drain does O(live) total work).
    NODE_AXIS_HEADROOM = 2.0
    BUCKET_SHRINK_DWELL = 8
    COMPACTION_BATCH_ROWS = 512

    def __init__(self, builder: Optional[SnapshotBuilder] = None):
        self.builder = builder or SnapshotBuilder()
        lim = self.builder.limits
        self._cap = max(lim.min_nodes, 8)
        self._r = max(len(self.builder.resource_names), len(FIXED_RESOURCES))
        self._rows: Dict[str, int] = {}
        # free rows below the high watermark: a lowest-first heap plus a
        # membership set (heap entries invalidated by compaction are
        # discarded lazily on pop) — reusing the LOWEST hole keeps the
        # live set naturally packed toward row 0
        self._free: List[int] = []
        self._free_set: set = set()
        self._high = 0  # rows in use (high watermark after frees are reused)
        self.node_axis_headroom = float(self.NODE_AXIS_HEADROOM)
        self.bucket_shrink_dwell = int(self.BUCKET_SHRINK_DWELL)
        self.compaction_batch_rows = int(self.COMPACTION_BATCH_ROWS)
        # pad-bucket hysteresis state: the bucket currently exposed by
        # tensors(), the consecutive below-bucket generations seen, and
        # the generation the last dwell tick was counted at (so several
        # tensors() calls within one encode count once)
        self._bucket = vb.pad_dim(0, lim.min_nodes)
        self._dwell = 0
        self._dwell_gen = 0
        # compaction observability (mirrored into scheduler_compactions_
        # total / scheduler_compaction_moved_rows each cycle)
        self.compactions_total = 0
        self.compaction_moved_rows_total = 0
        self.node_names: List[Optional[str]] = []
        # the api objects behind the rows, retained like _pods below: the
        # host-fallback solver (models.batch_scheduler._host_fallback)
        # rebuilds an object-model view when the device path is tripped
        self._node_objs: Dict[str, api.Node] = {}
        self._pods: Dict[str, api.Pod] = {}       # bound/assumed, by pod key
        self._pod_node: Dict[str, str] = {}
        self._pods_by_node: Dict[str, List[str]] = {}
        # Generation protocol for device-resident mirrors (the
        # cache.go:185-260 snapshotGeneration analogue, per ROW and split
        # by mutation family so consumers re-upload only what moved):
        #   _static_gen[i] — node-object state (allocatable, labels,
        #       taints, topology, images) last changed at this generation;
        #   _usage_gen[i]  — accumulated pod usage (requested, ports);
        #   _struct_gen    — array identity/axis changes (grow, resource
        #       widen, compaction): mirrors older than this must resync
        #       in full.
        self._gen = 1
        self._struct_gen = 1
        self._alloc(self._cap, self._r)

    def _bump(self) -> int:
        self._gen += 1
        return self._gen

    # -- storage ----------------------------------------------------------

    def _alloc(self, cap: int, r: int) -> None:
        lim = self.builder.limits
        self.allocatable = np.zeros((cap, r), dtype=np.float32)
        self.requested = np.zeros((cap, r), dtype=np.float32)
        self.nonzero_requested = np.zeros((cap, r), dtype=np.float32)
        self.node_valid = np.zeros(cap, dtype=bool)
        self.name_id = np.full(cap, -1, dtype=np.int32)
        self.label_bits = np.zeros((cap, lim.label_words), dtype=np.uint32)
        self.taint_bits = np.zeros((3, cap, lim.taint_words), dtype=np.uint32)
        self.port_bits = np.zeros((cap, lim.port_words), dtype=np.uint32)
        self.topo_ids = np.full((cap, len(lim.topology_keys)), -1, dtype=np.int32)
        self.image_bits = np.zeros((cap, lim.image_words), dtype=np.uint32)
        self.slice_id = np.full(cap, -1, dtype=np.int32)
        self.torus_coords = np.full((cap, 4), -1, dtype=np.int32)
        self.slice_dims = np.zeros((cap, 3), dtype=np.int32)
        self.slice_pos = np.full(cap, -1, dtype=np.int32)
        # i64 is deliberate here: monotonic host-side generation counters
        # for the mirror sync protocol — they never cross to the device
        # and must not wrap within a process lifetime
        self._static_gen = np.zeros(cap, dtype=np.int64)  # graftlint: disable=tensor-contract -- host-only generation counter, never device-resident
        self._usage_gen = np.zeros(cap, dtype=np.int64)  # graftlint: disable=tensor-contract -- host-only generation counter, never device-resident

    def _grow(self, cap: Optional[int] = None) -> None:
        """Reallocate the backing arrays with headroom.  Row indices are
        PRESERVED and the padded bucket is derived by tensors() from
        `_high`, so a grow is NOT a struct event: the device mirrors see
        new rows as ordinary dirty rows (or a pad-bucket crossing they
        absorb with an in-place resident grow) — never a forced full
        resync.  `struct_generation` is reserved for genuine identity
        changes (resource-axis widening, force_struct_event)."""
        if cap is None:
            cap = vb.pad_dim(
                max(int(self._cap * self.node_axis_headroom), self._high + 1),
                self.builder.limits.min_nodes,
            )
        old = self.tensors(pad=False)
        old_sg, old_ug = self._static_gen, self._usage_gen
        self._alloc(cap, self._r)
        h = self._high
        self.allocatable[:h] = old.allocatable[:h]
        self.requested[:h] = old.requested[:h]
        self.nonzero_requested[:h] = old.nonzero_requested[:h]
        self.node_valid[:h] = old.node_valid[:h]
        self.name_id[:h] = old.name_id[:h]
        self.label_bits[:h] = old.label_bits[:h]
        self.taint_bits[:, :h] = old.taint_bits[:, :h]
        self.port_bits[:h] = old.port_bits[:h]
        self.topo_ids[:h] = old.topo_ids[:h]
        self.image_bits[:h] = old.image_bits[:h]
        self.slice_id[:h] = old.slice_id[:h]
        self.torus_coords[:h] = old.torus_coords[:h]
        self.slice_dims[:h] = old.slice_dims[:h]
        self.slice_pos[:h] = old.slice_pos[:h]
        self._static_gen[:h] = old_sg[:h]
        self._usage_gen[:h] = old_ug[:h]
        self._cap = cap

    def ensure_resources(self) -> None:
        """Widen the resource axis after new scalar resources appeared in
        the builder's vocabulary (new columns read zero — nodes that don't
        expose a resource can't fit pods requesting it)."""
        r = len(self.builder.resource_names)
        if r <= self._r:
            return
        pad = ((0, 0), (0, r - self._r))
        self.allocatable = np.pad(self.allocatable, pad)
        self.requested = np.pad(self.requested, pad)
        self.nonzero_requested = np.pad(self.nonzero_requested, pad)
        self._r = r
        self._struct_gen = self._bump()

    # -- node lifecycle ---------------------------------------------------

    def add_node(self, node: api.Node) -> None:
        name = node.meta.name
        if name in self._rows:
            self.update_node(node)
            return
        self.builder._resource_vector(node.status.allocatable, 0, grow=True)
        self.ensure_resources()
        i = self._pop_free()
        if i is None:
            if self._high == self._cap:
                self._grow()
            i = self._high
            self._high += 1
            self.node_names.append(None)
        self._rows[name] = i
        self.node_names[i] = name
        self._node_objs[name] = node
        self._pods_by_node.setdefault(name, [])
        self.builder._write_node_row(
            node, i, self.node_valid, self.name_id, self.allocatable,
            self.label_bits, self.taint_bits, self.topo_ids, self.image_bits,
            self.slice_id, self.torus_coords, self.slice_dims, self.slice_pos,
        )
        self._static_gen[i] = self._usage_gen[i] = self._bump()

    def update_node(self, node: api.Node) -> None:
        """Re-encode a node's static state in place; accumulated pod usage
        (requested/ports) is preserved — it derives from bound pods, not
        the node object."""
        i = self._rows[node.meta.name]
        self._node_objs[node.meta.name] = node
        self.builder._resource_vector(node.status.allocatable, 0, grow=True)
        self.ensure_resources()
        self.builder._write_node_row(
            node, i, self.node_valid, self.name_id, self.allocatable,
            self.label_bits, self.taint_bits, self.topo_ids, self.image_bits,
            self.slice_id, self.torus_coords, self.slice_dims, self.slice_pos,
        )
        self._static_gen[i] = self._bump()

    def _pop_free(self) -> Optional[int]:
        """Lowest free row below the watermark, or None.  Heap entries
        compaction consumed are discarded lazily here."""
        while self._free:
            i = heapq.heappop(self._free)
            if i in self._free_set:
                self._free_set.discard(i)
                return i
        return None

    def remove_node(self, name: str) -> None:
        i = self._rows.pop(name)
        self._node_objs.pop(name, None)
        for pk in self._pods_by_node.pop(name, []):
            self._pods.pop(pk, None)
            self._pod_node.pop(pk, None)
        self._clear_row(i)
        heapq.heappush(self._free, i)
        self._free_set.add(i)
        self._maybe_compact()

    def _clear_row(self, i: int) -> None:
        self.node_valid[i] = False
        self.name_id[i] = -1
        self.allocatable[i] = 0
        self.requested[i] = 0
        self.nonzero_requested[i] = 0
        self.label_bits[i] = 0
        self.taint_bits[:, i] = 0
        self.port_bits[i] = 0
        self.topo_ids[i] = -1
        self.image_bits[i] = 0
        self.slice_id[i] = -1
        self.torus_coords[i] = -1
        self.slice_dims[i] = 0
        self.slice_pos[i] = -1
        self.node_names[i] = None
        self._static_gen[i] = self._usage_gen[i] = self._bump()

    def _move_row(self, src: int, dst: int) -> None:
        self.node_valid[dst] = self.node_valid[src]
        self.name_id[dst] = self.name_id[src]
        self.allocatable[dst] = self.allocatable[src]
        self.requested[dst] = self.requested[src]
        self.nonzero_requested[dst] = self.nonzero_requested[src]
        self.label_bits[dst] = self.label_bits[src]
        self.taint_bits[:, dst] = self.taint_bits[:, src]
        self.port_bits[dst] = self.port_bits[src]
        self.topo_ids[dst] = self.topo_ids[src]
        self.image_bits[dst] = self.image_bits[src]
        self.slice_id[dst] = self.slice_id[src]
        self.torus_coords[dst] = self.torus_coords[src]
        self.slice_dims[dst] = self.slice_dims[src]
        self.slice_pos[dst] = self.slice_pos[src]
        name = self.node_names[src]
        self.node_names[dst] = name
        self._rows[name] = dst
        self._static_gen[dst] = self._usage_gen[dst] = self._bump()
        self._clear_row(src)

    def _trim_tail(self) -> int:
        """Lower the high watermark past trailing holes (free — no row
        moves).  Amortized O(1) per removal: each trimmed row was freed
        exactly once."""
        trimmed = 0
        while self._high > 0 and not self.node_valid[self._high - 1]:
            self._high -= 1
            self._free_set.discard(self._high)
            self.node_names.pop()
            trimmed += 1
        return trimmed

    def _maybe_compact(self) -> None:
        """Deferred, bounded compaction: once occupancy drops below half
        the watermark, relocate at most `compaction_batch_rows` tail rows
        into the lowest holes per invocation (plus free trailing-hole
        trims), so snapshots return to a smaller shape bucket WITHOUT an
        O(live) sorted scan on every remove_node.  A scale-down storm
        triggers this repeatedly; each live row moves at most once per
        drain, so a full 10k-node drain does O(live) total work.  Moved
        rows bump their generations — they are ordinary dirty rows for
        the device mirrors, not a struct event; the exposed pad bucket
        follows later through tensors()'s shrink-dwell hysteresis."""
        live = len(self._rows)
        # trailing holes trim unconditionally (free, amortized O(1) per
        # removal): a newest-first drain must lower the watermark even
        # when occupancy never falls below half — otherwise the pad
        # bucket can't follow the fleet back down
        trimmed = self._trim_tail()
        if self._high <= max(2 * live, self.builder.limits.min_nodes):
            if trimmed:
                self.compactions_total += 1
            return
        moved = 0
        floor = max(live, self.builder.limits.min_nodes)
        budget = self.compaction_batch_rows
        while moved < budget and self._high > floor:
            dst = self._pop_free()
            if dst is None or dst >= self._high - 1:
                # no hole strictly below the tail row (a >= hole can
                # only be a race-free artifact of the floor clamp)
                if dst is not None:
                    heapq.heappush(self._free, dst)
                    self._free_set.add(dst)
                break
            self._move_row(self._high - 1, dst)
            moved += 1
            self._high -= 1
            self.node_names.pop()
            trimmed += self._trim_tail()
        if moved or trimmed:
            self.compactions_total += 1
            self.compaction_moved_rows_total += moved

    # -- pod (bound/assumed) lifecycle ------------------------------------

    @staticmethod
    def _pod_key(pod: api.Pod) -> str:
        return f"{pod.meta.namespace}/{pod.meta.name}"

    def add_pod(self, pod: api.Pod, node_name: Optional[str] = None) -> None:
        """Account a bound (or assumed) pod on its node.  The cache-side
        half of assume (cache.go:AssumePod): resources land immediately so
        the next batch's filters see them."""
        node_name = node_name or pod.spec.node_name
        i = self._rows.get(node_name)
        if i is None:
            raise KeyError(f"node {node_name!r} not in cluster state")
        key = self._pod_key(pod)
        if key in self._pods:
            raise ValueError(f"pod {key} already accounted")
        self.builder._resource_vector(
            self.builder.effective_requests(pod), 0, grow=True
        )
        self.ensure_resources()
        req, nz, ports = self.builder.pod_usage(pod, self._r)
        self.requested[i] += req
        self.nonzero_requested[i] += nz
        self.port_bits[i] |= ports
        self._usage_gen[i] = self._bump()
        self._pods[key] = pod
        self._pod_node[key] = node_name
        self._pods_by_node[node_name].append(key)

    def remove_pod(self, pod: api.Pod) -> None:
        """Unaccount a pod (ForgetPod / RemovePod).  Port bits are
        recomputed from the node's remaining pods — bits aren't
        subtractive."""
        key = self._pod_key(pod)
        node_name = self._pod_node.pop(key)
        self._pods.pop(key)
        i = self._rows[node_name]
        self._pods_by_node[node_name].remove(key)
        req, nz, _ = self.builder.pod_usage(pod, self._r)
        self.requested[i] -= req
        self.nonzero_requested[i] -= nz
        ports = np.zeros_like(self.port_bits[i])
        for pk in self._pods_by_node[node_name]:
            ports |= self.builder.pod_usage(self._pods[pk], self._r)[2]
        self.port_bits[i] = ports
        self._usage_gen[i] = self._bump()

    def has_pod(self, pod: api.Pod) -> bool:
        return self._pod_key(pod) in self._pods

    def bound_pods(self) -> List[Tuple[api.Pod, int]]:
        """(pod, node row) pairs — input to per-batch constraint tables."""
        return [
            (p, self._rows[self._pod_node[k]]) for k, p in self._pods.items()
        ]

    # -- snapshot ---------------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return len(self._rows)

    @property
    def node_axis_bucket(self) -> int:
        """The pad bucket tensors() currently exposes (post-hysteresis)
        — mirrored into scheduler_node_axis_bucket each cycle."""
        return min(self._bucket, self._cap)

    def tensors(self, pad: bool = True) -> ClusterTensors:
        """Current cluster tensors; O(1) views into the backing arrays
        (padded to the power-of-two bucket so jit cache keys are stable).
        The views alias live state — solvers transfer to device
        immediately, so mutate-after-snapshot is safe in practice; copy()
        if you need isolation.

        The exposed bucket follows grow-eager / shrink-lazy hysteresis:
        it rises to pad_dim(_high) immediately, but falls only after
        occupancy has sat below the lower bucket for
        `bucket_shrink_dwell` consecutive snapshot GENERATIONS (several
        tensors() calls against one unchanged generation count once), so
        add/remove oscillation around a bucket boundary never thrashes
        the compile-key lattice or the resident device arrays."""
        if pad:
            want = vb.pad_dim(self._high, self.builder.limits.min_nodes)
            if want >= self._bucket:
                self._bucket = want  # grow eagerly: rows must fit NOW
                self._dwell = 0
                self._dwell_gen = self._gen
            elif self._gen != self._dwell_gen:
                self._dwell_gen = self._gen
                self._dwell += 1
                if self._dwell >= self.bucket_shrink_dwell:
                    self._bucket = want  # dwell served: shrink to fit
                    self._dwell = 0
            n = self._bucket
        else:
            n = self._cap
        n = min(n, self._cap)
        return ClusterTensors(
            allocatable=self.allocatable[:n],
            requested=self.requested[:n],
            nonzero_requested=self.nonzero_requested[:n],
            node_valid=self.node_valid[:n],
            name_id=self.name_id[:n],
            label_bits=self.label_bits[:n],
            taint_bits=self.taint_bits[:, :n],
            port_bits=self.port_bits[:n],
            topo_ids=self.topo_ids[:n],
            image_bits=self.image_bits[:n],
            slice_id=self.slice_id[:n],
            torus_coords=self.torus_coords[:n],
            slice_dims=self.slice_dims[:n],
            slice_pos=self.slice_pos[:n],
        )

    # -- device-mirror sync protocol --------------------------------------

    def configure_elastic_axis(
        self,
        headroom: Optional[float] = None,
        shrink_dwell: Optional[int] = None,
        compaction_batch_rows: Optional[int] = None,
    ) -> None:
        """Apply the elastic-node-axis knobs (SchedulerConfiguration's
        nodeAxisHeadroom / bucketShrinkDwell / compactionBatchRows —
        FrameworkRegistry threads them onto the shared state)."""
        if headroom is not None:
            if headroom < 1.0:
                raise ValueError("node_axis_headroom must be >= 1.0")
            self.node_axis_headroom = float(headroom)
        if shrink_dwell is not None:
            if shrink_dwell < 1:
                raise ValueError("bucket_shrink_dwell must be >= 1")
            self.bucket_shrink_dwell = int(shrink_dwell)
        if compaction_batch_rows is not None:
            if compaction_batch_rows < 1:
                raise ValueError("compaction_batch_rows must be >= 1")
            self.compaction_batch_rows = int(compaction_batch_rows)

    def force_struct_event(self) -> None:
        """Declare a genuine axis-identity change: every mirror must
        full-resync.  The escape hatch for mutations outside the row
        protocol (tests, external surgery on the backing arrays)."""
        self._struct_gen = self._bump()

    @property
    def generation(self) -> int:
        return self._gen

    @property
    def struct_generation(self) -> int:
        """Mirrors synced before this generation must full-resync: the
        backing arrays were re-axised since (resource widening,
        force_struct_event).  Backing-array GROWTH and pad-bucket moves
        are deliberately NOT struct events — row indices survive them,
        so mirrors absorb the shape change in place (models/mirror.py
        incremental grow) with the full RESHARDED re-upload kept as the
        safety path."""
        return self._struct_gen

    def dirty_rows(self, synced_gen: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Row indices (static-family, usage-family) changed since
        synced_gen, within the first n rows.  Callers must already have
        checked struct_generation and the padded shape."""
        n = min(n, self._cap)
        static = np.nonzero(self._static_gen[:n] > synced_gen)[0]
        usage = np.nonzero(self._usage_gen[:n] > synced_gen)[0]
        return static.astype(np.int32), usage.astype(np.int32)


def _intern_pod_term(
    rows: List[tuple], index: Dict[tuple, int],
    term: api.PodAffinityTerm, owner: api.Pod,
) -> int:
    """Shared (anti-)affinity term interning: rows key on
    (topologyKey, merged selector signature, namespaces) — one
    implementation for required, anti, and preferred term tables."""
    if term.namespace_selector is not None:
        raise OverflowError(
            "PodAffinityTerm.namespace_selector requires Namespace "
            "objects, which are not modelled; list namespaces "
            "explicitly instead"
        )
    namespaces = tuple(sorted(term.namespaces or [owner.meta.namespace]))
    sel = _merge_match_label_keys(
        term.label_selector, term.match_label_keys, owner.meta.labels
    )
    sig = (term.topology_key, _label_selector_signature(sel), namespaces)
    idx = index.get(sig)
    if idx is None:
        idx = len(rows)
        index[sig] = idx
        rows.append((term.topology_key, sel, namespaces))
    return idx


def _refine_classes(
    pods: PodBatch,
    spread: SpreadTable,
    terms: TermTable,
    prefpod: Optional[PrefPodTable] = None,
    images: Optional[ImageTable] = None,
) -> PodBatch:
    """Split spec-equivalence classes by constraint identity.

    _pod_classes groups on the static Filter/Score inputs only — enough
    for the greedy scan, which evaluates spread/inter-pod per POD index.
    The joint auction evaluates those families per CLASS representative,
    so two pods with identical static state but different constraints
    (e.g. two services' pods with self-anti-affinity) must not share a
    class; the signature here adds each pod's spread rows + match flags
    and (anti-)affinity term memberships."""
    has_pref = prefpod is not None and prefpod.valid.any()
    has_images = images is not None and (images.pod_ids >= 0).any()
    if not (spread.valid.any() or terms.valid.any() or has_pref or has_images):
        return pods
    p = pods.class_id.shape[0]
    parts = [
            pods.class_id.view(np.uint32)[:, None],
            spread.pod_idx.view(np.uint32),
            spread.pod_matches.astype(np.uint8).view(np.uint8).reshape(p, -1).astype(np.uint32),
            terms.aff_idx.view(np.uint32),
            terms.anti_idx.view(np.uint32),
            terms.matches_incoming,  # packed u32 words: already a signature
            terms.self_match_all.astype(np.uint32)[:, None],
    ]
    if has_pref:
        parts += [
            prefpod.pod_idx.view(np.uint32),
            prefpod.pod_weight.view(np.uint32),
            prefpod.matches_incoming.astype(np.uint32),
        ]
    if has_images:
        # n_containers drives the ImageLocality clamp threshold
        # (image_locality_score hi = 1000MB x containers) and the auction
        # scores images per CONSTRAINT class — two pods with identical
        # known-image rows but different container counts must not share
        # a constraint class or one inherits the other's threshold
        parts += [
            images.pod_ids.view(np.uint32),
            images.n_containers.view(np.uint32)[:, None],
        ]
    cons_sig = np.ascontiguousarray(np.concatenate(parts[1:], axis=1))
    cons_id, cons_reps = _first_seen_unique(cons_sig)
    joint_sig = np.ascontiguousarray(
        np.stack([pods.class_id.view(np.uint32), cons_id.view(np.uint32)], axis=1)
    )
    class_id, reps = _first_seen_unique(joint_sig)
    c_dim = vb.pad_dim(len(reps), 1)
    class_rep = np.full(c_dim, -1, dtype=np.int32)
    class_rep[: len(reps)] = reps
    joint_spec = np.zeros(c_dim, dtype=np.int32)
    joint_spec[: len(reps)] = pods.class_id[reps]
    joint_cons = np.zeros(c_dim, dtype=np.int32)
    joint_cons[: len(reps)] = cons_id[reps]
    cc_dim = vb.pad_dim(len(cons_reps), 1)
    cons_rep = np.full(cc_dim, -1, dtype=np.int32)
    cons_rep[: len(cons_reps)] = cons_reps
    return pods._replace(
        class_id=class_id, class_rep=class_rep,
        spec_rep=pods.class_rep, joint_spec=joint_spec,
        cons_rep=cons_rep, joint_cons=joint_cons,
    )


def _first_seen_unique(sig: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Group rows of a 2-D signature array, ids in first-seen order.
    Returns (ids i32[P], first-row-index per group).  Vectorized — a
    Python dict loop here cost ~40ms per 10k pods on the per-batch
    encode path."""
    p = sig.shape[0]
    row_bytes = sig.view(np.uint8).reshape(p, -1)
    void = row_bytes.view(np.dtype((np.void, row_bytes.shape[1]))).reshape(p)
    _, first_idx, inverse = np.unique(
        void, return_index=True, return_inverse=True
    )
    order = np.argsort(first_idx, kind="stable")
    remap = np.empty(order.shape[0], dtype=np.int32)
    remap[order] = np.arange(order.shape[0], dtype=np.int32)
    return remap[inverse].astype(np.int32), first_idx[order]


def _pod_classes(
    valid: np.ndarray,
    name_id: np.ndarray,
    sel_idx: np.ndarray,
    tol_bits: np.ndarray,
    tol_all: np.ndarray,
    port_bits: np.ndarray,
    pref_idx: np.ndarray,
    pref_weight: np.ndarray,
    req: np.ndarray,
    nonzero_req: np.ndarray,
    pod_shape: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Group pods into spec-equivalence classes (see PodBatch docstring).

    The signature covers every placement-independent input of the
    Filter/Score chain: NodeName, NodeAffinity selector row, tolerations,
    host ports, preferred terms, and resource requests — so two pods of
    one class see byte-identical filter masks *and* score rows against
    any given cluster state (the joint solver scores per class, not per
    pod).  Spread constraints and inter-pod terms stay per-pod (they
    interact with solver state).
    """
    p = valid.shape[0]
    sig = np.concatenate(
        [
            valid.astype(np.uint32)[:, None],
            name_id.view(np.uint32)[:, None],
            sel_idx.view(np.uint32)[:, None],
            np.moveaxis(tol_bits, 1, 0).reshape(p, -1),
            tol_all.T.astype(np.uint32),
            port_bits,
            pref_idx.view(np.uint32),
            pref_weight.view(np.uint32),
            req.view(np.uint32),
            nonzero_req.view(np.uint32),
        ]
        + ([pod_shape.view(np.uint32)] if pod_shape is not None else []),
        axis=1,
    )
    # Row-bytes dict dedup: ~10x faster than np.unique(axis=0)'s
    # lexicographic row sort at 10k+ pods.
    sig = np.ascontiguousarray(sig)
    row_bytes = sig.view(np.uint8).reshape(p, -1)
    index: Dict[bytes, int] = {}
    class_id = np.empty(p, dtype=np.int32)
    reps: List[int] = []
    for i in range(p):
        key = row_bytes[i].tobytes()
        c = index.get(key)
        if c is None:
            c = len(reps)
            index[key] = c
            reps.append(i)
        class_id[i] = c
    c_dim = vb.pad_dim(len(reps), 1)
    class_rep = np.full(c_dim, -1, dtype=np.int32)
    class_rep[: len(reps)] = np.asarray(reps, dtype=np.int32)
    return class_id, class_rep


def _merge_match_label_keys(
    sel: Optional[api.LabelSelector],
    keys: Sequence[str],
    owner_labels: Dict[str, str],
) -> api.LabelSelector:
    """Fold the owning pod's values at match_label_keys into the selector
    (podtopologyspread/plugin.go + interpodaffinity since 1.29: an In
    requirement per present key; absent keys are skipped)."""
    sel = sel or api.LabelSelector()
    extra = [
        api.Requirement(k, api.OP_IN, [owner_labels[k]])
        for k in keys
        if k in owner_labels
    ]
    if not extra:
        return sel
    return api.LabelSelector(
        match_labels=dict(sel.match_labels),
        match_expressions=list(sel.match_expressions) + extra,
    )


def _label_selector_signature(sel: Optional[api.LabelSelector]) -> tuple:
    if sel is None:
        return ()
    return tuple(
        (r.key, r.op, tuple(sorted(r.values))) for r in sel.requirements()
    )


def _fill_selector_table(
    sel_rows: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    t_cap: int,
    e_cap: int,
    k_cap: int,
) -> SelectorTable:
    s_dim = vb.pad_constraint_dim(len(sel_rows))
    sel = SelectorTable(
        expr_ids=np.full((s_dim, t_cap, e_cap, k_cap), -1, dtype=np.int32),
        expr_op=np.zeros((s_dim, t_cap, e_cap), dtype=np.int32),
        expr_slot=np.full((s_dim, t_cap, e_cap), DOMAIN_LABELS, dtype=np.int32),
        term_valid=np.zeros((s_dim, t_cap), dtype=bool),
    )
    for s, (ids, ops, slots, tv) in enumerate(sel_rows):
        sel.expr_ids[s] = ids
        sel.expr_op[s] = ops
        sel.expr_slot[s] = slots
        sel.term_valid[s] = tv
    return sel


def _fill_preferred_table(
    pref_rows: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    e_cap: int,
    k_cap: int,
) -> PreferredTable:
    f_dim = vb.pad_constraint_dim(len(pref_rows))
    pref = PreferredTable(
        expr_ids=np.full((f_dim, e_cap, k_cap), -1, dtype=np.int32),
        expr_op=np.zeros((f_dim, e_cap), dtype=np.int32),
        expr_slot=np.full((f_dim, e_cap), DOMAIN_LABELS, dtype=np.int32),
        valid=np.zeros(f_dim, dtype=bool),
    )
    for f, (ids, ops, slots) in enumerate(pref_rows):
        pref.expr_ids[f] = ids
        pref.expr_op[f] = ops
        pref.expr_slot[f] = slots
        pref.valid[f] = True
    return pref


def _first_encounter(lids: np.ndarray) -> Tuple[List[int], np.ndarray]:
    """Dense per-batch indices for a vector of store-local ids: returns
    (distinct ids >= 0 in FIRST-ENCOUNTER order, an int32 array of the
    same shape remapping each id to its rank in that order, -1 kept).
    First-encounter order is the per-object dedup tables' insertion
    order, which the columnar path must reproduce exactly for
    bit-identical sel_idx/pref_idx and stable-id tuples."""
    uniq, first = np.unique(lids, return_index=True)
    mask = uniq >= 0
    uniq, first = uniq[mask], first[mask]
    if uniq.size == 0:
        return [], np.full(lids.shape, -1, dtype=np.int32)
    order = np.argsort(first, kind="stable")
    rank = np.empty(uniq.size, dtype=np.int32)
    rank[order] = np.arange(uniq.size, dtype=np.int32)
    pos = np.clip(np.searchsorted(uniq, lids), 0, uniq.size - 1)
    remap = np.where(lids >= 0, rank[pos], -1).astype(np.int32)
    return [int(i) for i in uniq[order]], remap


def _term_signature(term: api.NodeSelectorTerm) -> tuple:
    return tuple(
        (r.key, r.op, tuple(sorted(r.values))) for r in term.match_expressions
    )


def _selector_signature(sel: api.NodeSelector) -> tuple:
    return tuple(_term_signature(t) for t in sel.terms)
