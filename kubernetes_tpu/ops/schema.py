"""Dense tensor schema for cluster state + the snapshot builder.

This is the tensorization of the reference scheduler's per-node bookkeeping
(`framework.NodeInfo`, pkg/scheduler/framework/types.go:542-602) and of the
per-pod scheduling spec.  Everything the Filter/Score kernels consume lives
in statically-shaped arrays:

  ClusterTensors   one row per node: resource vectors + packed bitsets
  PodBatch         one row per pending pod
  SelectorTable    deduplicated required-node-affinity selectors (pods in a
                   real batch overwhelmingly share selectors — a Deployment's
                   pods are identical — so match masks are computed once per
                   distinct selector, [S, N], then gathered per pod)
  PreferredTable   deduplicated preferred scheduling terms for scoring

String state (labels, taints, ports, names, topology values) is interned
exactly via vocabularies (kubernetes_tpu.utils.vocab) and represented as
uint32 bitsets; selector expressions are expanded host-side into explicit
id sets, turning all matching on device into bit tests.  `Exists`/`NotIn`
operators expand against the *current* vocabulary, which is why pod-side
tables are rebuilt per batch while node-side bitsets persist.

Shapes are padded to power-of-two buckets (utils.vocab.pad_dim) so repeated
solves at similar scale hit the XLA compile cache.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..api import types as api
from ..utils import vocab as vb

# Resource axis layout: fixed head + discovered scalar resources.
RESOURCE_CPU = 0          # milli-cores
RESOURCE_MEMORY = 1       # bytes
RESOURCE_EPH = 2          # bytes
RESOURCE_PODS = 3         # pod-count capacity (AllowedPodNumber in the
                          # reference's Resource struct, types.go:593-602)
FIXED_RESOURCES = (api.CPU, api.MEMORY, api.EPHEMERAL_STORAGE, api.PODS)

# Taint-effect axis
EFFECT_INDEX = {api.NO_SCHEDULE: 0, api.PREFER_NO_SCHEDULE: 1, api.NO_EXECUTE: 2}

# Device resource units.  Byte-denominated resources are carried in MiB so
# every realistic quantity (and the products `quantity * 100` the scorers
# form) stays inside float32's exact-integer range (2^24): 64 GiB -> 65536.
# cpu stays in milli-cores, counts stay counts.  This keeps the f32 score
# kernels bit-faithful to the reference's int64 math for MiB-aligned
# requests, which is what real specs use.
DEVICE_UNIT_DIVISOR = {api.MEMORY: 1 << 20, api.EPHEMERAL_STORAGE: 1 << 20}

# Selector expression ops on device
OP_PAD = 0   # slot unused: contributes True
OP_POS = 1   # satisfied iff any listed id present on the node
OP_NEG = 2   # satisfied iff no listed id present on the node

# Expression domains.  Labels that are unique-per-node (hostname) or
# enumerable-per-key (zone, region) live in topo_ids[N, TK] as dense value
# ids rather than in the shared label bitset — a 50k-node cluster would
# otherwise need 50k bits of hostname vocabulary on every node.  Selector
# expressions over those keys evaluate against the topo slot; everything
# else evaluates against the label bitset.
DOMAIN_LABELS = -1          # expr_slot value meaning "label bitset domain"
TOPO_ANY_VALUE = -2         # id meaning "key present with any value" (Exists)


class ClusterTensors(NamedTuple):
    """Per-node state. N = padded node count, R = resource axis,
    LW/TW/PW = label/taint/port bitset words, TK = tracked topology keys."""

    allocatable: np.ndarray        # f32[N, R]
    requested: np.ndarray          # f32[N, R]   actual requests (BalancedAllocation)
    nonzero_requested: np.ndarray  # f32[N, R]   with scoring defaults (LeastAllocated)
    node_valid: np.ndarray         # bool[N]
    name_id: np.ndarray            # i32[N]
    label_bits: np.ndarray         # u32[N, LW]
    taint_bits: np.ndarray         # u32[3, N, TW]  effect-major
    port_bits: np.ndarray          # u32[N, PW]
    topo_ids: np.ndarray           # i32[N, TK]  per-key value id, -1 absent


class SelectorTable(NamedTuple):
    """S distinct required-node selectors in OR-of-AND form."""

    expr_ids: np.ndarray   # i32[S, T, E, K]  expanded ids, -1 pad
    expr_op: np.ndarray    # i32[S, T, E]     OP_PAD/OP_POS/OP_NEG
    expr_slot: np.ndarray  # i32[S, T, E]     DOMAIN_LABELS or topo slot
    term_valid: np.ndarray  # bool[S, T]


class PreferredTable(NamedTuple):
    """F distinct preferred NodeSelectorTerms (AND of expressions)."""

    expr_ids: np.ndarray   # i32[F, E, K]
    expr_op: np.ndarray    # i32[F, E]
    expr_slot: np.ndarray  # i32[F, E]
    valid: np.ndarray      # bool[F]


class SpreadTable(NamedTuple):
    """C distinct topology-spread constraint instances (constraint spec +
    owner namespace/selector/key-set, since eligibility is owner-scoped).
    Z = padded max topology-value vocabulary size.

    Counting state lives as per-node match vectors ([C, N]); the solver
    scatter-adds them into per-topology-value counts on device (the
    tensorization of preFilterState.TpPairToMatchNum,
    podtopologyspread/filtering.go + scoring.go)."""

    valid: np.ndarray         # bool[C]
    slot: np.ndarray          # i32[C]   topology-key slot in topo_ids
    max_skew: np.ndarray      # f32[C]
    hard: np.ndarray          # bool[C]  DoNotSchedule (filter) vs ScheduleAnyway (score)
    owner_sel_idx: np.ndarray  # i32[C]  owner pod's SelectorTable row, -1 none
    owner_keys: np.ndarray    # bool[C, TK] topology keys the owner's constraints use
    node_matches: np.ndarray  # f32[C, N] bound pods on node n matching constraint c
    pod_matches: np.ndarray   # bool[P, C] pending pod p matches c's selector+namespace
    pod_idx: np.ndarray       # i32[P, MC] constraint rows per pod, -1 pad


class TermTable(NamedTuple):
    """T distinct inter-pod (anti-)affinity terms: batch pods' required
    affinity + anti-affinity terms, plus bound pods' anti-affinity terms
    (needed for the existing-pods-anti-affinity direction,
    interpodaffinity/filtering.go:306-366).

    counts_match[t, v] (# pods whose labels+ns match term t in topology v)
    and counts_owner[t, v] (# pods *carrying* t as an anti-affinity term)
    are assembled on device from the per-node vectors below and updated
    in-scan as the solver places pods."""

    valid: np.ndarray            # bool[T]
    slot: np.ndarray             # i32[T]   topology-key slot
    node_matches: np.ndarray     # f32[T, N] bound pods on n matching term t
    node_owners: np.ndarray      # f32[T, N] bound pods on n owning anti-term t
    matches_incoming: np.ndarray  # bool[P, T] batch pod p matches term t
    aff_idx: np.ndarray          # i32[P, MA] pod's required affinity terms
    anti_idx: np.ndarray         # i32[P, MA] pod's required anti-affinity terms
    self_match_all: np.ndarray   # bool[P] pod matches all its own affinity terms


class PodBatch(NamedTuple):
    """Per-pending-pod state. P = padded batch size, MT = preferred slots."""

    valid: np.ndarray        # bool[P]
    req: np.ndarray          # f32[P, R]
    nonzero_req: np.ndarray  # f32[P, R]
    name_id: np.ndarray      # i32[P]  -1 none, -2 names an unknown node
    sel_idx: np.ndarray      # i32[P]  -1 no required selector
    tol_bits: np.ndarray     # u32[3, P, TW]
    tol_all: np.ndarray      # bool[3, P]
    port_bits: np.ndarray    # u32[P, PW]
    pref_idx: np.ndarray     # i32[P, MT]  rows of PreferredTable, -1 pad
    pref_weight: np.ndarray  # f32[P, MT]


class Snapshot(NamedTuple):
    cluster: ClusterTensors
    pods: PodBatch
    selectors: SelectorTable
    preferred: PreferredTable
    spread: SpreadTable
    terms: TermTable


@dataclass
class SnapshotLimits:
    """Static capacities.  All are *caps*, checked at encode time with a
    clear OverflowError; raise them (new executable) when a workload
    exceeds them."""

    max_terms: int = 4          # T: NodeSelectorTerms per selector
    max_exprs: int = 8          # E: expressions per term (incl. node_selector)
    max_ids_per_expr: int = 16  # K: expanded ids per expression
    max_preferred: int = 4      # MT: preferred terms per pod
    max_spread_per_pod: int = 4  # MC: topology spread constraints per pod
    max_pod_terms: int = 4      # MA: required (anti-)affinity terms per pod
    label_capacity: int = 4096
    taint_capacity: int = 256
    port_capacity: int = 2048
    topology_keys: Tuple[str, ...] = (api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)
    min_nodes: int = 8
    min_pods: int = 8

    @property
    def label_words(self) -> int:
        return vb.words_for(self.label_capacity)

    @property
    def taint_words(self) -> int:
        return vb.words_for(self.taint_capacity)

    @property
    def port_words(self) -> int:
        return vb.words_for(self.port_capacity)


@dataclass
class SnapshotMeta:
    """Host-side sidecar of a Snapshot: real counts and decode tables."""

    num_nodes: int
    num_pods: int
    node_names: List[str]
    resource_names: List[str]
    limits: SnapshotLimits
    topo_z: int = 1  # padded max topology-value vocab size (the Z axis)

    def node_name(self, idx: int) -> Optional[str]:
        if 0 <= idx < self.num_nodes:
            return self.node_names[idx]
        return None


class SnapshotBuilder:
    """Encodes api.Node / api.Pod objects into Snapshot tensors.

    Vocabularies are append-only and owned by the builder, so successive
    snapshots from the same builder keep node bitsets comparable (the
    incremental analogue of the reference cache's generation-tracked
    UpdateSnapshot, pkg/scheduler/internal/cache/cache.go:185).
    """

    def __init__(self, limits: Optional[SnapshotLimits] = None):
        self.limits = limits or SnapshotLimits()
        self.label_vocab = vb.PairVocab()
        self.taint_vocab = vb.PairVocab()
        self.port_vocab = vb.Vocab()
        self.name_vocab = vb.Vocab()
        self.topo_vocabs: Dict[str, vb.Vocab] = {
            k: vb.Vocab() for k in self.limits.topology_keys
        }
        self.scalar_resources: List[str] = []
        self._scalar_index: Dict[str, int] = {}

    # -- resource axis ----------------------------------------------------

    @property
    def resource_names(self) -> List[str]:
        return list(FIXED_RESOURCES) + self.scalar_resources

    def _resource_index(self, name: str, grow: bool) -> Optional[int]:
        try:
            return FIXED_RESOURCES.index(name)
        except ValueError:
            pass
        idx = self._scalar_index.get(name)
        if idx is None and grow:
            idx = len(FIXED_RESOURCES) + len(self.scalar_resources)
            self._scalar_index[name] = idx
            self.scalar_resources.append(name)
        return idx

    def _resource_vector(self, requests: Dict[str, int], r: int, grow: bool = True) -> np.ndarray:
        out = np.zeros(r, dtype=np.float32)
        for name, val in requests.items():
            idx = self._resource_index(name, grow)
            if idx is not None and idx < r:
                out[idx] = float(val) / DEVICE_UNIT_DIVISOR.get(name, 1)
        return out

    # -- vocab interning ---------------------------------------------------

    def _intern_node_strings(self, nodes: Sequence[api.Node]) -> None:
        topo = self.topo_vocabs
        for node in nodes:
            self.name_vocab.intern(node.meta.name)
            for k, v in node.meta.labels.items():
                if k in topo:
                    topo[k].intern(v)
                else:
                    self.label_vocab.intern((k, v))
            for t in node.effective_taints():
                self.taint_vocab.intern((t.key, t.value))

    # -- selector expansion ------------------------------------------------

    def _expand_requirement(self, r: api.Requirement) -> Tuple[int, int, List[int]]:
        """Return (op, domain slot, expanded ids).  Expansion is exact
        against the current vocabulary: a value no node carries simply
        yields no id, which under OP_POS means 'matches nowhere' — precisely
        the reference semantics of an In clause naming an absent value.

        Expressions over topology keys evaluate against topo_ids[:, slot]
        (see DOMAIN_LABELS); everything else against the label bitset."""
        try:
            slot = self.limits.topology_keys.index(r.key)
            voc = self.topo_vocabs[r.key]

            def lookup(v: str) -> int:
                return voc.get(v)

            def all_ids() -> List[int]:
                return [TOPO_ANY_VALUE]

            def value_of(i: int) -> str:
                return voc.item(i)

            id_range = range(len(voc))
        except ValueError:
            slot = DOMAIN_LABELS
            voc = None

            def lookup(v: str) -> int:
                return self.label_vocab.get((r.key, v))

            def all_ids() -> List[int]:
                return self.label_vocab.ids_for_key(r.key)

            def value_of(i: int) -> str:
                return self.label_vocab.item(i)[1]

            id_range = self.label_vocab.ids_for_key(r.key)

        if r.op == api.OP_IN:
            ids = [lookup(v) for v in r.values]
            return OP_POS, slot, [i for i in ids if i >= 0]
        if r.op == api.OP_NOT_IN:
            ids = [lookup(v) for v in r.values]
            return OP_NEG, slot, [i for i in ids if i >= 0]
        if r.op == api.OP_EXISTS:
            return OP_POS, slot, all_ids()
        if r.op == api.OP_DOES_NOT_EXIST:
            return OP_NEG, slot, all_ids()
        if r.op in (api.OP_GT, api.OP_LT):
            # Gt/Lt compare integer label values; expand exactly against the
            # known value set for the key (the vocab holds every value
            # present in the cluster, so this stays exact).  An unparseable
            # bound means the requirement matches nothing (not an encode
            # failure — one malformed spec must not sink the whole batch).
            ids: List[int] = []
            try:
                bound = int(r.values[0]) if r.values else None
            except ValueError:
                bound = None
            if bound is None:
                return OP_POS, slot, ids
            for i in id_range:
                try:
                    num = int(value_of(i))
                except ValueError:
                    continue
                if (r.op == api.OP_GT and num > bound) or (r.op == api.OP_LT and num < bound):
                    ids.append(i)
            return OP_POS, slot, ids
        raise ValueError(f"unsupported selector operator {r.op}")

    def _encode_term(
        self, exprs: Sequence[api.Requirement], e_cap: int, k_cap: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if len(exprs) > e_cap:
            raise OverflowError(
                f"{len(exprs)} expressions in one term exceed max_exprs={e_cap}"
            )
        ids = np.full((e_cap, k_cap), -1, dtype=np.int32)
        ops = np.zeros(e_cap, dtype=np.int32)
        slots = np.full(e_cap, DOMAIN_LABELS, dtype=np.int32)
        for j, r in enumerate(exprs):
            op, slot, expanded = self._expand_requirement(r)
            ops[j] = op
            slots[j] = slot
            ids[j] = vb.pad_ids(expanded, k_cap)
        return ids, ops, slots

    # -- pod pieces --------------------------------------------------------

    def _encode_tolerations(
        self, tols: Sequence[api.Toleration]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Expand tolerations into per-effect tolerated-taint bitsets.
        Matching semantics follow v1.Toleration.ToleratesTaint
        (api/core/v1/toleration.go): empty effect spans all effects, empty
        key + Exists tolerates everything, Exists-with-key tolerates every
        value of the key."""
        lim = self.limits
        bits = np.zeros((3, lim.taint_words), dtype=np.uint32)
        tol_all = np.zeros(3, dtype=bool)
        for t in tols:
            effects = range(3) if not t.effect else [EFFECT_INDEX[t.effect]]
            if not t.key:
                if t.op == api.OP_EXISTS:
                    for e in effects:
                        tol_all[e] = True
                continue
            if t.op == api.OP_EXISTS:
                ids = self.taint_vocab.ids_for_key(t.key)
            else:
                i = self.taint_vocab.get((t.key, t.value))
                ids = [i] if i >= 0 else []
            for e in effects:
                for i in ids:
                    vb.set_bit(bits[e], i)
        return bits, tol_all

    def _encode_ports(self, ports: Sequence[Tuple[str, str, int]]) -> np.ndarray:
        """Intern (protocol, port) claims.  Host-IP specificity is folded to
        the wildcard (conservative: two pods claiming the same port on
        *different* specific IPs are treated as conflicting; the reference's
        exact rule is nodeports/node_ports.go:130-150).  Exact-IP support
        rides the host-side fallback once needed."""
        bits = np.zeros(self.limits.port_words, dtype=np.uint32)
        for proto, _ip, port in ports:
            vb.set_bit(bits, self.port_vocab.intern((proto, port)))
        return bits

    # -- build -------------------------------------------------------------

    def build(
        self,
        nodes: Sequence[api.Node],
        pending_pods: Sequence[api.Pod],
        bound_pods: Sequence[api.Pod] = (),
        num_nodes_hint: int = 0,
        num_pods_hint: int = 0,
    ) -> Tuple[Snapshot, SnapshotMeta]:
        lim = self.limits

        # Interning order matters: node strings first, so pod-side
        # Exists/NotIn expansions and toleration expansions see every pair
        # present in the cluster.
        self._intern_node_strings(nodes)
        for p in bound_pods:
            self._resource_vector(p.resource_requests(), 0, grow=True)
        for p in pending_pods:
            self._resource_vector(p.resource_requests(), 0, grow=True)

        r = len(self.resource_names)
        n = vb.pad_dim(max(len(nodes), num_nodes_hint), lim.min_nodes)
        p_dim = vb.pad_dim(max(len(pending_pods), num_pods_hint), lim.min_pods)

        index_by_name = {nd.meta.name: i for i, nd in enumerate(nodes)}
        cluster = self._build_cluster(nodes, bound_pods, n, r, index_by_name)
        pods, sel, pref, sel_index = self._build_pods(pending_pods, p_dim, r)
        spread, terms = self._build_constraints(
            pending_pods, bound_pods, index_by_name, sel_index, n, p_dim
        )
        meta = SnapshotMeta(
            num_nodes=len(nodes),
            num_pods=len(pending_pods),
            node_names=[nd.meta.name for nd in nodes],
            resource_names=self.resource_names,
            limits=lim,
            topo_z=vb.pad_dim(
                max([len(v) for v in self.topo_vocabs.values()] or [1]), 1
            ),
        )
        return Snapshot(cluster, pods, sel, pref, spread, terms), meta

    def _build_cluster(
        self,
        nodes: Sequence[api.Node],
        bound_pods: Sequence[api.Pod],
        n: int,
        r: int,
        index_by_name: Dict[str, int],
    ) -> ClusterTensors:
        lim = self.limits
        alloc = np.zeros((n, r), dtype=np.float32)
        requested = np.zeros((n, r), dtype=np.float32)
        nonzero = np.zeros((n, r), dtype=np.float32)
        valid = np.zeros(n, dtype=bool)
        name_id = np.full(n, -1, dtype=np.int32)
        label_bits = np.zeros((n, lim.label_words), dtype=np.uint32)
        taint_bits = np.zeros((3, n, lim.taint_words), dtype=np.uint32)
        port_bits = np.zeros((n, lim.port_words), dtype=np.uint32)
        topo_ids = np.full((n, len(lim.topology_keys)), -1, dtype=np.int32)

        for i, node in enumerate(nodes):
            valid[i] = True
            name_id[i] = self.name_vocab.get(node.meta.name)
            alloc[i] = self._resource_vector(node.status.allocatable, r, grow=False)
            for k, v in node.meta.labels.items():
                if k in self.topo_vocabs:
                    continue
                vb.set_bit(label_bits[i], self.label_vocab.get((k, v)))
            for t in node.effective_taints():
                vb.set_bit(taint_bits[EFFECT_INDEX[t.effect], i], self.taint_vocab.get((t.key, t.value)))
            for j, key in enumerate(lim.topology_keys):
                val = node.meta.labels.get(key)
                if val is not None:
                    topo_ids[i, j] = self.topo_vocabs[key].get(val)

        for pod in bound_pods:
            i = index_by_name.get(pod.spec.node_name)
            if i is None:
                continue
            req = self._resource_vector(pod.resource_requests(), r, grow=False)
            req[RESOURCE_PODS] = 1.0
            requested[i] += req
            nz = req.copy()
            nz_cpu, nz_mem = pod.nonzero_requests()
            nz[RESOURCE_CPU] = nz_cpu
            nz[RESOURCE_MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
            nonzero[i] += nz
            port_bits[i] |= self._encode_ports(pod.host_ports())

        return ClusterTensors(
            allocatable=alloc,
            requested=requested,
            nonzero_requested=nonzero,
            node_valid=valid,
            name_id=name_id,
            label_bits=label_bits,
            taint_bits=taint_bits,
            port_bits=port_bits,
            topo_ids=topo_ids,
        )

    def _build_pods(
        self, pods: Sequence[api.Pod], p_dim: int, r: int
    ) -> Tuple[PodBatch, SelectorTable, PreferredTable, Dict[tuple, int]]:
        lim = self.limits
        t_cap, e_cap, k_cap, mt = (
            lim.max_terms, lim.max_exprs, lim.max_ids_per_expr, lim.max_preferred,
        )

        req = np.zeros((p_dim, r), dtype=np.float32)
        nonzero = np.zeros((p_dim, r), dtype=np.float32)
        valid = np.zeros(p_dim, dtype=bool)
        name_id = np.full(p_dim, -1, dtype=np.int32)
        sel_idx = np.full(p_dim, -1, dtype=np.int32)
        tol_bits = np.zeros((3, p_dim, lim.taint_words), dtype=np.uint32)
        tol_all = np.zeros((3, p_dim), dtype=bool)
        port_bits = np.zeros((p_dim, lim.port_words), dtype=np.uint32)
        pref_idx = np.full((p_dim, mt), -1, dtype=np.int32)
        pref_weight = np.zeros((p_dim, mt), dtype=np.float32)

        # Dedup tables keyed by canonical signatures.
        sel_rows: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        sel_index: Dict[tuple, int] = {}
        pref_rows: List[Tuple[np.ndarray, np.ndarray]] = []
        pref_index: Dict[tuple, int] = {}

        for i, pod in enumerate(pods):
            valid[i] = True
            rv = self._resource_vector(pod.resource_requests(), r, grow=False)
            rv[RESOURCE_PODS] = 1.0
            req[i] = rv
            nz = rv.copy()
            nz_cpu, nz_mem = pod.nonzero_requests()
            nz[RESOURCE_CPU] = nz_cpu
            nz[RESOURCE_MEMORY] = nz_mem / DEVICE_UNIT_DIVISOR[api.MEMORY]
            nonzero[i] = nz

            if pod.spec.node_name:
                nid = self.name_vocab.get(pod.spec.node_name)
                name_id[i] = nid if nid >= 0 else -2

            selector = pod.required_node_selector()
            if selector is not None:
                sig = _selector_signature(selector)
                idx = sel_index.get(sig)
                if idx is None:
                    idx = len(sel_rows)
                    sel_index[sig] = idx
                    sel_rows.append(self._encode_selector(selector, t_cap, e_cap, k_cap))
                sel_idx[i] = idx

            bits, tall = self._encode_tolerations(pod.spec.tolerations)
            tol_bits[:, i, :] = bits
            tol_all[:, i] = tall
            port_bits[i] = self._encode_ports(pod.host_ports())

            preferred = pod.preferred_node_affinity()
            if len(preferred) > mt:
                raise OverflowError(
                    f"{len(preferred)} preferred terms exceed max_preferred={mt}"
                )
            for j, pt in enumerate(preferred):
                sig = _term_signature(pt.preference)
                idx = pref_index.get(sig)
                if idx is None:
                    idx = len(pref_rows)
                    pref_index[sig] = idx
                    pref_rows.append(
                        self._encode_term(pt.preference.match_expressions, e_cap, k_cap)
                    )
                pref_idx[i, j] = idx
                pref_weight[i, j] = float(pt.weight)

        s_dim = vb.pad_dim(len(sel_rows), 1)
        sel = SelectorTable(
            expr_ids=np.full((s_dim, t_cap, e_cap, k_cap), -1, dtype=np.int32),
            expr_op=np.zeros((s_dim, t_cap, e_cap), dtype=np.int32),
            expr_slot=np.full((s_dim, t_cap, e_cap), DOMAIN_LABELS, dtype=np.int32),
            term_valid=np.zeros((s_dim, t_cap), dtype=bool),
        )
        for s, (ids, ops, slots, tv) in enumerate(sel_rows):
            sel.expr_ids[s] = ids
            sel.expr_op[s] = ops
            sel.expr_slot[s] = slots
            sel.term_valid[s] = tv

        f_dim = vb.pad_dim(len(pref_rows), 1)
        pref = PreferredTable(
            expr_ids=np.full((f_dim, e_cap, k_cap), -1, dtype=np.int32),
            expr_op=np.zeros((f_dim, e_cap), dtype=np.int32),
            expr_slot=np.full((f_dim, e_cap), DOMAIN_LABELS, dtype=np.int32),
            valid=np.zeros(f_dim, dtype=bool),
        )
        for f, (ids, ops, slots) in enumerate(pref_rows):
            pref.expr_ids[f] = ids
            pref.expr_op[f] = ops
            pref.expr_slot[f] = slots
            pref.valid[f] = True

        batch = PodBatch(
            valid=valid,
            req=req,
            nonzero_req=nonzero,
            name_id=name_id,
            sel_idx=sel_idx,
            tol_bits=tol_bits,
            tol_all=tol_all,
            port_bits=port_bits,
            pref_idx=pref_idx,
            pref_weight=pref_weight,
        )
        return batch, sel, pref, sel_index

    def _topo_slot(self, key: str) -> int:
        try:
            return self.limits.topology_keys.index(key)
        except ValueError:
            raise OverflowError(
                f"topology key {key!r} is not tracked; add it to "
                "SnapshotLimits.topology_keys"
            ) from None

    def _build_constraints(
        self,
        pods: Sequence[api.Pod],
        bound_pods: Sequence[api.Pod],
        index_by_name: Dict[str, int],
        sel_index: Dict[tuple, int],
        n: int,
        p_dim: int,
    ) -> Tuple[SpreadTable, TermTable]:
        lim = self.limits
        tk = len(lim.topology_keys)
        mc, ma = lim.max_spread_per_pod, lim.max_pod_terms
        bound_by_node = [
            (p, index_by_name[p.spec.node_name])
            for p in bound_pods
            if p.spec.node_name in index_by_name
        ]

        # ---- topology spread constraints --------------------------------
        # A constraint instance is owner-scoped: eligibility honours the
        # owner's node selector/affinity and requires every topology key of
        # *all* the owner's constraints (filtering.go PreFilter).
        spread_rows: List[tuple] = []  # (api constraint, owner_ns, owner_sel, keys)
        spread_index: Dict[tuple, int] = {}
        pod_spread_idx = np.full((p_dim, mc), -1, dtype=np.int32)
        for i, pod in enumerate(pods):
            cons = pod.spec.topology_spread_constraints
            if not cons:
                continue
            if len(cons) > mc:
                raise OverflowError(
                    f"{len(cons)} spread constraints exceed max_spread_per_pod={mc}"
                )
            owner_sel = pod.required_node_selector()
            owner_sel_row = (
                sel_index[_selector_signature(owner_sel)] if owner_sel else -1
            )
            keys = tuple(sorted({c.topology_key for c in cons}))
            for j, c in enumerate(cons):
                sig = (
                    c.topology_key,
                    c.max_skew,
                    c.when_unsatisfiable,
                    _label_selector_signature(c.label_selector),
                    pod.meta.namespace,
                    owner_sel_row,
                    keys,
                )
                idx = spread_index.get(sig)
                if idx is None:
                    idx = len(spread_rows)
                    spread_index[sig] = idx
                    spread_rows.append((c, pod.meta.namespace, owner_sel_row, keys))
                pod_spread_idx[i, j] = idx

        c_dim = vb.pad_dim(len(spread_rows), 1)
        spread = SpreadTable(
            valid=np.zeros(c_dim, dtype=bool),
            slot=np.zeros(c_dim, dtype=np.int32),
            max_skew=np.ones(c_dim, dtype=np.float32),
            hard=np.zeros(c_dim, dtype=bool),
            owner_sel_idx=np.full(c_dim, -1, dtype=np.int32),
            owner_keys=np.zeros((c_dim, tk), dtype=bool),
            node_matches=np.zeros((c_dim, n), dtype=np.float32),
            pod_matches=np.zeros((p_dim, c_dim), dtype=bool),
            pod_idx=pod_spread_idx,
        )
        for ci, (c, owner_ns, owner_sel_row, keys) in enumerate(spread_rows):
            spread.valid[ci] = True
            spread.slot[ci] = self._topo_slot(c.topology_key)
            spread.max_skew[ci] = float(c.max_skew)
            spread.hard[ci] = c.when_unsatisfiable == "DoNotSchedule"
            spread.owner_sel_idx[ci] = owner_sel_row
            for k in keys:
                spread.owner_keys[ci, self._topo_slot(k)] = True
            sel = c.label_selector or api.LabelSelector()
            for q, ni in bound_by_node:
                if q.meta.namespace == owner_ns and sel.matches(q.meta.labels):
                    spread.node_matches[ci, ni] += 1.0
            for i, pod in enumerate(pods):
                spread.pod_matches[i, ci] = (
                    pod.meta.namespace == owner_ns and sel.matches(pod.meta.labels)
                )

        # ---- inter-pod (anti-)affinity terms ----------------------------
        term_rows: List[Tuple[api.PodAffinityTerm, Tuple[str, ...]]] = []
        term_index: Dict[tuple, int] = {}

        def intern_term(term: api.PodAffinityTerm, owner_ns: str) -> int:
            namespaces = tuple(sorted(term.namespaces or [owner_ns]))
            sig = (
                term.topology_key,
                _label_selector_signature(term.label_selector),
                namespaces,
            )
            idx = term_index.get(sig)
            if idx is None:
                idx = len(term_rows)
                term_index[sig] = idx
                term_rows.append((term, namespaces))
            return idx

        def pod_terms(pod: api.Pod) -> Tuple[List[api.PodAffinityTerm], List[api.PodAffinityTerm]]:
            aff = pod.spec.affinity
            a = aff.pod_affinity.required if aff and aff.pod_affinity else []
            b = aff.pod_anti_affinity.required if aff and aff.pod_anti_affinity else []
            return list(a), list(b)

        aff_idx = np.full((p_dim, ma), -1, dtype=np.int32)
        anti_idx = np.full((p_dim, ma), -1, dtype=np.int32)
        for i, pod in enumerate(pods):
            aff_terms, anti_terms = pod_terms(pod)
            if len(aff_terms) > ma or len(anti_terms) > ma:
                raise OverflowError(
                    f"pod has {len(aff_terms)}/{len(anti_terms)} (anti-)affinity "
                    f"terms, exceeding max_pod_terms={ma}"
                )
            for j, t in enumerate(aff_terms):
                aff_idx[i, j] = intern_term(t, pod.meta.namespace)
            for j, t in enumerate(anti_terms):
                anti_idx[i, j] = intern_term(t, pod.meta.namespace)
        # Bound pods' anti-affinity terms participate in the
        # existing-pods-anti-affinity direction even if no pending pod
        # carries them.
        bound_anti: List[Tuple[int, int]] = []  # (term row, node index)
        for q, ni in bound_by_node:
            _, anti_terms = pod_terms(q)
            for t in anti_terms:
                bound_anti.append((intern_term(t, q.meta.namespace), ni))

        t_dim = vb.pad_dim(len(term_rows), 1)
        terms = TermTable(
            valid=np.zeros(t_dim, dtype=bool),
            slot=np.zeros(t_dim, dtype=np.int32),
            node_matches=np.zeros((t_dim, n), dtype=np.float32),
            node_owners=np.zeros((t_dim, n), dtype=np.float32),
            matches_incoming=np.zeros((p_dim, t_dim), dtype=bool),
            aff_idx=aff_idx,
            anti_idx=anti_idx,
            self_match_all=np.zeros(p_dim, dtype=bool),
        )

        def term_matches(term: api.PodAffinityTerm, namespaces, pod: api.Pod) -> bool:
            if pod.meta.namespace not in namespaces:
                return False
            sel = term.label_selector or api.LabelSelector()
            return sel.matches(pod.meta.labels)

        for ti, (term, namespaces) in enumerate(term_rows):
            terms.valid[ti] = True
            terms.slot[ti] = self._topo_slot(term.topology_key)
            for q, ni in bound_by_node:
                if term_matches(term, namespaces, q):
                    terms.node_matches[ti, ni] += 1.0
            for i, pod in enumerate(pods):
                terms.matches_incoming[i, ti] = term_matches(term, namespaces, pod)
        for ti, ni in bound_anti:
            terms.node_owners[ti, ni] += 1.0
        for i, pod in enumerate(pods):
            aff_terms, _ = pod_terms(pod)
            terms.self_match_all[i] = bool(aff_terms) and all(
                term_matches(t, tuple(t.namespaces or [pod.meta.namespace]), pod)
                for t in aff_terms
            )

        return spread, terms

    def _encode_selector(
        self, selector: api.NodeSelector, t_cap: int, e_cap: int, k_cap: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        if len(selector.terms) > t_cap:
            raise OverflowError(
                f"{len(selector.terms)} selector terms exceed max_terms={t_cap}"
            )
        ids = np.full((t_cap, e_cap, k_cap), -1, dtype=np.int32)
        ops = np.zeros((t_cap, e_cap), dtype=np.int32)
        slots = np.full((t_cap, e_cap), DOMAIN_LABELS, dtype=np.int32)
        term_valid = np.zeros(t_cap, dtype=bool)
        for t, term in enumerate(selector.terms):
            term_valid[t] = True
            ids[t], ops[t], slots[t] = self._encode_term(term.match_expressions, e_cap, k_cap)
        return ids, ops, slots, term_valid


def _label_selector_signature(sel: Optional[api.LabelSelector]) -> tuple:
    if sel is None:
        return ()
    return tuple(
        (r.key, r.op, tuple(sorted(r.values))) for r in sel.requirements()
    )


def _term_signature(term: api.NodeSelectorTerm) -> tuple:
    return tuple(
        (r.key, r.op, tuple(sorted(r.values))) for r in term.match_expressions
    )


def _selector_signature(sel: api.NodeSelector) -> tuple:
    return tuple(_term_signature(t) for t in sel.terms)
