"""Score kernels — the Score extension point as one weighted-sum pass.

Replaces the reference's parallel per-node score + NormalizeScore + weight
application (pkg/scheduler/framework/runtime/framework.go:1090-1180) with
closed-form vector math over the node axis.  Implemented scorers:

  NodeResourcesFit/LeastAllocated   least_allocated.go:30-61
  NodeResourcesBalancedAllocation   balanced_allocation.go:138-176
  NodeResourcesMostAllocated        most_allocated.go:30-53 (opt-in strategy)
  NodeAffinity (preferred terms)    nodeaffinity/node_affinity.go Score
  TaintToleration (PreferNoSchedule) tainttoleration/taint_toleration.go Score

Go-side scorers run in int64 with truncating division; these kernels mimic
that with float32 + floor, which is exact for the quantities the schema
carries (see schema.DEVICE_UNIT_DIVISOR).  Normalization follows
helper.DefaultNormalizeScore (plugins/helper/normalize_score.go): scale to
[0,100] by the max over *feasible* nodes, optionally reversed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

import jax
import jax.numpy as jnp

from .filters import PodView, preferred_match
from .schema import RESOURCE_CPU, RESOURCE_MEMORY, ClusterTensors, PreferredTable

MAX_NODE_SCORE = 100.0
_PREFER_NO_SCHEDULE = 1  # taint-effect row


@dataclass(frozen=True)
class ScoreConfig:
    """Plugin weights (reference defaults:
    apis/config/v1/default_plugins.go:38-50) and the resource sets the
    allocation scorers consider (default cpu+memory, weight 1 each —
    apis/config/v1/defaults.go defaultResourceSpec)."""

    fit_weight: float = 1.0              # NodeResourcesFit
    balanced_weight: float = 1.0         # NodeResourcesBalancedAllocation
    node_affinity_weight: float = 2.0    # NodeAffinity
    taint_weight: float = 3.0            # TaintToleration
    spread_weight: float = 2.0           # PodTopologySpread (ops.topology)
    # (resource_index, weight) pairs for Least/MostAllocated
    fit_resources: Tuple[Tuple[int, float], ...] = (
        (RESOURCE_CPU, 1.0),
        (RESOURCE_MEMORY, 1.0),
    )
    # resource indices for BalancedAllocation
    balanced_resources: Tuple[int, ...] = (RESOURCE_CPU, RESOURCE_MEMORY)
    fit_strategy: str = "LeastAllocated"  # or MostAllocated | RequestedToCapacityRatio
    interpod_weight: float = 2.0         # InterPodAffinity (preferred terms)
    image_weight: float = 1.0            # ImageLocality
    # RequestedToCapacityRatio shape: (utilization%, score) points,
    # piecewise-linear (requested_to_capacity_ratio.go buildBrokenLinear).
    # The default shape is the bin-packing example from the reference
    # docs: score rises with utilization.
    rtcr_shape: Tuple[Tuple[float, float], ...] = ((0.0, 0.0), (100.0, 10.0))


DEFAULT_SCORE_CONFIG = ScoreConfig()


def _floor(x: jnp.ndarray) -> jnp.ndarray:
    """Go int64 division truncates; operands here are non-negative."""
    return jnp.floor(x)


def least_allocated(
    cluster: ClusterTensors, pod: PodView, cfg: ScoreConfig
) -> jnp.ndarray:
    """score = sum_r w_r * floor((cap - req) * 100 / cap) / sum w, skipping
    resources a node doesn't expose (allocable==0 skips the weight too —
    least_allocated.go:34-37).  Uses NonZeroRequested."""
    req = cluster.nonzero_requested + pod.nonzero_req[None, :]
    cap = cluster.allocatable
    total = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    wsum = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    for idx, weight in cfg.fit_resources:
        c = cap[:, idx]
        q = req[:, idx]
        ok = c > 0
        s = jnp.where(ok & (q <= c), _floor((c - q) * MAX_NODE_SCORE / jnp.maximum(c, 1.0)), 0.0)
        total = total + weight * s * ok
        wsum = wsum + weight * ok
    return jnp.where(wsum > 0, _floor(total / jnp.maximum(wsum, 1.0)), 0.0)


def most_allocated(
    cluster: ClusterTensors, pod: PodView, cfg: ScoreConfig
) -> jnp.ndarray:
    """score = sum_r w_r * floor(req * 100 / cap) / sum w (most_allocated.go:30-53)."""
    req = cluster.nonzero_requested + pod.nonzero_req[None, :]
    cap = cluster.allocatable
    total = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    wsum = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    for idx, weight in cfg.fit_resources:
        c = cap[:, idx]
        q = req[:, idx]
        ok = c > 0
        s = jnp.where(ok & (q <= c), _floor(q * MAX_NODE_SCORE / jnp.maximum(c, 1.0)), 0.0)
        total = total + weight * s * ok
        wsum = wsum + weight * ok
    return jnp.where(wsum > 0, _floor(total / jnp.maximum(wsum, 1.0)), 0.0)


def requested_to_capacity_ratio(
    cluster: ClusterTensors, pod: PodView, cfg: ScoreConfig
) -> jnp.ndarray:
    """Piecewise-linear score of utilization percent per resource,
    weight-averaged (noderesources/requested_to_capacity_ratio.go
    buildRequestedToCapacityRatioScorerFunction): the shape maps
    utilization (0..100) to a 0..10 score, rescaled here to 0..100 like
    the other strategies (MaxCustomPriorityScore=10 is scaled by
    MaxNodeScore/10 in the reference runtime)."""
    req = cluster.nonzero_requested + pod.nonzero_req[None, :]
    cap = cluster.allocatable
    xs = jnp.asarray([p[0] for p in cfg.rtcr_shape], jnp.float32)
    ys = jnp.asarray([p[1] for p in cfg.rtcr_shape], jnp.float32)
    total = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    wsum = jnp.zeros(cap.shape[0], dtype=jnp.float32)
    for idx, weight in cfg.fit_resources:
        c = cap[:, idx]
        q = req[:, idx]
        ok = c > 0
        util = jnp.clip(q * 100.0 / jnp.maximum(c, 1.0), 0.0, 100.0)
        s = jnp.interp(util, xs, ys) * (MAX_NODE_SCORE / 10.0)
        total = total + weight * jnp.where(ok & (q <= c), _floor(s), 0.0)
        wsum = wsum + weight * ok
    return jnp.where(wsum > 0, _floor(total / jnp.maximum(wsum, 1.0)), 0.0)


def balanced_allocation(
    cluster: ClusterTensors, pod: PodView, cfg: ScoreConfig
) -> jnp.ndarray:
    """score = floor((1 - std(fractions)) * 100) with fractions clamped to 1,
    over resources with allocable > 0.  The reference's two-resource
    |f1-f2|/2 shortcut equals the general population-std formula, so one
    formula serves all arities (balanced_allocation.go:138-176).  Uses
    actual Requested (useRequested=true, balanced_allocation.go:130)."""
    req = cluster.requested + pod.req[None, :]
    cap = cluster.allocatable
    fracs = []
    valids = []
    for idx in cfg.balanced_resources:
        c = cap[:, idx]
        ok = c > 0
        f = jnp.minimum(req[:, idx] / jnp.maximum(c, 1.0), 1.0)
        fracs.append(jnp.where(ok, f, 0.0))
        valids.append(ok)
    f = jnp.stack(fracs, axis=-1)          # [N, B]
    v = jnp.stack(valids, axis=-1)         # [N, B]
    count = v.sum(axis=-1)
    mean = f.sum(axis=-1) / jnp.maximum(count, 1)
    var = (jnp.where(v, (f - mean[:, None]) ** 2, 0.0)).sum(axis=-1) / jnp.maximum(count, 1)
    std = jnp.sqrt(var)
    return _floor((1.0 - std) * MAX_NODE_SCORE)


def node_affinity_raw(pod: PodView, pref_mask: jnp.ndarray) -> jnp.ndarray:
    """Sum of weights of matching preferred terms (nodeaffinity Score).
    pref_mask: bool[F, N] from filters.preferred_match."""
    f = pref_mask.shape[0]
    idx = jnp.clip(pod.pref_idx, 0, f - 1)               # [MT]
    hit = pref_mask[idx]                                 # [MT, N]
    w = jnp.where(pod.pref_idx >= 0, pod.pref_weight, 0.0)
    return (w[:, None] * hit).sum(axis=0)                # [N]


def taint_toleration_raw(cluster: ClusterTensors, pod: PodView) -> jnp.ndarray:
    """Count of untolerated PreferNoSchedule taints per node
    (tainttoleration countIntolerableTaintsPreferNoSchedule)."""
    untol = cluster.taint_bits[_PREFER_NO_SCHEDULE] & ~pod.tol_bits[_PREFER_NO_SCHEDULE][None, :]
    counts = jax.lax.population_count(untol).sum(axis=-1).astype(jnp.float32)
    return jnp.where(pod.tol_all[_PREFER_NO_SCHEDULE], 0.0, counts)


def normalize(
    raw: jnp.ndarray,
    feasible: jnp.ndarray,
    reverse: bool = False,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """helper.DefaultNormalizeScore: scale by the max over feasible nodes to
    [0,100] with truncating division; if the max is 0, scores become 0
    (or 100 when reversed).  Under shard_map the max must span every node
    shard — pass the mesh axis_name and it is pmax-reduced."""
    m = jnp.max(jnp.where(feasible, raw, 0.0))
    if axis_name is not None:
        m = jax.lax.pmax(m, axis_name)
    scaled = _floor(MAX_NODE_SCORE * raw / jnp.maximum(m, 1e-30))
    out = jnp.where(m > 0, scaled, 0.0)
    if reverse:
        out = jnp.where(m > 0, MAX_NODE_SCORE - out, MAX_NODE_SCORE)
    return out


def score_from_raw(
    cluster: ClusterTensors,
    pod: PodView,
    feasible: jnp.ndarray,
    aff_raw: jnp.ndarray,
    taint_raw: jnp.ndarray,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    axis_name: str | None = None,
    spread_score: jnp.ndarray | None = None,
    extra: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Weighted plugin-score sum with precomputed *raw* static scores.

    aff_raw/taint_raw are the placement-independent per-node raw scores
    (node_affinity_raw / taint_toleration_raw), hoisted out of the
    solver's scan per pod class; normalization stays per-step because its
    maxima range over the pod's current feasible set.  fit/balanced are
    computed here from the carried requested state.  `extra` is an
    already-normalized, already-weighted additional score row (the
    hoisted preferred-interpod contribution)."""
    fit, bal = resource_score_parts(cluster, pod, cfg)
    return combine_scores(
        fit, bal, aff_raw, taint_raw, feasible, cfg,
        axis_name=axis_name, spread_score=spread_score, extra=extra,
    )


def resource_score_parts(
    cluster: ClusterTensors, pod: PodView, cfg: ScoreConfig
) -> tuple:
    """(fit, bal) — the requested-state-dependent score rows.  These
    depend only on the pod's SPEC (requests), so solvers with a
    factorized class axis compute them once per spec class and combine
    per joint class (combine_scores)."""
    if cfg.fit_strategy == "MostAllocated":
        fit = most_allocated(cluster, pod, cfg)
    elif cfg.fit_strategy == "RequestedToCapacityRatio":
        fit = requested_to_capacity_ratio(cluster, pod, cfg)
    else:
        fit = least_allocated(cluster, pod, cfg)
    return fit, balanced_allocation(cluster, pod, cfg)


def combine_scores(
    fit: jnp.ndarray,
    bal: jnp.ndarray,
    aff_raw: jnp.ndarray,
    taint_raw: jnp.ndarray,
    feasible: jnp.ndarray,
    cfg: ScoreConfig,
    axis_name: str | None = None,
    spread_score: jnp.ndarray | None = None,
    extra: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Normalize + weight-sum precomputed score rows over a feasible
    set.  Normalization is per-(pod, feasible-set) — the RunScorePlugins
    NormalizeScore pass (runtime/framework.go:1147) — so it stays in the
    per-class combine even when the raw rows are hoisted."""
    aff = normalize(aff_raw, feasible, axis_name=axis_name)
    taint = normalize(taint_raw, feasible, reverse=True, axis_name=axis_name)
    total = (
        cfg.fit_weight * fit
        + cfg.balanced_weight * bal
        + cfg.node_affinity_weight * aff
        + cfg.taint_weight * taint
    )
    if spread_score is not None:
        total = total + cfg.spread_weight * spread_score
    if extra is not None:
        total = total + extra
    return jnp.where(feasible, total, -1.0)


def score_for_pod(
    cluster: ClusterTensors,
    pod: PodView,
    feasible: jnp.ndarray,
    pref_mask: jnp.ndarray,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    axis_name: str | None = None,
    spread_score: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Weighted plugin-score sum for one pod over all nodes: f32[N].
    Infeasible nodes score -1 (callers mask again before argmax anyway).
    axis_name: mesh axis to reduce normalization maxima over when the node
    axis is sharded.  spread_score: pre-normalized PodTopologySpread score
    (ops.topology.spread_score), weighted in here."""
    return score_from_raw(
        cluster,
        pod,
        feasible,
        node_affinity_raw(pod, pref_mask),
        taint_toleration_raw(cluster, pod),
        cfg,
        axis_name=axis_name,
        spread_score=spread_score,
    )


_IMG_MB = 1024.0 * 1024.0
_IMG_MIN = 23.0 * _IMG_MB              # minThreshold (image_locality.go)
_IMG_MAX_PER_CONTAINER = 1000.0 * _IMG_MB


def image_locality_score(cluster, images, p, axis_name=None) -> jnp.ndarray:
    """ImageLocality Score, 0..100 per node
    (imagelocality/image_locality.go): sum of the pod's image sizes
    already present on the node, each scaled by its cluster spread ratio
    (nodes-having-it / valid nodes), clamped into
    [23MB, 1000MB x containers] and linearly mapped to the score range.
    No NormalizeScore pass — the reference plugin returns the scaled
    value directly.  Under shard_map the spread ratio must span shards:
    pass axis_name and the per-image node counts psum."""
    ids = images.pod_ids[p]                                  # [MI]
    active = ids >= 0
    idc = jnp.clip(ids, 0, images.sizes.shape[0] - 1)
    word = idc // 32
    bit = idc % 32
    present = ((cluster.image_bits[:, word] >> bit) & 1).astype(jnp.float32)
    n_valid = jnp.maximum(cluster.node_valid.sum(), 1).astype(jnp.float32)
    counts = (present * cluster.node_valid[:, None]).sum(axis=0)  # [MI]
    if axis_name is not None:
        n_valid = jnp.maximum(jax.lax.psum(cluster.node_valid.sum(), axis_name), 1).astype(jnp.float32)
        counts = jax.lax.psum(counts, axis_name)
    scaled = images.sizes[idc] * counts / n_valid                 # [MI]
    raw = (present * (scaled * active)[None, :]).sum(axis=-1)     # [N]
    # the threshold scales with the pod's TOTAL image-bearing container
    # count (incl. init and cluster-unknown images) — scaling by known
    # images only would inflate scores ~2x vs the reference
    n_containers = jnp.maximum(images.n_containers[p], 1.0)
    lo = _IMG_MIN
    hi = _IMG_MAX_PER_CONTAINER * n_containers
    score = _floor(MAX_NODE_SCORE * (jnp.clip(raw, lo, hi) - lo) / (hi - lo))
    return jnp.where(active.any(), score, 0.0)


def static_extra(
    cluster,
    prefpod,
    images,
    features,
    cfg: ScoreConfig,
    rep,
    feasible,
    pp_state=None,
    axis_name=None,
) -> jnp.ndarray:
    """The hoisted per-class static score extras (preferred inter-pod
    affinity + ImageLocality), shared by the greedy/auction hoists and
    evaluate_single so the families can't drift apart.  `feasible` is
    the normalization set; `pp_state` the prep_pref_pod output (required
    when features.interpod_pref).  axis_name: mesh axis when the node
    axis is sharded — normalization extrema and image spread ratios span
    shards."""
    from .interpod import pref_pod_raw

    total = jnp.zeros(cluster.allocatable.shape[0], jnp.float32)
    if features.interpod_pref:
        raw = pref_pod_raw(pp_state, prefpod, rep)
        total = total + cfg.interpod_weight * normalize_minmax(
            raw, feasible, axis_name=axis_name
        )
    if features.images:
        total = total + cfg.image_weight * image_locality_score(
            cluster, images, rep, axis_name=axis_name
        )
    return total


def normalize_minmax(
    raw: jnp.ndarray,
    feasible: jnp.ndarray,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """interpodaffinity/scoring.go NormalizeScore: scale to [0,100] by
    (raw - min) / (max - min) over feasible nodes — unlike the default
    normalizer this handles NEGATIVE raws (anti-affinity weights)."""
    big = jnp.float32(1e30)
    mx = jnp.max(jnp.where(feasible, raw, -big))
    mn = jnp.min(jnp.where(feasible, raw, big))
    if axis_name is not None:
        mx = jax.lax.pmax(mx, axis_name)
        mn = jax.lax.pmin(mn, axis_name)
    span = mx - mn
    out = jnp.where(
        span > 0, _floor(MAX_NODE_SCORE * (raw - mn) / jnp.maximum(span, 1e-30)), 0.0
    )
    return jnp.where(feasible, out, 0.0)
