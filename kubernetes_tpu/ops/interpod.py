"""InterPodAffinity as per-node bitsets.

The reference's PreFilter builds topology-pair count maps and Filter does
three boolean checks per node (interpodaffinity/filtering.go:306-366):

  1. no existing pod's required anti-affinity term matches the incoming
     pod in the node's topology
  2. none of the incoming pod's anti-affinity terms match an existing pod
     in the node's topology
  3. every affinity term has a matching existing pod in the node's
     topology — with the first-pod-of-a-group escape: all terms globally
     unmatched + the pod matches its own terms + node has the keys.

Every check consumes only count *presence* (> 0), and presence is
monotone during a batch solve (placements never remove pods), so the
state is three bitsets over the term axis instead of [T, Z] count tensors:

  present_bits[N, W] : term t has a matching pod in node n's topology
  blocked_bits[N, W] : a pod carrying anti-term t sits in n's topology
  global_any[W]      : term t has a matching pod anywhere

and the per-step work is O(N * W) word ops — no gathers or scatters in
the scan.  Updates exploit that terms share at most TK topology keys:
one node-mask per key, OR-ed with per-(slot, pod) precomputed bit rows.

All selector/namespace string matching was precomputed host-side into
schema.TermTable matrices — the O(pods x nodes) pairwise term the north
star turns into bit algebra.

Not yet modelled: namespaceSelector on terms, matchLabelKeys, and the
preferred (scoring) terms — required terms only.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .schema import ClusterTensors, TermTable


class TermState(NamedTuple):
    present_bits: jnp.ndarray  # u32[N, W]
    blocked_bits: jnp.ndarray  # u32[N, W]
    global_any: jnp.ndarray    # u32[W]
    # static within a solve:
    key_bits: jnp.ndarray      # u32[N, W] node has term t's topology key
    slot_v: jnp.ndarray        # i32[TK, N] node topo values by slot
    mi_slot_bits: jnp.ndarray  # u32[TK, P, W] matches_incoming split by term slot
    anti_slot_bits: jnp.ndarray  # u32[TK, P, W] own anti terms split by slot
    aff_bits: jnp.ndarray      # u32[P, W] own required affinity terms
    anti_bits: jnp.ndarray     # u32[P, W] own required anti-affinity terms


def _pack_bits_t(mat: jnp.ndarray) -> jnp.ndarray:
    """Pack bool[..., T] -> u32[..., ceil(T/32)] (little-endian bits)."""
    t = mat.shape[-1]
    w = (t + 31) // 32
    pad = w * 32 - t
    if pad:
        mat = jnp.concatenate(
            [mat, jnp.zeros(mat.shape[:-1] + (pad,), dtype=bool)], axis=-1
        )
    grouped = mat.reshape(mat.shape[:-1] + (w, 32)).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (grouped * weights).sum(axis=-1, dtype=jnp.uint32)


def _idx_to_bits(idx: jnp.ndarray, t_dim: int) -> jnp.ndarray:
    """int32[P, MA] term indices (-1 pad) -> bool[P, T] membership."""
    return (jnp.arange(t_dim)[None, None, :] == idx[:, :, None]).any(axis=1)


def _unpack_bits_t(bits: jnp.ndarray, t_dim: int) -> jnp.ndarray:
    """u32[..., W] packed (little-endian per word) -> bool[..., T]."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    expanded = (bits[..., :, None] >> shifts) & jnp.uint32(1)
    flat = expanded.reshape(*bits.shape[:-1], bits.shape[-1] * 32)
    return flat[..., :t_dim].astype(bool)


# coherence: rebuilt-per-solve -- affinity term grids derive from THIS
# snapshot's cluster tensors; a cached copy would score a stale generation
def prep_terms(
    cluster: ClusterTensors,
    terms: TermTable,
    z: int,
    axis_name: str | None = None,
    slots: tuple = (),
    has_bound: bool = True,
) -> TermState:
    """One-time assembly (the PreFilter analogue).  z is the topo-value
    vocab bound, used only for the prep-time count scatter.  Under
    shard_map pass axis_name: global_any must OR across node shards
    (pre-pack — psum on packed bitsets would carry between bits), and
    counts must be psum-reduced so a topology domain spanning shards is
    seen whole.  has_bound=False (FeatureFlags.bound_terms) statically
    elides the count scatter + [T, N] value-space gathers — the tables
    are runtime arrays, so XLA cannot fold them even when zero, and the
    gathers cost ~0.2 s at 32k nodes x 256 terms."""
    t_dim = terms.valid.shape[0]
    v = jnp.take_along_axis(cluster.topo_ids, terms.slot[None, :], axis=1).T  # [T, N]
    vc = jnp.clip(v, 0, z - 1)
    ok = (v >= 0) & cluster.node_valid[None, :] & terms.valid[:, None]

    if has_bound:
        def per_t(vc_row, ok_row, m_row, o_row):
            cm = jnp.zeros(z, jnp.float32).at[vc_row].add(m_row * ok_row)
            co = jnp.zeros(z, jnp.float32).at[vc_row].add(o_row * ok_row)
            return cm, co

        cm, co = jax.vmap(per_t)(vc, ok, terms.node_matches, terms.node_owners)
        if axis_name is not None:
            cm = jax.lax.psum(cm, axis_name)
            co = jax.lax.psum(co, axis_name)
        present = ok & (jnp.take_along_axis(cm, vc, axis=-1) > 0)   # [T, N]
        blocked = ok & (jnp.take_along_axis(co, vc, axis=-1) > 0)   # [T, N]
        global_any = _pack_bits_t((cm.sum(axis=-1) > 0) & terms.valid)
    else:
        shape = (t_dim, cluster.node_valid.shape[0])
        present = jnp.zeros(shape, bool)
        blocked = jnp.zeros(shape, bool)
        global_any = _pack_bits_t(jnp.zeros(t_dim, bool))

    # matches_incoming arrives PACKED (u32 words, schema.TermTable) —
    # slot splitting happens directly in word space.
    valid_words = _pack_bits_t(terms.valid)                      # [W]
    mi_bits = terms.matches_incoming & valid_words[None, :]      # [P, W]
    # Only the topology-key slots some term actually uses get a row in the
    # per-slot bit tables (static from FeatureFlags.term_slots) — real
    # workloads use one or two keys, so the per-step slot loop shrinks
    # from TK to that count.
    used = jnp.asarray(slots or tuple(range(cluster.topo_ids.shape[1])), dtype=jnp.int32)
    slot_onehot = terms.slot[None, :] == used[:, None]           # [U, T]
    slot_words = _pack_bits_t(slot_onehot)                       # [U, W]
    anti_membership = _idx_to_bits(terms.anti_idx, t_dim) & terms.valid[None, :]
    aff_membership = _idx_to_bits(terms.aff_idx, t_dim) & terms.valid[None, :]

    return TermState(
        present_bits=_pack_bits_t(present.T),
        blocked_bits=_pack_bits_t(blocked.T),
        global_any=global_any,
        key_bits=_pack_bits_t(ok.T),
        slot_v=cluster.topo_ids.T[used],
        mi_slot_bits=mi_bits[None, :, :] & slot_words[:, None, :],
        anti_slot_bits=_pack_bits_t(
            anti_membership[None, :, :] & slot_onehot[:, None, :]
        ),
        aff_bits=_pack_bits_t(aff_membership),
        anti_bits=_pack_bits_t(anti_membership),
    )


def interpod_filter(
    state: TermState, terms: TermTable, p: jnp.ndarray
) -> jnp.ndarray:
    """The three checks for pod p over all nodes: bool[N], as bit algebra."""
    mi_all = jnp.zeros_like(state.global_any)
    for s in range(state.mi_slot_bits.shape[0]):
        mi_all = mi_all | state.mi_slot_bits[s, p]

    # 1. existing pods' anti-affinity against the incoming pod
    viol_existing = (state.blocked_bits & mi_all[None, :]).any(axis=-1)

    # 2. incoming pod's anti-affinity against existing pods
    viol_own = (state.present_bits & state.anti_bits[p][None, :]).any(axis=-1)

    # 3. incoming pod's affinity (with the first-pod escape)
    aff = state.aff_bits[p]                                       # [W]
    any_active = (aff != 0).any()
    all_here = ((aff[None, :] & ~state.present_bits) == 0).all(axis=-1)
    keys_ok = ((aff[None, :] & ~state.key_bits) == 0).all(axis=-1)
    none_anywhere = ((aff & state.global_any) == 0).all()
    fallback = none_anywhere & terms.self_match_all[p] & keys_ok
    aff_ok = ~any_active | (all_here & keys_ok) | fallback

    return aff_ok & ~viol_existing & ~viol_own


def interpod_update(
    state: TermState,
    terms: TermTable,
    p: jnp.ndarray,
    topo_at: jnp.ndarray,
    found: jnp.ndarray,
    slots: tuple = (),
) -> TermState:
    """Account a placement: terms the placed pod matches turn present (and
    globally-any) in the placement's topology; its own anti-affinity terms
    turn blocked there.  topo_at = the chosen node's topo_ids row ([TK]);
    the sharded solve psum-broadcasts it from the owning shard.  slots
    must match the tuple prep_terms was built with
    (FeatureFlags.term_slots)."""
    idxs = slots or tuple(range(state.slot_v.shape[0]))
    present = state.present_bits
    blocked = state.blocked_bits
    global_any = state.global_any
    for j, s in enumerate(idxs):
        ta = topo_at[s]
        node_mask = (state.slot_v[j] == ta) & (ta >= 0) & found
        mi_bits = state.mi_slot_bits[j, p]
        anti_bits = state.anti_slot_bits[j, p]
        present = present | jnp.where(node_mask[:, None], mi_bits[None, :], 0)
        blocked = blocked | jnp.where(node_mask[:, None], anti_bits[None, :], 0)
        global_any = global_any | jnp.where((ta >= 0) & found, mi_bits, 0)
    return state._replace(
        present_bits=present, blocked_bits=blocked, global_any=global_any
    )


class PrefPodState(NamedTuple):
    """Domain-summed preferred-term match data (prep_pref_pod)."""

    counts_dom: jnp.ndarray   # f32[U, N] matching bound pods in n's topology
    ownerw_dom: jnp.ndarray   # f32[U, N] Σ signed owner weights in n's topology


def prep_pref_pod(
    cluster: ClusterTensors,
    table,
    z: int,
    axis_name: str | None = None,
    has_bound: bool = True,
) -> PrefPodState:
    """Domain-sum the per-node match counts / owner weights over each
    row's topology value (interpodaffinity/scoring.go PreScore builds the
    same topology-pair score map).  Under shard_map, value-space sums
    psum across node shards.  has_bound=False
    (FeatureFlags.bound_pref) statically folds the zero tables away."""
    if not has_bound:
        shape = (table.valid.shape[0], cluster.node_valid.shape[0])
        return PrefPodState(
            jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)
        )
    v = jnp.take_along_axis(cluster.topo_ids, table.slot[None, :], axis=1).T
    vc = jnp.clip(v, 0, z - 1)
    ok = (v >= 0) & cluster.node_valid[None, :] & table.valid[:, None]

    def per_u(vc_row, ok_row, c_row, w_row):
        cz = jnp.zeros(z, jnp.float32).at[vc_row].add(c_row * ok_row)
        wz = jnp.zeros(z, jnp.float32).at[vc_row].add(w_row * ok_row)
        return cz, wz

    cz, wz = jax.vmap(per_u)(vc, ok, table.node_counts, table.owner_weight)
    if axis_name is not None:
        cz = jax.lax.psum(cz, axis_name)
        wz = jax.lax.psum(wz, axis_name)
    counts_dom = jnp.where(ok, jnp.take_along_axis(cz, vc, axis=-1), 0.0)
    ownerw_dom = jnp.where(ok, jnp.take_along_axis(wz, vc, axis=-1), 0.0)
    return PrefPodState(counts_dom, ownerw_dom)


def pref_pod_raw(state: PrefPodState, table, p: jnp.ndarray) -> jnp.ndarray:
    """Raw preferred-interpod score of pod p over all nodes: f32[N].

    Both directions of scoring.go processExistingPod:
      Σ_j weight(p, j) * |matching existing pods in n's topology|   (own terms)
      Σ_u [p matches u] * Σ owner weights of u in n's topology      (their terms)
    """
    u_dim = state.counts_dom.shape[0]
    idx = jnp.clip(table.pod_idx[p], 0, u_dim - 1)          # [MA]
    w = jnp.where(table.pod_idx[p] >= 0, table.pod_weight[p], 0.0)
    own = (w[:, None] * state.counts_dom[idx]).sum(axis=0)   # [N]
    mi = table.matches_incoming[p].astype(jnp.float32)       # [U]
    theirs = (mi[:, None] * state.ownerw_dom).sum(axis=0)    # [N]
    return own + theirs
