"""Device-resident Filter/Score partials — the O(changes) warm-start
kernels.

Every solve hoists a per-pod-class triple out of its scan
(ops.assign.class_statics): static feasibility (NodeName + taints +
NodeAffinity + bound-port conflicts), the raw preferred-node-affinity
score row, and the raw PreferNoSchedule taint count — three [C, N]
tables recomputed from scratch per batch even though (a) churn batches
re-present the same pod classes over and over (a Deployment's replicas
are one class) and (b) under sustained churn <1% of node rows change
between solves.  At 50k nodes with selector-bearing classes that
re-evaluation IS the dominant per-batch cost: selector matching alone is
S x T x E x K x N element ops.

These kernels keep the triple RESIDENT on device next to the
DeviceClusterMirror (models/mirror.py) and warm-start each solve from
it:

  ClassSpecs      per-slot static pod spec (the placement-independent
                  inputs the triple derives from), resident so dirty
                  ROWS can be re-evaluated for every cached class
                  without the batch's tables;
  PartialsStore   the resident [G, N] triple, one row per cached class
                  signature;
  eval_store      full recompute (first sync / resync discipline);
  refresh_rows    scatter-recompute ONLY the node columns dirtied since
                  the last sync (ClusterState.dirty_rows — includes the
                  rows the previous wave's picks touched);
  insert_slots    full rows for classes first seen this batch;
  gather_statics  the batch-ordered [C, N] view the solver consumes
                  (ops.assign greedy/wavefront `statics=` operand).

Bit-parity with the cold path is BY CONSTRUCTION: `_eval_slot` calls
the very kernels class_statics calls (match_terms,
static_feasible_for_pod, node_affinity_raw, taint_toleration_raw) on
the slot's stored spec, and every function is elementwise over the node
axis, so a column subset evaluated on gathered rows equals the same
columns of a full evaluation.  models/partials.py owns the host-side
cache protocol (signature keying, generation watermarks, the resync /
rollback discipline) and the parity gate.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .filters import PodView, match_terms, static_feasible_for_pod
from .schema import ClusterTensors
from .scores import node_affinity_raw, taint_toleration_raw


class ClassStatics(NamedTuple):
    """The per-class hoisted Filter/Score triple in BATCH class order —
    exactly what ops.assign.class_statics produces, gathered from the
    resident store instead of recomputed (C = padded joint-class dim)."""

    sfeas: jnp.ndarray  # bool[C, N]
    aff: jnp.ndarray    # f32[C, N]
    taint: jnp.ndarray  # f32[C, N]


class ClassSpecs(NamedTuple):
    """Resident per-slot static pod spec: everything the partials triple
    depends on besides the cluster tensors.  G = slot capacity; T/E/K,
    MT, TW, PW follow SnapshotLimits exactly like the batch tables —
    slot rows are byte-copies of the encoder's rows, so re-evaluating a
    slot is re-evaluating its representative pod."""

    valid: jnp.ndarray        # bool[G]
    name_id: jnp.ndarray      # i32[G]
    has_sel: jnp.ndarray      # bool[G]
    sel_ids: jnp.ndarray      # i32[G, T, E, K]
    sel_op: jnp.ndarray       # i32[G, T, E]
    sel_slot: jnp.ndarray     # i32[G, T, E]
    sel_tv: jnp.ndarray       # bool[G, T]
    tol_bits: jnp.ndarray     # u32[3, G, TW]
    tol_all: jnp.ndarray      # bool[3, G]
    port_bits: jnp.ndarray    # u32[G, PW]
    pref_ids: jnp.ndarray     # i32[G, MT, E, K]
    pref_op: jnp.ndarray      # i32[G, MT, E]
    pref_slot: jnp.ndarray    # i32[G, MT, E]
    pref_valid: jnp.ndarray   # bool[G, MT]
    pref_weight: jnp.ndarray  # f32[G, MT]


class PartialsStore(NamedTuple):
    """The resident partials triple, one row per cached class slot."""

    sfeas: jnp.ndarray  # bool[G, N]
    aff: jnp.ndarray    # f32[G, N]
    taint: jnp.ndarray  # f32[G, N]


def _eval_slot(cluster: ClusterTensors, specs: ClassSpecs, g):
    """One slot's partials row over the given cluster rows — the same
    kernel chain class_statics runs per class representative, fed from
    the stored spec instead of the batch tables (the parity claim)."""
    term_ok = match_terms(
        cluster, specs.sel_ids[g], specs.sel_op[g], specs.sel_slot[g]
    )  # bool[T, N]
    sel_mask = (term_ok & specs.sel_tv[g][:, None]).any(axis=0)[None, :]
    mt = specs.pref_valid.shape[1]
    pv = PodView(
        valid=specs.valid[g],
        req=jnp.zeros((1,), jnp.float32),          # unused by static kernels
        nonzero_req=jnp.zeros((1,), jnp.float32),  # unused by static kernels
        name_id=specs.name_id[g],
        sel_idx=jnp.where(specs.has_sel[g], 0, -1).astype(jnp.int32),
        tol_bits=specs.tol_bits[:, g, :],
        tol_all=specs.tol_all[:, g],
        port_bits=specs.port_bits[g],
        pref_idx=jnp.where(
            specs.pref_valid[g], jnp.arange(mt, dtype=jnp.int32), -1
        ),
        pref_weight=specs.pref_weight[g],
    )
    pref_mask = (
        match_terms(
            cluster, specs.pref_ids[g], specs.pref_op[g], specs.pref_slot[g]
        )
        & specs.pref_valid[g][:, None]
    )  # bool[MT, N]
    sfeas = static_feasible_for_pod(cluster, pv, sel_mask) & ~(
        (cluster.port_bits & pv.port_bits[None, :]).any(axis=-1)
    )
    return (
        sfeas,
        node_affinity_raw(pv, pref_mask),
        taint_toleration_raw(cluster, pv),
    )


def take_rows(cluster: ClusterTensors, idx) -> ClusterTensors:
    """The node-axis rows of every cluster leaf at `idx` (taint_bits is
    effect-major: its node axis is dim 1) — the sub-cluster the dirty
    refresh evaluates against."""
    return ClusterTensors(
        allocatable=cluster.allocatable[idx],
        requested=cluster.requested[idx],
        nonzero_requested=cluster.nonzero_requested[idx],
        node_valid=cluster.node_valid[idx],
        name_id=cluster.name_id[idx],
        label_bits=cluster.label_bits[idx],
        taint_bits=cluster.taint_bits[:, idx, :],
        port_bits=cluster.port_bits[idx],
        topo_ids=cluster.topo_ids[idx],
        image_bits=cluster.image_bits[idx],
        slice_id=cluster.slice_id[idx],
        torus_coords=cluster.torus_coords[idx],
        slice_dims=cluster.slice_dims[idx],
        slice_pos=cluster.slice_pos[idx],
    )


def take_specs(specs: ClassSpecs, idx) -> ClassSpecs:
    """Slot rows of the spec store at `idx` (tol axes are effect-major:
    slot axis is dim 1)."""
    return ClassSpecs(
        valid=specs.valid[idx],
        name_id=specs.name_id[idx],
        has_sel=specs.has_sel[idx],
        sel_ids=specs.sel_ids[idx],
        sel_op=specs.sel_op[idx],
        sel_slot=specs.sel_slot[idx],
        sel_tv=specs.sel_tv[idx],
        tol_bits=specs.tol_bits[:, idx, :],
        tol_all=specs.tol_all[:, idx],
        port_bits=specs.port_bits[idx],
        pref_ids=specs.pref_ids[idx],
        pref_op=specs.pref_op[idx],
        pref_slot=specs.pref_slot[idx],
        pref_valid=specs.pref_valid[idx],
        pref_weight=specs.pref_weight[idx],
    )


def set_spec_rows(specs: ClassSpecs, rows: ClassSpecs, idx) -> ClassSpecs:
    """Scatter freshly-encoded spec rows into the resident store at
    slot indices `idx` (duplicate indices carry identical rows — the
    bucket-padding convention, see models.mirror._pad_idx)."""
    return ClassSpecs(
        valid=specs.valid.at[idx].set(rows.valid),
        name_id=specs.name_id.at[idx].set(rows.name_id),
        has_sel=specs.has_sel.at[idx].set(rows.has_sel),
        sel_ids=specs.sel_ids.at[idx].set(rows.sel_ids),
        sel_op=specs.sel_op.at[idx].set(rows.sel_op),
        sel_slot=specs.sel_slot.at[idx].set(rows.sel_slot),
        sel_tv=specs.sel_tv.at[idx].set(rows.sel_tv),
        tol_bits=specs.tol_bits.at[:, idx].set(rows.tol_bits),
        tol_all=specs.tol_all.at[:, idx].set(rows.tol_all),
        port_bits=specs.port_bits.at[idx].set(rows.port_bits),
        pref_ids=specs.pref_ids.at[idx].set(rows.pref_ids),
        pref_op=specs.pref_op.at[idx].set(rows.pref_op),
        pref_slot=specs.pref_slot.at[idx].set(rows.pref_slot),
        pref_valid=specs.pref_valid.at[idx].set(rows.pref_valid),
        pref_weight=specs.pref_weight.at[idx].set(rows.pref_weight),
    )


def eval_store(cluster: ClusterTensors, specs: ClassSpecs) -> PartialsStore:
    """Full recompute: every slot's partials row over every node — the
    first-sync upload and the periodic-resync discipline's dispatch."""
    g_dim = specs.valid.shape[0]
    sfeas, aff, taint = jax.vmap(
        lambda g: _eval_slot(cluster, specs, g)
    )(jnp.arange(g_dim, dtype=jnp.int32))
    return PartialsStore(sfeas=sfeas, aff=aff, taint=taint)


def refresh_rows(
    store: PartialsStore,
    specs: ClassSpecs,
    cluster: ClusterTensors,
    idx,
) -> PartialsStore:
    """Re-evaluate ONLY the node columns at `idx` (the rows dirtied
    since the last sync, bucket-padded by repeating the first index) for
    every cached slot, and scatter them into the store — the
    O(changed-rows) half of the warm start."""
    sub = take_rows(cluster, idx)
    cols = eval_store(sub, specs)  # [G, D]
    return PartialsStore(
        sfeas=store.sfeas.at[:, idx].set(cols.sfeas),
        aff=store.aff.at[:, idx].set(cols.aff),
        taint=store.taint.at[:, idx].set(cols.taint),
    )


def insert_slots(
    store: PartialsStore,
    specs: ClassSpecs,
    cluster: ClusterTensors,
    idx,
) -> PartialsStore:
    """Full [N] rows for the slots at `idx` (classes first seen this
    batch, bucket-padded by repeating the first index), scattered into
    the store."""
    rows = eval_store(cluster, take_specs(specs, idx))  # [M, N]
    return PartialsStore(
        sfeas=store.sfeas.at[idx].set(rows.sfeas),
        aff=store.aff.at[idx].set(rows.aff),
        taint=store.taint.at[idx].set(rows.taint),
    )


def grow_store_cols(store: PartialsStore, dn: int) -> PartialsStore:
    """Pad `dn` zero node-columns onto every resident row — the elastic
    node axis's in-place partials grow (one on-device concat per leaf,
    zero host transfer).  The caller immediately refresh_rows()-es the
    new column range against the grown cluster, so the pad value never
    reaches a solve: every class row stays warm across the bucket
    crossing."""
    import jax.numpy as jnp

    def pad(arr):
        return jnp.concatenate(
            [arr, jnp.zeros(arr.shape[:1] + (dn,), arr.dtype)], axis=1
        )

    return PartialsStore(
        sfeas=pad(store.sfeas), aff=pad(store.aff), taint=pad(store.taint)
    )


def shrink_store_cols(store: PartialsStore, n: int) -> PartialsStore:
    """Slice the resident rows to the first `n` node columns — the
    post-dwell bucket shrink (every live row index is < n by the
    watermark invariant)."""
    return PartialsStore(
        sfeas=store.sfeas[:, :n],
        aff=store.aff[:, :n],
        taint=store.taint[:, :n],
    )


def gather_statics(store: PartialsStore, slots) -> ClassStatics:
    """The batch-ordered [C, N] statics view: store rows at `slots`
    (one slot id per joint class; padded classes alias class 0's slot,
    matching class_statics' clipped-representative convention)."""
    return ClassStatics(
        sfeas=store.sfeas[slots],
        aff=store.aff[slots],
        taint=store.taint[slots],
    )


# Shared single-chip executables: every PartialsCache on the default
# device set dispatches through these, so N caches (one per scheduler
# profile / test instance) share one compile cache per shape bucket
# instead of paying one XLA compile each.  Mesh-mode caches build their
# own out_shardings-pinned twins (models/partials.py).
eval_store_jit = jax.jit(eval_store)
refresh_rows_jit = jax.jit(refresh_rows)
insert_slots_jit = jax.jit(insert_slots)
gather_statics_jit = jax.jit(gather_statics)
set_spec_rows_jit = jax.jit(set_spec_rows)
grow_store_cols_jit = jax.jit(grow_store_cols, static_argnums=(1,))
shrink_store_cols_jit = jax.jit(shrink_store_cols, static_argnums=(1,))
