"""TPU slice topology — torus-aware gang carve-outs as tensor ops.

A TPU slice is a torus of devices; a training gang wants a *contiguous
axis-aligned sub-cuboid* of one slice (the ICI-connected block), not G
scattered hosts.  The cluster tensors carry each node's slice id, torus
coordinates and the owning slice's extent (ops/schema.py, from the
api.LABEL_TPU_* node labels); this module turns them into the three
batched ops the solver scan consumes:

  contiguity   corner_mask: is node n the min-corner of a fully-free
               a x b x c sub-cuboid of its slice?  Free occupancy is
               scattered into a value-space grid ``[S, D, D, D]`` (the
               prep_spread idiom — node space in, value space for the
               window math, node space out), a 3-D integral image makes
               every window sum O(1), and the per-node gather answers
               all N corners in one shot.
  adjacency    carveout_eval: the carve-out score family.  Anchors
               (first member of a gang, or a solo shaped pod) prefer
               corners by best-fit leftover (minimize the fragment the
               carve-out leaves behind) then by coordinate-sum packing;
               anchored members prefer in-cuboid nodes by torus hop
               distance to the carved corner.  Bonuses are large exact
               integers, so contiguous placements score strictly above
               fragmenting ones and the host oracle reproduces the
               totals bit-for-bit (testing/oracle.py).
  fragmentation  cluster-wide packing health: per-slice largest
               placeable free cube (edge k, the same integral-image
               window check swept over k) and the free-device share
               those cubes cover — ``score = 1 - placeable/free``,
               0 = every free device sits in a maximal cube.

Everything is jit/shard_map-friendly: under ``axis_name`` the grid
scatters psum across node shards (a slice spanning shards is counted
whole) and the per-node gathers stay local — the ops.assign "one
implementation, two layouts" idiom.

Semantics contract (shared verbatim by the device kernels, the host
oracle, and CoschedulingPermit's release check):

  * a node is FREE iff it carries no (bound or in-scan assumed) pods —
    ``requested[:, RESOURCE_PODS] == 0`` — and belongs to a slice;
  * a carve-out is a non-wrapping axis-aligned box ``[lo, lo+shape)``
    inside one slice's declared extent;
  * the gang's FIRST placed member anchors the carve-out at its own
    coordinates (the anchor filter/score steers it onto a free-box
    min-corner); every later member of the gang targets the anchored
    box.  ``require`` policy turns both preferences into filters, so a
    gang that cannot fit contiguously parks whole (all-or-nothing
    releases the anchor too); ``prefer`` falls back to scattered
    placement and counts a carve-out fallback.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..analysis.markers import hot_path
from .schema import RESOURCE_PODS, ClusterTensors

# Carve-out score-family weights.  Exact small integers inside f32's
# exact envelope (2^24): the base score families sum to <= ~700, so the
# ordering is strict — in-carve-out/corner >> same-slice >> any base
# score difference — and the host oracle's float math lands on the same
# totals.  testing/oracle.py imports these; change them only together.
BONUS_CARVE = 1_000_000.0   # in-carve-out member / free-box corner anchor
BONUS_SLICE = 10_000.0      # anchored gang's slice (prefer-mode fallback)
W_LEFTOVER = 100.0          # anchor best-fit: slice free count minus volume
W_HOP = 10.0                # member compactness: torus hops to the corner
W_CORNER = 10.0             # anchor packing: corner coordinate sum


class SliceStats(NamedTuple):
    """fragmentation() report (device scalars/vectors)."""

    score: jnp.ndarray         # f32[]  1 - largest-placeable-cube share of free
    largest_cube: jnp.ndarray  # i32[S] per-slice largest free cube edge
    free_count: jnp.ndarray    # f32[S] free devices per slice (the histogram)


def free_devices(cluster: ClusterTensors) -> jnp.ndarray:
    """bool[N]: slice-member nodes hosting no pods (training devices are
    whole-node; RESOURCE_PODS counts bound AND in-scan assumed pods, so
    the mask tightens as the solve places gangs)."""
    return (
        cluster.node_valid
        & (cluster.slice_id >= 0)
        & (cluster.requested[:, RESOURCE_PODS] <= 0)
    )


# coherence: rebuilt-per-solve -- the occupancy grid tightens as the solve
# places gangs; a copy cached across solves would double-place
def _cell_grid(
    cluster: ClusterTensors,
    free: jnp.ndarray,
    slice_z: int,
    dmax: int,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """bool[S, D, D, D]: coordinate (s, x, y, z) is present AND free.
    A coordinate shared by several nodes (core index) is free only when
    every node on it is free.  Under shard_map the presence/occupancy
    scatters psum across shards before combining."""
    xyz = cluster.torus_coords[:, :3]
    has = (cluster.slice_id >= 0) & (xyz >= 0).all(axis=-1)
    sc = jnp.clip(cluster.slice_id, 0, slice_z - 1)
    cc = jnp.clip(xyz, 0, dmax - 1)
    idx = (sc, cc[:, 0], cc[:, 1], cc[:, 2])
    shape = (slice_z, dmax, dmax, dmax)
    pres = jnp.zeros(shape, jnp.int32).at[idx].max(has.astype(jnp.int32))
    occ = jnp.zeros(shape, jnp.int32).at[idx].max(
        (has & ~free).astype(jnp.int32)
    )
    if axis_name is not None:
        pres = jax.lax.psum(pres, axis_name)
        occ = jax.lax.psum(occ, axis_name)
    return (pres > 0) & (occ == 0)


def _integral(cell: jnp.ndarray) -> jnp.ndarray:
    """Zero-padded 3-D integral image: I[s, i, j, k] = free cells with
    x < i, y < j, z < k — every box sum becomes 8 gathers."""
    g = jnp.pad(cell.astype(jnp.float32), ((0, 0), (1, 0), (1, 0), (1, 0)))
    return g.cumsum(axis=1).cumsum(axis=2).cumsum(axis=3)


def _box_sum(integral, s, lo, hi):
    """Free-cell count in [lo, hi) of slice s (vectorized gathers; lo/hi
    i32[..., 3] already within [0, D])."""
    def at(a, b, c):
        return integral[s, a, b, c]

    l0, l1, l2 = lo[..., 0], lo[..., 1], lo[..., 2]
    h0, h1, h2 = hi[..., 0], hi[..., 1], hi[..., 2]
    return (
        at(h0, h1, h2)
        - at(l0, h1, h2) - at(h0, l1, h2) - at(h0, h1, l2)
        + at(l0, l1, h2) + at(l0, h1, l2) + at(h0, l1, l2)
        - at(l0, l1, l2)
    )


def corner_mask(
    cluster: ClusterTensors,
    free: jnp.ndarray,
    shape: jnp.ndarray,
    slice_z: int,
    dmax: int,
    axis_name: Optional[str] = None,
    integral: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """bool[N]: node n is the min-corner of a fully-free ``shape`` box
    inside its slice's declared extent.  ``shape`` is a traced i32[3]
    (per-pod), so one executable serves every gang shape."""
    if integral is None:
        integral = _integral(
            _cell_grid(cluster, free, slice_z, dmax, axis_name=axis_name)
        )
    xyz = cluster.torus_coords[:, :3]
    has = (cluster.slice_id >= 0) & (xyz >= 0).all(axis=-1)
    fits = has & ((xyz + shape[None, :]) <= cluster.slice_dims).all(axis=-1)
    s = jnp.clip(cluster.slice_id, 0, slice_z - 1)
    lo = jnp.clip(xyz, 0, dmax)
    hi = jnp.clip(xyz + shape[None, :], 0, dmax)
    vol = shape.prod().astype(jnp.float32)
    full = _box_sum(integral, s, lo, hi) >= vol
    return fits & full & free


def slice_free_counts(
    cluster: ClusterTensors,
    free: jnp.ndarray,
    slice_z: int,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """f32[S]: free COORDINATES per slice (core-collapsed, matching the
    cell grid's granularity would cost another scatter — node counts
    are the best-fit signal and stay exact integers)."""
    sc = jnp.clip(cluster.slice_id, 0, slice_z - 1)
    counts = jnp.zeros(slice_z, jnp.float32).at[sc].add(
        jnp.where(free, 1.0, 0.0)
    )
    if axis_name is not None:
        counts = jax.lax.psum(counts, axis_name)
    return counts


def carveout_eval(
    cluster: ClusterTensors,
    pods,
    i,
    gang_sl: Optional[jnp.ndarray],
    gang_lo: Optional[jnp.ndarray],
    features,
    axis_name: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The carve-out Filter+Score slice for pod ``i`` against the carry
    state: ``(bonus f32[N], ok bool[N])``.  ``ok`` is the require-mode
    filter (anchors: free-box corners; members: the anchored cuboid);
    ``bonus`` is the adjacency-aware score family added on top of the
    normalized base scores (module constants).  Unshaped pods return
    (0, True) everywhere — the family is free for them."""
    shape = pods.pod_shape[i]                       # i32[3]
    shaped = shape.prod() > 0
    g = pods.group_id[i]
    n = cluster.slice_id.shape[0]
    sid = cluster.slice_id
    xyz = cluster.torus_coords[:, :3]

    free = free_devices(cluster)
    corner = corner_mask(
        cluster, free, shape, features.slice_z, features.slice_dim,
        axis_name=axis_name,
    )
    fc = slice_free_counts(cluster, free, features.slice_z, axis_name=axis_name)
    leftover = jnp.maximum(
        fc[jnp.clip(sid, 0, features.slice_z - 1)]
        - shape.prod().astype(jnp.float32),
        0.0,
    )
    coordsum = jnp.where(
        (xyz >= 0).all(axis=-1), xyz.sum(axis=-1), 0
    ).astype(jnp.float32)
    anchor_bonus = jnp.where(
        corner,
        BONUS_CARVE - W_LEFTOVER * leftover - W_CORNER * coordsum,
        0.0,
    )

    if gang_sl is not None:
        gc = jnp.clip(g, 0, gang_sl.shape[0] - 1)
        asl, alo = gang_sl[gc], gang_lo[gc]
        anchored = shaped & (g >= 0) & (asl >= 0)
    else:
        asl = jnp.int32(-1)
        alo = jnp.full(3, -1, jnp.int32)
        anchored = jnp.bool_(False)
    # one member per DEVICE: a member targets free in-cuboid nodes only
    # (the anchor occupied its corner; each later member takes the next
    # free device, nearest-to-corner first)
    same = (sid == asl) & (sid >= 0) & free
    in_cub = (
        same
        & (xyz >= alo[None, :]).all(axis=-1)
        & (xyz < alo[None, :] + shape[None, :]).all(axis=-1)
    )
    hop = jnp.abs(xyz - alo[None, :]).sum(axis=-1).astype(jnp.float32)
    member_bonus = jnp.where(
        in_cub,
        BONUS_CARVE + BONUS_SLICE - W_HOP * hop,
        jnp.where(same, BONUS_SLICE - W_HOP * hop, 0.0),
    )

    bonus = jnp.where(
        shaped, jnp.where(anchored, member_bonus, anchor_bonus), 0.0
    )
    ok = jnp.where(
        shaped,
        jnp.where(anchored, in_cub, corner),
        jnp.ones(n, dtype=bool),
    )
    return bonus, ok


@hot_path
def fragmentation(
    cluster: ClusterTensors,
    slice_z: int,
    dmax: int,
    axis_name: Optional[str] = None,
) -> SliceStats:
    """Cluster-wide packing health from the current free mask: per-slice
    largest placeable free cube (the same integral-image window check,
    swept over the static edge ladder k = 1..D) and the share of free
    devices those cubes cover.  ``score`` is 0 when every free device
    sits inside a maximal cube (freshly drained slices), approaching 1
    as free devices shatter into unplaceable fragments."""
    free = free_devices(cluster)
    cell = _cell_grid(cluster, free, slice_z, dmax, axis_name=axis_name)
    integral = _integral(cell)
    # per-slice declared extent, in value space (psum-combined so a
    # shard that owns no node of a slice still sees its dims)
    sc = jnp.clip(cluster.slice_id, 0, slice_z - 1)
    sdims = jnp.zeros((slice_z, 3), jnp.int32).at[sc].max(
        jnp.where((cluster.slice_id >= 0)[:, None], cluster.slice_dims, 0)
    )
    if axis_name is not None:
        sdims = jax.lax.pmax(sdims, axis_name)
    largest = jnp.zeros(slice_z, jnp.int32)
    coords = jnp.arange(dmax)
    for k in range(1, dmax + 1):
        lo = jnp.stack(
            jnp.meshgrid(coords, coords, coords, indexing="ij"), axis=-1
        )                                              # [D, D, D, 3]
        hi = jnp.clip(lo + k, 0, dmax)
        s_idx = jnp.arange(slice_z)[:, None, None, None]
        cnt = _box_sum(
            integral,
            jnp.broadcast_to(s_idx, (slice_z, dmax, dmax, dmax)),
            jnp.broadcast_to(lo[None], (slice_z, dmax, dmax, dmax, 3)),
            jnp.broadcast_to(hi[None], (slice_z, dmax, dmax, dmax, 3)),
        )
        in_bounds = (
            (lo[None] + k) <= sdims[:, None, None, None, :]
        ).all(axis=-1)
        exists = (in_bounds & (cnt >= float(k ** 3))).any(axis=(1, 2, 3))
        largest = jnp.where(exists, k, largest)
    free_count = slice_free_counts(cluster, free, slice_z, axis_name=axis_name)
    placeable = (largest.astype(jnp.float32) ** 3).sum()
    total_free = free_count.sum()
    score = 1.0 - placeable / jnp.maximum(total_free, 1.0)
    return SliceStats(
        score=jnp.maximum(score, 0.0),
        largest_cube=largest,
        free_count=free_count,
    )


def fragmentation_report(cluster: ClusterTensors) -> dict:
    """Host convenience: derive the static capacities from the (host or
    device) cluster tensors and return plain numbers — what bench c10
    and tests read."""
    import numpy as np

    from ..utils.vocab import pad_dim

    sids = np.asarray(cluster.slice_id)
    if not (sids >= 0).any():
        return {"score": 0.0, "largest_cube": [], "free_count": []}
    slice_z = pad_dim(int(sids.max()) + 1, 1)
    dmax = max(int(np.asarray(cluster.slice_dims).max()), 1)
    stats = fragmentation(
        jax.tree.map(jnp.asarray, cluster), slice_z, dmax
    )
    n_real = int(sids.max()) + 1
    return {
        "score": float(stats.score),
        "largest_cube": np.asarray(stats.largest_cube)[:n_real].tolist(),
        "free_count": np.asarray(stats.free_count)[:n_real].tolist(),
    }
