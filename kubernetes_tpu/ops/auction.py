"""Joint batched assignment — the auction-style parallel solve.

The greedy scan (ops.assign) preserves the reference's one-pod-at-a-time
semantics (schedule_one.go:66-133) but is inherently sequential: P scan
steps.  For large pending bursts — the gang/coscheduling config in
BASELINE — this module solves the batch *jointly* in rounds:

  1. filtering + scoring runs once per pod *class* (pods with
     byte-identical specs — schema.PodBatch.class_id — see identical
     masks and score rows, so the pass is [C, N] with C typically tens,
     not [P, N]); each class's max-score tie nodes are enumerated by
     cumsum-rank with a per-round hashed rotation (the joint analogue of
     the reference's uniform selectHost sampling, schedule_one.go:
     867-905) and the class's j-th pod bids the j-th tie node — distinct
     bids while ties last, so uniform clusters commit in bulk;
  2. each node accepts its bidders in solve order (priority, then batch
     index — queuesort/priority_sort.go:52) while they fit its remaining
     capacity, computed with one sort + segmented cumulative sum — no
     host round-trips;
  3. accepted pods commit (their resources leave the pool); rejected
     pods re-bid against the updated pool next round.

Every round in which an unplaced pod still has a feasible node commits at
least one pod (the first bidder in solve order on each node always fits),
so the loop terminates; contention bursts converge in a handful of
rounds because acceptance is per-node-parallel.

Gang semantics (all-or-nothing groups, api.PodSpec.scheduling_group):
after the rounds converge, groups with any unplaced member release all
their placements in one masked subtract — the coscheduling-PodGroup
pattern (no in-tree reference counterpart; the out-of-tree coscheduling
plugin's Permit phase is the analogue).

Constraint coverage: the static families + resources (NodeResourcesFit,
NodeName, NodeUnschedulable, TaintToleration, NodeAffinity, NodePorts
against bound pods), PLUS the two coupled families the round structure
can repair:

  * PodTopologySpread (hard + soft): filtering/scoring reads the round's
    counts; after acceptance a per-(constraint, topology value) prefix
    cap releases over-admitted pods (rank r kept iff
    count + r + 1 - globalMin <= maxSkew, the filtering.go:336 criterion
    applied cumulatively), then counts commit from net accepts.
  * InterPodAntiAffinity (required, both directions incl. existing-pods
    anti-affinity): the filter handles bound state; within-round
    conflicts (a carrier and a matcher of one term accepted into one
    topology domain) release everything after the first accepted pod of
    that (term, value) group.

Affinity-direction terms (co-location + the first-pod escape) and
in-batch host-port claims still route to the greedy scan
(`auction_features_ok`): concurrent co-location bids can deadlock-split
groups, which is exactly what the reference serializes for.

Placements released by repair re-bid next round against updated counts;
pods still unplaced at max_rounds return -1 and the host scheduler parks
and retries them — system-level behaviour is unchanged, only the batch
boundary moves.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import retrace
from ..analysis.markers import hot_path
from .assign import (
    NEG_INF,
    REASON_GANG,
    REASON_INTERPOD,
    REASON_NONE,
    REASON_PORTS,
    REASON_RESOURCES,
    REASON_SPREAD,
    REASON_STATIC,
    FeatureFlags,
    class_statics,
    features_of,
    required_topo_z_split,
    solve_order,
)
from .filters import fits_resources, pod_view, preferred_match, selector_match
from .interpod import (
    _idx_to_bits,
    _pack_bits_t,
    _unpack_bits_t,
    interpod_filter,
    prep_terms,
)
from .schema import ClusterTensors, Snapshot, num_groups
from .scores import (
    DEFAULT_SCORE_CONFIG,
    ScoreConfig,
    combine_scores,
    resource_score_parts,
)
from .topology import prep_spread, spread_filter, spread_score

_BIG_I = jnp.int32(2**30)


class AuctionResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, -1 unschedulable/dropped
    scores: jnp.ndarray       # f32[P]: accepted bid's score (-inf if none)
    rounds: jnp.ndarray       # i32[]: bidding rounds executed
    gang_dropped: jnp.ndarray  # bool[P]: placed but released with its gang
    cluster: ClusterTensors   # post-solve cluster
    reasons: jnp.ndarray = None  # i32[P]: assign.REASON_* for unplaced pods
    debug_sp_counts: jnp.ndarray = None  # f32[C, N] final spread counts (debug)


def auction_features_ok(features: FeatureFlags) -> bool:
    """True when the joint solve covers this batch's constraint families.
    Slice carve-outs (features.slices) are sequential-by-construction —
    the anchor member's placement defines every later member's cuboid —
    so shaped batches stay on the greedy scan."""
    return not (features.ports or features.interpod_aff or features.slices)


def default_tie_k(snapshot: Snapshot) -> int:  # graftlint: disable=purity -- host-side prep on the pre-transfer snapshot
    """Tie nodes enumerated per class per round: enough for the LARGEST
    class to bid distinct nodes (a burst of identical pods would
    otherwise cram onto tie_k nodes instead of spreading over the tie
    set), power-of-two bucketed for jit-cache stability, bounded by the
    node axis."""
    from ..utils.vocab import pad_dim

    cid = np.asarray(snapshot.pods.class_id)
    live = cid[np.asarray(snapshot.pods.valid)]
    biggest = int(np.bincount(live).max()) if live.size else 1
    return min(pad_dim(max(biggest, 64), 1), snapshot.cluster.allocatable.shape[0])


@hot_path
def auction_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    n_groups: int = 0,
    tie_seed: int = 0,
    max_rounds: int = 64,
    features: Optional[FeatureFlags] = None,
    topo_z: Optional[Tuple[int, int]] = None,
    tie_k: int = 128,
    axis_name: Optional[str] = None,
) -> AuctionResult:
    """Jointly assign the pending batch: rounds of (parallel bid →
    per-node prefix acceptance → constraint repair).  n_groups:
    gang-group count (static; 0 disables the gang post-pass).  topo_z:
    (z_spread, z_terms) per-family padded value capacities (static;
    auto-derived outside jit — required_topo_z_split).  tie_k (static):
    tie nodes enumerated per class per round; classes with more active
    pods than surviving tie nodes wrap and resolve through repair.

    Relative to greedy, concurrent bids don't see each other's score
    impact within a round — acceptance order still respects priority,
    capacity is never oversubscribed, and the spread / anti-affinity
    repairs keep every committed placement constraint-valid.  Where no
    two pods contend, round-1 bids equal the greedy picks (same
    filter/score kernels).

    axis_name: mesh axis when called under shard_map with the NODE axis
    sharded (parallel.sharded.sharded_auction_assign).  One
    implementation serves both layouts: pod-space state (bids,
    acceptance, repair ranks, gang bookkeeping) is replicated; node-space
    state (capacity, spread counts, interpod bits) stays sharded, with
    ownership-masked psum gathers at the pod<->node boundary, pmax/pmin
    for score normalization and election, and an all_gather merge of the
    per-shard tie sets.  Placements are bit-identical to the single-chip
    solve (top_k ties resolve to the lowest global node index in both
    layouts).
    """
    if features is None:
        features = features_of(snapshot)
    if not auction_features_ok(features):
        raise ValueError(
            "auction_assign does not cover in-batch host ports or "
            f"affinity-direction inter-pod terms; route batches with "
            f"{features} through greedy_assign"
        )
    if topo_z is None:
        topo_z = required_topo_z_split(snapshot)
    z_spread, z_terms = topo_z
    if axis_name is None:
        tie_k = min(tie_k, snapshot.cluster.allocatable.shape[0])
    # sharded: the wrapper guarantees tie_k <= GLOBAL node count; the
    # local shape here is one shard, so clamping against it would
    # silently shrink the tie set (each shard's top_k clamps to its
    # local size below; the merge restores the global tie_k)
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]      # LOCAL node count under shard_map
    p = pods.req.shape[0]

    # -- shard-layout helpers (identity when axis_name is None) -----------
    if axis_name is not None:
        n_shards = jax.lax.psum(1, axis_name)
        offset = jax.lax.axis_index(axis_name) * n
        n_total = n * n_shards
    else:
        offset = 0
        n_total = n

    def _pmax(x):
        return x if axis_name is None else jax.lax.pmax(x, axis_name)

    def _pmin(x):
        return x if axis_name is None else jax.lax.pmin(x, axis_name)

    def _psum(x):
        return x if axis_name is None else jax.lax.psum(x, axis_name)

    def _any(x):
        if axis_name is None:
            return x.any()
        return jax.lax.pmax(x.any().astype(jnp.int32), axis_name) > 0

    def node_rows(mat, idx):
        """Gather rows of a node-axis tensor at GLOBAL node ids [P].
        Sharded: the owning shard contributes, psum replicates."""
        if axis_name is None:
            return mat[idx]
        own = (idx >= offset) & (idx < offset + n)
        loc = jnp.clip(idx - offset, 0, n - 1)
        vals = mat[loc]
        mask = own.reshape(own.shape + (1,) * (vals.ndim - own.ndim))
        if vals.dtype == jnp.bool_:
            out = jax.lax.psum(
                jnp.where(mask, vals, False).astype(jnp.int32), axis_name
            )
            return out > 0
        return jax.lax.psum(
            jnp.where(mask, vals, jnp.zeros_like(vals)), axis_name
        )

    def node_cell_gather(mat, rows, idx):
        """mat[rows[p], idx[p]] where mat is [R, N]-sharded on axis 1 and
        idx holds GLOBAL node ids."""
        if axis_name is None:
            return mat[rows, idx]
        own = (idx >= offset) & (idx < offset + n)
        loc = jnp.clip(idx - offset, 0, n - 1)
        return jax.lax.psum(
            jnp.where(own, mat[rows, loc], jnp.zeros((), mat.dtype)),
            axis_name,
        )

    def scatter_add_rows(dst, idx, vals, mask):
        """dst.at[idx].add(vals * mask) with idx GLOBAL; sharded, only
        the owning shard writes its local rows."""
        if axis_name is None:
            return dst.at[idx].add(vals * mask[:, None])
        own = mask & (idx >= offset) & (idx < offset + n)
        loc = jnp.clip(idx - offset, 0, n - 1)
        return dst.at[loc].add(vals * own[:, None].astype(vals.dtype))
    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    # Factorized class axes (PodBatch docstring): heavy per-row kernels
    # run on the small spec / constraint factors; the joint axis only
    # gathers + combines.  sfeas/aff/taint rows are identical across
    # joint classes sharing a spec class, so computing them on the spec
    # axis is exact, not an approximation.
    s_reps = jnp.clip(pods.spec_rep, 0, p - 1)      # [Cs]
    k_reps = jnp.clip(pods.cons_rep, 0, p - 1)      # [Cc]
    c_dim = pods.class_rep.shape[0]
    cs_dim = pods.spec_rep.shape[0]
    cc_dim = pods.cons_rep.shape[0]
    jspec = jnp.clip(pods.joint_spec, 0, cs_dim - 1)  # [C]
    jcons = jnp.clip(pods.joint_cons, 0, cc_dim - 1)  # [C]
    sfeas_s, aff_s, taint_s = class_statics(
        cluster, pods, sel_mask, pref_mask, reps=s_reps
    )
    reps = jnp.clip(pods.class_rep, 0, p - 1)
    pref_raw_k = img_k = None
    if features.interpod_pref:
        # raw preferred-interpod rows per CONSTRAINT class; the joint
        # combine normalizes each against its spec class's static
        # feasibility (static_extra's contract — the normalization set
        # is placement-independent)
        from .interpod import prep_pref_pod, pref_pod_raw

        pp = prep_pref_pod(
            cluster, prefpod, z_terms, axis_name=axis_name,
            has_bound=features.bound_pref,
        )
        pref_raw_k = jax.vmap(lambda rep: pref_pod_raw(pp, prefpod, rep))(
            k_reps
        )
    if features.images:
        from .scores import image_locality_score

        img_k = jax.vmap(
            lambda rep: image_locality_score(
                cluster, images, rep, axis_name=axis_name
            )
        )(k_reps)

    def joint_extra(s, k):
        """Already-weighted extra score row for joint class (s, k), or
        None when neither family is active (matches static_extra)."""
        if pref_raw_k is None and img_k is None:
            return None
        from .scores import normalize_minmax

        total = jnp.zeros(n, jnp.float32)
        if pref_raw_k is not None:
            total = total + cfg.interpod_weight * normalize_minmax(
                pref_raw_k[k], sfeas_s[s], axis_name=axis_name
            )
        if img_k is not None:
            total = total + cfg.image_weight * img_k[k]
        return total

    order = solve_order(pods)
    # solve_pos[i] = pod i's rank in solve order (repair keeps prefixes
    # in this order, matching acceptance's priority discipline)
    solve_pos = jnp.zeros(p, jnp.int32).at[order].set(
        jnp.arange(p, dtype=jnp.int32)
    )

    sp0 = (
        prep_spread(
            cluster, sel_mask, spread, z_spread, axis_name=axis_name,
            has_bound=features.bound_spread,
        )
        if features.spread
        else None
    )
    tm0 = (
        prep_terms(
            cluster, terms, z_terms, axis_name=axis_name,
            slots=features.term_slots, has_bound=features.bound_terms,
        )
        if features.interpod
        else None
    )
    if features.interpod:
        t_dim = terms.valid.shape[0]
        # dense [P, T] involvement tables for the within-round repair
        mi_dense = (
            _unpack_bits_t(terms.matches_incoming, t_dim)
            & terms.valid[None, :]
        )
        anti_dense = _idx_to_bits(terms.anti_idx, t_dim) & terms.valid[None, :]
        slot_of_t = terms.slot                                    # [T]

    seed_c = jnp.uint32(tie_seed * 2 + 1)
    arange_p = jnp.arange(p, dtype=jnp.int32)

    def bids(requested, nonzero, assigned, rnd, sp_counts, tm_bits):
        # Pods of one class (byte-identical spec incl. requests) see
        # identical filter masks and score rows against the current pool,
        # so filtering + scoring runs once per *class* — and the class
        # axis itself factorizes: resource fit + fit/balanced score rows
        # per SPEC class ([Cs, N], a handful of rows), spread/inter-pod
        # filter rows per CONSTRAINT class ([Cc, N], one per service
        # shape), with the joint [C, N] pass reduced to gathers, the
        # normalize-and-weight combine, and top_k.  Within a round the
        # class's max-score tie set is fixed, so bidding needs no per-pod
        # (P x N) pass either: rank the tie nodes once per class in
        # counter-hash order (uniform, like the reference's selectHost
        # sampling schedule_one.go:867) and hand the class's j-th active
        # pod the j-th tie node.  Pods of a class thus bid *distinct*
        # nodes while ties last — fewer conflicts than independent
        # sampling — and the whole per-pod step is O(P) gathers.
        cl = cluster._replace(requested=requested, nonzero_requested=nonzero)
        sp = sp0._replace(counts_node=sp_counts) if features.spread else None
        tm = (
            tm0._replace(
                present_bits=tm_bits[0], blocked_bits=tm_bits[1],
                global_any=tm_bits[2],
            )
            if features.interpod
            else None
        )

        def per_spec(rep):
            pod = pod_view(pods, rep)
            fit, bal = resource_score_parts(cl, pod, cfg)
            return fits_resources(cl, pod), fit, bal

        fits_s, fit_s, bal_s = jax.vmap(per_spec)(s_reps)   # [Cs, N]
        spf_k = (
            jax.vmap(
                lambda rep: spread_filter(
                    sp, spread, rep, axis_name=axis_name
                )
            )(k_reps)
            if features.spread
            else None
        )
        ipf_k = (
            jax.vmap(lambda rep: interpod_filter(tm, terms, rep))(k_reps)
            if features.interpod
            else None
        )

        def per_class(c, rep):
            s, k = jspec[c], jcons[c]
            feas = sfeas_s[s] & fits_s[s]
            if features.spread:
                feas = feas & spf_k[k]
            if features.interpod:
                feas = feas & ipf_k[k]
            sp_score = (
                spread_score(sp, spread, rep, feas, axis_name=axis_name)
                if features.soft_spread
                else None
            )
            scores = combine_scores(
                fit_s[s], bal_s[s], aff_s[s], taint_s[s], feas, cfg,
                axis_name=axis_name, spread_score=sp_score,
                extra=joint_extra(s, k),
            )
            masked = jnp.where(feas, scores, NEG_INF)
            best = _pmax(jnp.max(masked))
            tie = jnp.asarray(feas & (masked == best))
            # Tie nodes enumerated by top_k over a per-(class, round)
            # hashed node ordering: one fused top_k per class instead of
            # the earlier full-[N] inverse scatter (TPU scatters
            # serialize; at hundreds of classes the scatter dominated the
            # round).  The hash randomizes which tie nodes surface and
            # rotates every round, so re-bidding classes diversify.  The
            # hash input is the GLOBAL node id, so the tie ORDER is
            # layout-independent; sharded, each shard takes its local
            # top-k and an all_gather + re-top_k merges them (equal keys
            # resolve to the lowest global id in both layouts).
            rot = (
                (c.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
                ^ (rnd.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
                ^ seed_c
            ) * jnp.uint32(0x27D4EB2F)
            gids = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(1)
            if axis_name is not None:
                gids = gids + jnp.uint32(offset)
            hkey = (gids * jnp.uint32(0x9E3779B9)) ^ rot
            key = jnp.where(tie, (hkey >> 2).astype(jnp.int32), -1)
            local_k = min(tie_k, n)  # a shard holds at most n tie nodes
            _vals, topk_idx = jax.lax.top_k(key, local_k)  # i32[K_local]
            if axis_name is not None:
                topk_idx = topk_idx + offset
                vals_g = jax.lax.all_gather(_vals, axis_name)    # [D, Kl]
                idx_g = jax.lax.all_gather(topk_idx, axis_name)  # [D, Kl]
                m_vals, m_pos = jax.lax.top_k(vals_g.reshape(-1), tie_k)
                topk_idx = idx_g.reshape(-1)[m_pos]
            cnt = jnp.minimum(
                _psum(tie.sum()), tie_k
            ).astype(jnp.int32)
            return topk_idx, cnt, best

        inv_c, cnt_c, best_c = jax.vmap(per_class)(
            jnp.arange(c_dim, dtype=jnp.int32), reps
        )  # i32[C, K], i32[C], f32[C]

        # Within-class position j of each active pod, in solve order (so
        # higher-priority pods take earlier tie slots).
        cls = jnp.clip(pods.class_id, 0, c_dim - 1)
        active = (assigned < 0) & pods.valid
        actkey = jnp.where(active, cls, c_dim)
        sperm = order[jnp.argsort(actkey[order], stable=True)]
        skey = actkey[sperm]
        firstpos = jnp.searchsorted(skey, skey, side="left")
        j = jnp.zeros(p, jnp.int32).at[sperm].set(
            arange_p - firstpos.astype(jnp.int32)
        )
        cnt = cnt_c[cls]
        has = active & (best_c[cls] > NEG_INF) & (cnt > 0)
        # the per-round rotation lives in the tie hash; j indexes the
        # class's hash-ordered tie list directly
        slot = j % jnp.maximum(cnt, 1)
        bid = jnp.where(has, inv_c[cls, slot], n_total).astype(jnp.int32)
        val = jnp.where(has, best_c[cls], NEG_INF)
        return bid, val

    _BIGF = jnp.float32(1e9)

    # how many admit passes one round's spread repair runs: each pass
    # commits what fits under the current global minimum, then the next
    # pass re-evaluates the remainder against the RAISED minimum — the
    # sequential scan's continuously-rising min, approximated in k steps
    SPREAD_REPAIR_ITERS = 3

    if features.spread:
        # [N, C] row-gather layouts: axis-1 (per-column) gathers and
        # scatters of [C, P] tables serialize on TPU (~0.08 s each at
        # 16k pods); row gathers of the transposed layout are contiguous
        v_nc = sp0.v.T
        elig_nc = sp0.eligible.T
        cmax_sp = sp0.counts_node.shape[0]
        # per-slot value one-hots [Z, N]: value-space -> node-space maps
        # become small matmuls on the MXU instead of [C, N] gathers from
        # [C, Z] tables (gathers serialize: ~0.08 s per call at 16k
        # nodes; the matmul is [C, Z] @ [Z, N] with Z tiny)
        spread_onehot = {}
        for s in features.spread_slots:
            v_n = cluster.topo_ids[:, s]
            spread_onehot[s] = (
                (v_n[None, :] == jnp.arange(z_spread)[:, None])
                & (v_n >= 0)[None, :]
            ).astype(jnp.float32)                                # [Z, N]

    def _slot_sorts(topo_pt):
        """Per-slot (perm, inv, firstv) of the round's bid values —
        depends only on the bids, so it hoists out of the repair's
        admit iterations.  topo_pt: [P, TK] bid nodes' topo values
        (gathered once per round; replicated under shard_map)."""
        out = {}
        for s in features.spread_slots:
            v_p = topo_pt[:, s]
            key = jnp.where(v_p >= 0, v_p, _BIG_I)
            perm = order[jnp.argsort(key[order], stable=True)]
            skey = key[perm]
            firstv = jnp.searchsorted(skey, skey, side="left")   # [P]
            inv = jnp.zeros(p, jnp.int32).at[perm].set(arange_p)
            out[s] = (perm, inv, firstv)
        return out

    def _spread_ranks(cand, v_pc, slot_sorts):
        """rank[P, C]: among `cand` pods matching row c, this pod's
        0-based position (solve order) within its (row, value) group.
        One value-sort per spread SLOT (hoisted) + a segmented [P, C]
        cumsum with row gathers (per-row sorts serialize on TPU)."""
        act_pc = cand[:, None] & spread.pod_matches & (v_pc >= 0)  # [P, C]
        rank_pc = jnp.zeros((p, cmax_sp), jnp.int32)
        for s in features.spread_slots:
            perm, inv, firstv = slot_sorts[s]
            rows_s = spread.slot == s                            # [C]
            act_s = act_pc & rows_s[None, :]
            srt = act_s[perm].astype(jnp.int32)                  # [P, C]
            exc = jnp.cumsum(srt, axis=0) - srt                  # exclusive
            seg = exc - exc[firstv]                              # segmented
            back = seg[inv]                                      # unsort
            rank_pc = jnp.where(rows_s[None, :], back, rank_pc)
        return rank_pc

    def spread_repair(accept, nodes, sp_counts, topo_pt):
        """Keep the subset of capacity-accepted pods whose placements
        satisfy every hard constraint (rank r in its (row, value) group
        kept iff count + r + 1 - min <= maxSkew — the filtering.go:336
        criterion applied to the round's concurrent admits).  Runs
        SPREAD_REPAIR_ITERS admit passes, committing each pass's admits
        into a working copy of the counts so the global minimum rises
        WITHIN the round — without this, a round can only advance each
        constraint by maxSkew per topology value."""
        md = spread.min_domains
        kept = jnp.zeros(p, bool)
        counts_it = sp_counts
        v_pc = node_rows(v_nc, nodes)                            # [P, C]
        slot_sorts = _slot_sorts(topo_pt)
        for _ in range(SPREAD_REPAIR_ITERS):
            cand = accept & ~kept
            min_c = _pmin(jnp.min(
                jnp.where(sp0.eligible, counts_it, _BIGF), axis=-1
            ))
            min_c = jnp.where(min_c >= _BIGF, 0.0, min_c)
            min_c = jnp.where((md > 0) & (sp0.sizes < md), 0.0, min_c)
            rank_pc = _spread_ranks(cand, v_pc, slot_sorts)
            admit = cand
            for j in range(spread.pod_idx.shape[1]):
                cidx = spread.pod_idx[:, j]
                c = jnp.clip(cidx, 0, cmax_sp - 1)
                vj = v_pc[arange_p, c]
                own = cand & (cidx >= 0) & spread.hard[c] & (vj >= 0)
                cnt = node_cell_gather(counts_it, c, nodes)
                # sequential criterion: count + rank + selfMatch - min <=
                # maxSkew.  A carrier whose own labels don't match its
                # constraint's selector (selfMatch=0, legal in k8s) gets
                # one extra admit slot — releasing it at the boundary
                # would park a pod the filter just passed, forever.
                self_m = spread.pod_matches[arange_p, c].astype(jnp.float32)
                allowed = (
                    spread.max_skew[c] + min_c[c] - cnt + (1.0 - self_m)
                )
                rank = rank_pc[arange_p, c].astype(jnp.float32)
                admit = admit & ~(own & (rank >= allowed))
            kept = kept | admit
            counts_it = commit_spread(
                admit, nodes, counts_it, topo_pt, v_pc
            )
        return kept

    def interpod_repair(accept, topo_pt):
        """Release within-round anti-affinity conflicts: in each (term,
        topology value) group containing an accepted CARRIER of the term,
        only the first accepted involved pod (solve order) survives."""
        release = jnp.zeros(p, bool)
        slots_used = features.term_slots or tuple(
            range(cluster.topo_ids.shape[1])
        )
        for s in slots_used:
            v_p = topo_pt[:, s]                                  # [P]
            rel_t = slot_of_t == s                               # [T]
            inv = (mi_dense | anti_dense) & rel_t[None, :]       # [P, T]
            involved = inv & accept[:, None] & (v_p >= 0)[:, None]
            flat = (
                jnp.clip(v_p, 0, z_terms - 1)[:, None] * t_dim
                + jnp.arange(t_dim)[None, :]
            )                                                    # [P, T]
            pos = jnp.where(involved, solve_pos[:, None], _BIG_I)
            minpos = jnp.full(z_terms * t_dim, _BIG_I, jnp.int32).at[
                flat.reshape(-1)
            ].min(pos.reshape(-1))
            carrier = involved & anti_dense
            c_any = jnp.zeros(z_terms * t_dim, bool).at[
                flat.reshape(-1)
            ].max(carrier.reshape(-1))
            viol = involved & c_any[flat] & (solve_pos[:, None] > minpos[flat])
            release = release | viol.any(axis=1)
        return accept & ~release

    def commit_spread(accept, nodes, sp_counts, topo_pt, v_pc=None):
        """Fold net accepts into the node-space counts (the batched
        spread_update): every row a placed pod matches gains one on every
        node sharing the placement's topology value."""
        if v_pc is None:
            v_pc = node_rows(v_nc, nodes)                        # [P, C]
        elig_pc = node_rows(elig_nc, nodes)
        act = (
            accept[:, None] & spread.pod_matches & elig_pc & (v_pc >= 0)
        ).astype(jnp.float32)
        # Both directions ride the MXU: pod-space -> value-space counts
        # as act^T @ onehot(pod value), then value-space -> node-space
        # as adds @ onehot(node value).  The equivalent scatter-add +
        # take_along_axis each serialized at ~0.08 s per repair pass.
        # Precision.HIGHEST: spread counts are exact integers feeding the
        # exact admit criterion (count + rank + selfMatch - min <=
        # maxSkew).  Default TPU matmul precision casts to bf16, which
        # rounds counts past 256 and flips admit/release decisions.
        hi = jax.lax.Precision.HIGHEST
        adds = jnp.zeros((cmax_sp, z_spread), jnp.float32)
        zr = jnp.arange(z_spread)
        for s in features.spread_slots:
            v_p = topo_pt[:, s]                                  # [P]
            oh_pz = (
                (v_p[:, None] == zr[None, :]) & (v_p >= 0)[:, None]
            ).astype(jnp.float32)                                # [P, Z]
            rows_s = spread.slot == s                            # [C]
            act_s = act * rows_s[None, :]
            adds = adds + jnp.einsum(
                "pc,pz->cz", act_s, oh_pz, precision=hi
            )
        delta = jnp.zeros_like(sp_counts)
        for s in features.spread_slots:
            rows_s = spread.slot == s                            # [C]
            d = jnp.matmul(adds, spread_onehot[s], precision=hi)  # [C, N]
            delta = jnp.where(rows_s[:, None], d, delta)
        return sp_counts + jnp.where(sp0.v >= 0, delta, 0.0)

    def commit_terms(accept, nodes, topo_pt, present, blocked, global_any):
        """Batched interpod_update: matched terms turn present (and
        global) in each placement's topology; carried anti terms turn
        blocked there.  Scatter in value space as bools (replicated —
        built from pod-space data), then map back to LOCAL nodes and
        pack."""
        slots_used = features.term_slots or tuple(
            range(cluster.topo_ids.shape[1])
        )
        for s in slots_used:
            v_p = topo_pt[:, s]                                  # [P]
            rel_t = slot_of_t == s
            ok_p = accept & (v_p >= 0)
            vcp = jnp.clip(v_p, 0, z_terms - 1)
            mi_s = mi_dense & rel_t[None, :] & ok_p[:, None]     # [P, T]
            an_s = anti_dense & rel_t[None, :] & ok_p[:, None]
            z_mi = jnp.zeros((z_terms, t_dim), bool).at[vcp].max(mi_s)
            z_an = jnp.zeros((z_terms, t_dim), bool).at[vcp].max(an_s)
            v_n = cluster.topo_ids[:, s]                         # [N]
            vn = jnp.clip(v_n, 0, z_terms - 1)
            has = (v_n >= 0)[:, None]
            present = present | _pack_bits_t(z_mi[vn] & has)
            blocked = blocked | _pack_bits_t(z_an[vn] & has)
            global_any = global_any | _pack_bits_t(z_mi.any(axis=0))
        return present, blocked, global_any

    def body(state):
        (assigned, bid_scores, requested, nonzero, rnd, _progress,
         sp_counts, tm_present, tm_blocked, tm_global) = state
        bid, val = bids(
            requested, nonzero, assigned, rnd, sp_counts,
            (tm_present, tm_blocked, tm_global),
        )

        # Per-node prefix acceptance in solve order: pre-permute pods into
        # solve order, then a *stable* sort by bid keeps that order within
        # each node group (no composite integer key to overflow).  Bids
        # are GLOBAL node ids; pod-space state is replicated, so this
        # whole block is layout-independent except the remaining-capacity
        # gather and the requested scatter.
        perm = order[jnp.argsort(bid[order], stable=True)]
        sbid = bid[perm]
        sreq = pods.req[perm]                                   # [P, R]
        prefix = jnp.cumsum(sreq, axis=0)
        first = jnp.searchsorted(sbid, sbid, side="left")       # [P]
        within = prefix - prefix[first] + sreq[first]
        remaining = node_rows(
            cluster.allocatable - requested, jnp.clip(sbid, 0, n_total - 1)
        )
        ok = ((sreq <= 0) | (within <= remaining)).all(axis=-1) & (
            sbid < n_total
        )
        accept = jnp.zeros(p, bool).at[perm].set(ok)
        nodes = jnp.clip(bid, 0, n_total - 1)
        topo_pt = (
            node_rows(cluster.topo_ids, nodes)
            if (features.spread or features.interpod)
            else None
        )

        # constraint repair: releases only shrink the accept set, so
        # capacity stays safe; released pods re-bid next round
        pre_repair = accept
        if features.spread:
            accept = spread_repair(accept, nodes, sp_counts, topo_pt)
        if features.interpod:
            accept = interpod_repair(accept, topo_pt)
        # a round that only RELEASES still progresses: the released pods
        # re-bid under the next round's rotation and updated counts (the
        # filter now excludes the domains that capped them); max_rounds
        # bounds the loop regardless
        progress = accept.any() | (pre_repair & ~accept).any()

        requested = scatter_add_rows(requested, nodes, pods.req, accept)
        nonzero = scatter_add_rows(
            nonzero, nodes, pods.nonzero_req, accept
        )
        if features.spread:
            sp_counts = commit_spread(accept, nodes, sp_counts, topo_pt)
        if features.interpod:
            tm_present, tm_blocked, tm_global = commit_terms(
                accept, nodes, topo_pt, tm_present, tm_blocked, tm_global
            )
        assigned = jnp.where(accept, bid, assigned)
        bid_scores = jnp.where(accept, val, bid_scores)
        return (assigned, bid_scores, requested, nonzero, rnd + 1,
                progress, sp_counts, tm_present, tm_blocked, tm_global)

    def cond(state):
        assigned, _s, _r, _n, rnd, progress = state[:6]
        unplaced = ((assigned < 0) & pods.valid).any()
        return (rnd < max_rounds) & progress & unplaced

    zero = jnp.zeros(())
    init = (
        jnp.full(p, -1, jnp.int32),
        jnp.full(p, NEG_INF),
        cluster.requested,
        cluster.nonzero_requested,
        jnp.int32(0),
        jnp.bool_(True),
        sp0.counts_node if features.spread else zero,
        tm0.present_bits if features.interpod else zero,
        tm0.blocked_bits if features.interpod else zero,
        tm0.global_any if features.interpod else zero,
    )
    (assigned, bid_scores, requested, nonzero, rounds, _,
     sp_counts_f, tm_present_f, tm_blocked_f, tm_global_f) = (
        jax.lax.while_loop(cond, body, init)
    )

    # Failure reasons for unplaced pods (QueueingHints-lite): one staged
    # [C, N] filter pass against the FINAL state per class — the first
    # stage that empties the candidate set; a pod with survivors at every
    # stage parked on capacity contention/max_rounds, which requeues like
    # a resource failure.
    cl_f = cluster._replace(requested=requested, nonzero_requested=nonzero)
    sp_f = sp0._replace(counts_node=sp_counts_f) if features.spread else None
    tm_f = (
        tm0._replace(
            present_bits=tm_present_f, blocked_bits=tm_blocked_f,
            global_any=tm_global_f,
        )
        if features.interpod
        else None
    )

    fits_f_s = jax.vmap(
        lambda rep: fits_resources(cl_f, pod_view(pods, rep))
    )(s_reps)
    spf_f_k = (
        jax.vmap(
            lambda rep: spread_filter(sp_f, spread, rep, axis_name=axis_name)
        )(k_reps)
        if features.spread
        else None
    )
    ipf_f_k = (
        jax.vmap(lambda rep: interpod_filter(tm_f, terms, rep))(k_reps)
        if features.interpod
        else None
    )

    def class_reason(c, rep):
        s, k = jspec[c], jcons[c]
        s_static = sfeas_s[s]
        f = s_static & fits_f_s[s]
        a_res = _any(f)
        if features.spread:
            f = f & spf_f_k[k]
        a_spread = _any(f)
        if features.interpod:
            f = f & ipf_f_k[k]
        a_inter = _any(f)
        return jnp.where(
            a_inter, REASON_RESOURCES,  # feasible yet unplaced: contention
            jnp.where(
                ~_any(s_static), REASON_STATIC,
                jnp.where(
                    ~a_res, REASON_RESOURCES,
                    jnp.where(~a_spread, REASON_SPREAD, REASON_INTERPOD),
                ),
            ),
        ).astype(jnp.int32)

    reason_c = jax.vmap(class_reason)(
        jnp.arange(c_dim, dtype=jnp.int32), reps
    )
    cls_all = jnp.clip(pods.class_id, 0, c_dim - 1)
    reasons = jnp.where(assigned >= 0, REASON_NONE, reason_c[cls_all])

    # Gang post-pass: all-or-nothing groups.
    gang_dropped = jnp.zeros(p, bool)
    if n_groups > 0:
        g = pods.group_id
        gc = jnp.clip(g, 0, n_groups - 1)
        incomplete = jnp.zeros(n_groups, bool).at[gc].max(
            (assigned < 0) & pods.valid & (g >= 0)
        )
        gang_dropped = (g >= 0) & incomplete[gc] & (assigned >= 0)
        nodes = jnp.clip(assigned, 0, n_total - 1)
        requested = scatter_add_rows(
            requested, nodes, -pods.req, gang_dropped
        )
        nonzero = scatter_add_rows(
            nonzero, nodes, -pods.nonzero_req, gang_dropped
        )
        assigned = jnp.where(gang_dropped, -1, assigned)
        bid_scores = jnp.where(gang_dropped, NEG_INF, bid_scores)
        reasons = jnp.where(gang_dropped, REASON_GANG, reasons)

    final = cluster._replace(requested=requested, nonzero_requested=nonzero)
    return AuctionResult(
        assigned, bid_scores, rounds, gang_dropped, final, reasons,
        sp_counts_f if features.spread else None,
    )


_ = num_groups  # canonical definition lives in ops.schema (re-exported here)


def auction_assign_jit(
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: int = 0,
    max_rounds: int = 64,
):
    """Jitted closure; n_groups/features/topo_z static per executable."""

    @partial(jax.jit, static_argnums=(1, 2, 3, 4))
    def run(
        snapshot: Snapshot,
        n_groups: int,
        features: FeatureFlags,
        topo_z: Tuple[int, int],
        tie_k: int,
    ):
        return auction_assign(
            snapshot, cfg, n_groups=n_groups, tie_seed=tie_seed,
            max_rounds=max_rounds, features=features, topo_z=topo_z,
            tie_k=tie_k,
        )

    def call(
        snapshot: Snapshot,
        n_groups: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        topo_z: Optional[Tuple[int, int]] = None,
        tie_k: Optional[int] = None,
    ) -> AuctionResult:
        if features is None:
            features = features_of(snapshot)
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if topo_z is None:
            topo_z = required_topo_z_split(snapshot)
        if tie_k is None:
            tie_k = default_tie_k(snapshot)
        out = run(snapshot, n_groups, features, topo_z, tie_k)
        retrace.note(
            "auction", run,
            lambda: retrace.signature(
                snapshot, (n_groups, features, topo_z, tie_k)
            ),
        )
        return out

    return call
