"""Joint batched assignment — the auction-style parallel solve.

The greedy scan (ops.assign) preserves the reference's one-pod-at-a-time
semantics (schedule_one.go:66-133) but is inherently sequential: P scan
steps.  For large pending bursts — the gang/coscheduling config in
BASELINE — this module solves the batch *jointly* in rounds:

  1. filtering + scoring runs once per pod *class* (pods with
     byte-identical specs — schema.PodBatch.class_id — see identical
     masks and score rows, so the pass is [C, N] with C typically tens,
     not [P, N]); each class's max-score tie nodes are enumerated by
     cumsum-rank with a per-round hashed rotation (the joint analogue of
     the reference's uniform selectHost sampling, schedule_one.go:
     867-905) and the class's j-th pod bids the j-th tie node — distinct
     bids while ties last, so uniform clusters commit in bulk;
  2. each node accepts its bidders in solve order (priority, then batch
     index — queuesort/priority_sort.go:52) while they fit its remaining
     capacity, computed with one sort + segmented cumulative sum — no
     host round-trips;
  3. accepted pods commit (their resources leave the pool); rejected
     pods re-bid against the updated pool next round.

Every round in which an unplaced pod still has a feasible node commits at
least one pod (the first bidder in solve order on each node always fits),
so the loop terminates; contention bursts converge in a handful of
rounds because acceptance is per-node-parallel.

Gang semantics (all-or-nothing groups, api.PodSpec.scheduling_group):
after the rounds converge, groups with any unplaced member release all
their placements in one masked subtract — the coscheduling-PodGroup
pattern (no in-tree reference counterpart; the out-of-tree coscheduling
plugin's Permit phase is the analogue).

Constraint coverage: the static families + resources (NodeResourcesFit,
NodeName, NodeUnschedulable, TaintToleration, NodeAffinity, NodePorts
against bound pods).  Batches using topology spread, inter-pod affinity,
or in-batch host-port claims must route to the greedy scan — those
families couple concurrent placements, which is exactly what the
reference serializes for; `auction_features_ok` is the routing predicate.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .assign import (
    NEG_INF,
    FeatureFlags,
    class_statics,
    features_of,
    solve_order,
)
from .filters import fits_resources, pod_view, preferred_match, selector_match
from .schema import ClusterTensors, Snapshot, num_groups
from .scores import DEFAULT_SCORE_CONFIG, ScoreConfig, score_from_raw


class AuctionResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, -1 unschedulable/dropped
    scores: jnp.ndarray       # f32[P]: accepted bid's score (-inf if none)
    rounds: jnp.ndarray       # i32[]: bidding rounds executed
    gang_dropped: jnp.ndarray  # bool[P]: placed but released with its gang
    cluster: ClusterTensors   # post-solve cluster


def auction_features_ok(features: FeatureFlags) -> bool:
    """True when the joint solve covers this batch's constraint families."""
    return not (features.spread or features.interpod or features.ports)


def auction_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    n_groups: int = 0,
    tie_seed: int = 0,
    max_rounds: int = 64,
    features: Optional[FeatureFlags] = None,
) -> AuctionResult:
    """Jointly assign the pending batch: rounds of (parallel bid →
    per-node prefix acceptance).  n_groups: gang-group count (static;
    0 disables the gang post-pass).

    Relative to greedy, concurrent bids don't see each other's score
    impact within a round — acceptance order still respects priority,
    and capacity is never oversubscribed.  Where no two pods contend,
    round-1 bids equal the greedy picks (same filter/score kernels).
    """
    if features is None:
        features = features_of(snapshot)
    if not auction_features_ok(features):
        raise ValueError(
            "auction_assign covers static+resource families only; route "
            f"batches with {features} through greedy_assign"
        )
    cluster, pods, sel, pref = jax.tree.map(
        jnp.asarray, (snapshot.cluster, snapshot.pods, snapshot.selectors,
                      snapshot.preferred)
    )
    n = cluster.allocatable.shape[0]
    p = pods.req.shape[0]
    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    sfeas_c, aff_c, taint_c = class_statics(cluster, pods, sel_mask, pref_mask)
    c_dim = sfeas_c.shape[0]

    order = solve_order(pods)

    seed_c = jnp.uint32(tie_seed * 2 + 1)
    reps = jnp.clip(pods.class_rep, 0, p - 1)
    arange_p = jnp.arange(p, dtype=jnp.int32)

    def bids(requested, nonzero, assigned, rnd):
        # Pods of one class (byte-identical spec incl. requests) see
        # identical filter masks and score rows against the current pool,
        # so filtering + scoring runs once per *class* — [C, N] with C
        # typically tens.  Within a round the class's max-score tie set
        # is fixed, so bidding needs no per-pod (P x N) pass either: rank
        # the tie nodes once per class in counter-hash order (uniform,
        # like the reference's selectHost sampling schedule_one.go:867)
        # and hand the class's j-th active pod the j-th tie node.  Pods
        # of a class thus bid *distinct* nodes while ties last — fewer
        # conflicts than independent sampling — and the whole per-pod
        # step is O(P) gathers.
        cl = cluster._replace(requested=requested, nonzero_requested=nonzero)

        def per_class(c, rep):
            pod = pod_view(pods, rep)
            feas = sfeas_c[c] & fits_resources(cl, pod)
            scores = score_from_raw(cl, pod, feas, aff_c[c], taint_c[c], cfg)
            masked = jnp.where(feas, scores, NEG_INF)
            best = jnp.max(masked)
            tie = jnp.asarray(feas & (masked == best))
            # Tie nodes enumerated by cumsum-rank + inverse scatter (a
            # full [N] sort would dominate the round at 50k nodes); the
            # per-round hashed rotation randomizes which tie node the
            # class's first pod lands on.
            t = tie.astype(jnp.int32)
            rank = jnp.cumsum(t) - t                       # exclusive rank
            inv = jnp.full(n, n, jnp.int32).at[
                jnp.where(tie, rank, n)
            ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
            rot = (
                (c.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
                ^ (rnd.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
                ^ seed_c
            ) * jnp.uint32(0x27D4EB2F)
            return inv, t.sum(), (rot >> 8).astype(jnp.int32), best

        inv_c, cnt_c, rot_c, best_c = jax.vmap(per_class)(
            jnp.arange(c_dim, dtype=jnp.int32), reps
        )  # i32[C, N], i32[C], i32[C], f32[C]

        # Within-class position j of each active pod, in solve order (so
        # higher-priority pods take earlier tie slots).
        cls = jnp.clip(pods.class_id, 0, c_dim - 1)
        active = (assigned < 0) & pods.valid
        actkey = jnp.where(active, cls, c_dim)
        sperm = order[jnp.argsort(actkey[order], stable=True)]
        skey = actkey[sperm]
        firstpos = jnp.searchsorted(skey, skey, side="left")
        j = jnp.zeros(p, jnp.int32).at[sperm].set(
            arange_p - firstpos.astype(jnp.int32)
        )
        cnt = cnt_c[cls]
        has = active & (best_c[cls] > NEG_INF) & (cnt > 0)
        slot = (j + rot_c[cls]) % jnp.maximum(cnt, 1)
        bid = jnp.where(has, inv_c[cls, slot], n).astype(jnp.int32)
        val = jnp.where(has, best_c[cls], NEG_INF)
        return bid, val

    def body(state):
        assigned, bid_scores, requested, nonzero, rnd, _progress = state
        bid, val = bids(requested, nonzero, assigned, rnd)

        # Per-node prefix acceptance in solve order: pre-permute pods into
        # solve order, then a *stable* sort by bid keeps that order within
        # each node group (no composite integer key to overflow).
        perm = order[jnp.argsort(bid[order], stable=True)]
        sbid = bid[perm]
        sreq = pods.req[perm]                                   # [P, R]
        prefix = jnp.cumsum(sreq, axis=0)
        first = jnp.searchsorted(sbid, sbid, side="left")       # [P]
        within = prefix - prefix[first] + sreq[first]
        remaining = (cluster.allocatable - requested)[jnp.clip(sbid, 0, n - 1)]
        ok = ((sreq <= 0) | (within <= remaining)).all(axis=-1) & (sbid < n)
        accept = jnp.zeros(p, bool).at[perm].set(ok)

        nodes = jnp.clip(bid, 0, n - 1)
        w = accept[:, None].astype(jnp.float32)
        requested = requested.at[nodes].add(pods.req * w)
        nonzero = nonzero.at[nodes].add(pods.nonzero_req * w)
        assigned = jnp.where(accept, bid, assigned)
        bid_scores = jnp.where(accept, val, bid_scores)
        return (assigned, bid_scores, requested, nonzero, rnd + 1, accept.any())

    def cond(state):
        assigned, _scores, _req, _nz, rnd, progress = state
        unplaced = ((assigned < 0) & pods.valid).any()
        return (rnd < max_rounds) & progress & unplaced

    init = (
        jnp.full(p, -1, jnp.int32),
        jnp.full(p, NEG_INF),
        cluster.requested,
        cluster.nonzero_requested,
        jnp.int32(0),
        jnp.bool_(True),
    )
    assigned, bid_scores, requested, nonzero, rounds, _ = jax.lax.while_loop(
        cond, body, init
    )

    # Gang post-pass: all-or-nothing groups.
    gang_dropped = jnp.zeros(p, bool)
    if n_groups > 0:
        g = pods.group_id
        gc = jnp.clip(g, 0, n_groups - 1)
        incomplete = jnp.zeros(n_groups, bool).at[gc].max(
            (assigned < 0) & pods.valid & (g >= 0)
        )
        gang_dropped = (g >= 0) & incomplete[gc] & (assigned >= 0)
        nodes = jnp.clip(assigned, 0, n - 1)
        w = gang_dropped[:, None].astype(jnp.float32)
        requested = requested.at[nodes].add(-pods.req * w)
        nonzero = nonzero.at[nodes].add(-pods.nonzero_req * w)
        assigned = jnp.where(gang_dropped, -1, assigned)
        bid_scores = jnp.where(gang_dropped, NEG_INF, bid_scores)

    final = cluster._replace(requested=requested, nonzero_requested=nonzero)
    return AuctionResult(assigned, bid_scores, rounds, gang_dropped, final)


_ = num_groups  # canonical definition lives in ops.schema (re-exported here)


def auction_assign_jit(
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: int = 0,
    max_rounds: int = 64,
):
    """Jitted closure; n_groups/features static per executable."""

    @partial(jax.jit, static_argnums=(1, 2))
    def run(snapshot: Snapshot, n_groups: int, features: FeatureFlags):
        return auction_assign(
            snapshot, cfg, n_groups=n_groups, tie_seed=tie_seed,
            max_rounds=max_rounds, features=features,
        )

    def call(
        snapshot: Snapshot,
        n_groups: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
    ) -> AuctionResult:
        if features is None:
            features = features_of(snapshot)
        if n_groups is None:
            n_groups = num_groups(snapshot)
        return run(snapshot, n_groups, features)

    return call
