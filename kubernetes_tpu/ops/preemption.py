"""Preemption dry-run as a tensorized cumulative victim subtraction.

The reference's PostFilter (DefaultPreemption) walks candidate nodes in
parallel goroutines, dry-run-removes lower-priority pods, and picks the
least-disruptive candidate (framework/preemption/preemption.go:125-316,
plugins/defaultpreemption/default_preemption.go:345).  The TPU shape of
that loop: per candidate node, victims sorted by priority ascending, a
cumulative sum of their resource vectors, and one broadcast comparison
answering "after evicting the k cheapest victims, does the preemptor
fit?" for every (node, k) pair at once — the data-dependent dry-run loop
becomes a cumsum + argmax.

Victim-choice policy (documented divergence): we evict the k
lowest-priority pods on the node (priority ascending, pod key breaking
ties), the minimal such k.  The reference instead removes all
lower-priority pods then reprieves as many as fit back, highest-priority
first (preemption.go:
selectVictimsOnNode) — for resource-only constraints both keep the
highest-priority pods and differ only when a single high-priority
victim could replace several low-priority ones.  The pure-Python oracle
(testing/oracle.py:preempt_oracle) implements this module's policy, and
parity is asserted against it.

Candidate ranking follows pickOneNodeForPreemption's criteria order
minus PDBs (no PodDisruptionBudget API yet, stubbed at zero violations):
lowest highest-victim-priority, then lowest priority sum, then fewest
victims, then lowest node row (preemption.go:316 SelectCandidate).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DryRunResult(NamedTuple):
    feasible: jnp.ndarray   # bool[C]  pod fits after evicting min_k victims
    min_k: jnp.ndarray      # i32[C]   victims needed (only valid if feasible)


@jax.jit
def dry_run_victims(
    free: jnp.ndarray,         # f32[C, R]  allocatable - requested per candidate
    victim_req: jnp.ndarray,   # f32[C, K, R]  victims sorted by priority asc
    victim_valid: jnp.ndarray, # bool[C, K]
    pod_req: jnp.ndarray,      # f32[R]
) -> DryRunResult:
    """For each candidate node: the smallest victim prefix whose eviction
    admits the pod.  Ranking statistics (max/sum of evicted priorities)
    are computed host-side from the victim lists with exact integer math —
    Kubernetes priorities reach ~2e9, past float32's 2^24 exact-integer
    envelope, so summing them on device would mis-rank candidates."""
    c, k, r = victim_req.shape
    w = victim_valid[..., None].astype(victim_req.dtype)
    cum = jnp.cumsum(victim_req * w, axis=1)                    # [C, K, R]
    # free after evicting 0..K victims — k=0 prepended
    free_k = free[:, None, :] + jnp.concatenate(
        [jnp.zeros((c, 1, r), free.dtype), cum], axis=1
    )                                                           # [C, K+1, R]
    fits = (
        (pod_req[None, None, :] <= 0) | (pod_req[None, None, :] <= free_k)
    ).all(axis=-1)                                              # [C, K+1]
    # prefix length k is only meaningful if there ARE k valid victims
    n_victims = victim_valid.sum(axis=1)                        # [C]
    ks = jnp.arange(k + 1)[None, :]
    fits = fits & (ks <= n_victims[:, None])
    feasible = fits.any(axis=1)
    min_k = jnp.argmax(fits, axis=1).astype(jnp.int32)          # first True
    return DryRunResult(feasible, min_k)
