"""Preemption dry-run as a tensorized cumulative victim subtraction.

The reference's PostFilter (DefaultPreemption) walks candidate nodes in
parallel goroutines, dry-run-removes lower-priority pods, and picks the
least-disruptive candidate (framework/preemption/preemption.go:125-316,
plugins/defaultpreemption/default_preemption.go:345).  The TPU shape of
that loop: per candidate node, victims sorted by priority ascending, a
cumulative sum of their resource vectors, and one broadcast comparison
answering "after evicting the k cheapest victims, does the preemptor
fit?" for every (node, k) pair at once — the data-dependent dry-run loop
becomes a cumsum + argmax.

Two granularities share that shape:

  * dry_run_victims — ONE preemptor against its candidate set (the
    per-pod fallback path the solve circuit breaker routes to);
  * batched_dry_run — EVERY failed pod of a PostFilter pass against
    every node with victims, one ``[P, N, K]`` dispatch.  The per-node
    victim tensors are encoded once per pass (scheduler/preemption.py
    builds them from the same snapshot machinery the Filter/Score path
    uses); per-preemptor victim eligibility (only strictly-lower
    priorities are evictable) and the PDB-aware eviction order are
    threaded in as a per-priority-level permutation + prefix length, so
    pods sharing a priority share one row of host prep.

Victim-choice policy (documented divergence): we evict the k
lowest-priority pods on the node (priority ascending, pod key breaking
ties), the minimal such k.  The reference instead removes all
lower-priority pods then reprieves as many as fit back, highest-priority
first (preemption.go:
selectVictimsOnNode) — for resource-only constraints both keep the
highest-priority pods and differ only when a single high-priority
victim could replace several low-priority ones.  The pure-Python oracle
(testing/oracle.py Oracle.preempt) implements this module's policy, and
parity is asserted against it.

Candidate ranking follows pickOneNodeForPreemption's criteria order
INCLUDING PodDisruptionBudgets: fewest PDB-violating victims first
(minNumPDBViolatingScoreFunc, preemption.go:463), then lowest
highest-victim-priority, then lowest priority sum, then fewest victims,
then lowest node row (preemption.go:316 SelectCandidate).  The
violation counts are computed ON DEVICE by the batched kernel (viol_k —
small integers, exact in i32); the max/sum-of-priority statistics stay
host-side with exact integer math — Kubernetes priorities reach ~2e9,
past float32's 2^24 exact-integer envelope, so summing them on device
would mis-rank candidates.  PDB-violating victims sort to the BACK of
each node's eviction order (the prefix-eviction analogue of the
reference's reprieve pass, which tries hardest to KEEP PDB-violating
victims — preemption.go:198); scheduler/preemption.py computes that
order per priority level and hands it down as ``perm``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..analysis import retrace
from ..analysis.markers import hot_path


class DryRunResult(NamedTuple):
    feasible: jnp.ndarray   # bool[C]  pod fits after evicting min_k victims
    min_k: jnp.ndarray      # i32[C]  victims needed (only valid if feasible)


@jax.jit
def dry_run_victims(
    free: jnp.ndarray,         # f32[C, R]  allocatable - requested per candidate
    victim_req: jnp.ndarray,   # f32[C, K, R]  victims sorted by priority asc
    victim_valid: jnp.ndarray, # bool[C, K]
    pod_req: jnp.ndarray,      # f32[R]
) -> DryRunResult:
    """For each candidate node: the smallest victim prefix whose eviction
    admits the pod.  Ranking statistics (max/sum of evicted priorities)
    are computed host-side from the victim lists with exact integer math —
    Kubernetes priorities reach ~2e9, past float32's 2^24 exact-integer
    envelope, so summing them on device would mis-rank candidates."""
    c, k, r = victim_req.shape
    w = victim_valid[..., None].astype(victim_req.dtype)
    cum = jnp.cumsum(victim_req * w, axis=1)                    # [C, K, R]
    # free after evicting 0..K victims — k=0 prepended
    free_k = free[:, None, :] + jnp.concatenate(
        [jnp.zeros((c, 1, r), free.dtype), cum], axis=1
    )                                                           # [C, K+1, R]
    fits = (
        (pod_req[None, None, :] <= 0) | (pod_req[None, None, :] <= free_k)
    ).all(axis=-1)                                              # [C, K+1]
    # prefix length k is only meaningful if there ARE k valid victims
    n_victims = victim_valid.sum(axis=1)                        # [C]
    ks = jnp.arange(k + 1)[None, :]
    fits = fits & (ks <= n_victims[:, None])
    feasible = fits.any(axis=1)
    min_k = jnp.argmax(fits, axis=1).astype(jnp.int32)          # first True
    return DryRunResult(feasible, min_k)


# -- the batched (whole-PostFilter-pass) dry-run ---------------------------


class PreemptionBatch(NamedTuple):
    """One PostFilter pass's preemption inputs, encoded ONCE from the
    cluster state: N candidate nodes (every node holding at least one
    pod below the pass's highest preemptor priority), K victim slots per
    node sorted by (priority asc, pod key), L distinct preemptor
    priority levels, P failed pods.  ``perm``/``elig_len``/``viol``
    carry the per-level eviction order: victims evictable at level l are
    the first ``elig_len[l, n]`` entries of ``perm[l, n]``, PDB-clean
    victims first (see module docstring)."""

    free: jnp.ndarray        # f32[N, R]  allocatable - requested per node
    victim_req: jnp.ndarray  # f32[N, K, R]  usage per victim slot
    perm: jnp.ndarray        # i32[L, N, K]  eviction order per level
    elig_len: jnp.ndarray    # i32[L, N]  evictable victims per level
    viol: jnp.ndarray        # bool[L, N, K]  PDB violation, eviction order
    pods_req: jnp.ndarray    # f32[P, R]  preemptor resource vectors
    pod_level: jnp.ndarray   # i32[P]  priority-level index per preemptor


class BatchDryRunResult(NamedTuple):
    feasible: jnp.ndarray  # bool[P, N]  pod p fits on node n after min_k
    min_k: jnp.ndarray     # i32[P, N]  victims needed (valid if feasible)
    viol_k: jnp.ndarray    # i32[P, N]  PDB violations in the evicted prefix


@hot_path
def batched_dry_run(batch: PreemptionBatch) -> BatchDryRunResult:
    """Every (failed pod, candidate node) dry run of one PostFilter pass
    in one dispatch: cumulative eviction per priority level (shared by
    every pod at that level), then a ``[P, N, K+1]`` broadcast fit test.
    The PDB-violation count of each minimal prefix comes back as a
    device-side ranking axis (viol_k); exact-integer priority statistics
    stay host-side (see dry_run_victims)."""
    l, n, k = batch.perm.shape
    # victims re-ordered into each level's eviction order
    ordered = jnp.take_along_axis(
        batch.victim_req[None, :, :, :], batch.perm[..., None], axis=2
    )                                                       # [L, N, K, R]
    in_prefix = (
        jnp.arange(k, dtype=jnp.int32)[None, None, :]
        < batch.elig_len[:, :, None]
    )                                                       # [L, N, K]
    cum = jnp.cumsum(
        ordered * in_prefix[..., None].astype(ordered.dtype), axis=2
    )                                                       # [L, N, K, R]
    cum_viol = jnp.cumsum(
        (batch.viol & in_prefix).astype(jnp.int32), axis=2
    )                                                       # [L, N, K]
    # per-pod gather of its level's cumulative tensors
    cum_p = cum[batch.pod_level]                            # [P, N, K, R]
    p = cum_p.shape[0]
    r = cum_p.shape[3]
    free_k = batch.free[None, :, None, :] + jnp.concatenate(
        [jnp.zeros((p, n, 1, r), cum_p.dtype), cum_p], axis=2
    )                                                       # [P, N, K+1, R]
    req = batch.pods_req[:, None, None, :]
    fits = ((req <= 0) | (req <= free_k)).all(axis=-1)      # [P, N, K+1]
    pod_elig = batch.elig_len[batch.pod_level]              # [P, N]
    ks = jnp.arange(k + 1, dtype=jnp.int32)[None, None, :]
    fits = fits & (ks <= pod_elig[:, :, None])
    feasible = fits.any(axis=2)
    min_k = jnp.argmax(fits, axis=2).astype(jnp.int32)      # first True
    viol_at = jnp.take_along_axis(
        cum_viol[batch.pod_level],
        jnp.maximum(min_k - 1, 0)[..., None],
        axis=2,
    )[..., 0]                                               # [P, N]
    viol_k = jnp.where(min_k > 0, viol_at, 0)
    return BatchDryRunResult(feasible, min_k, viol_k)


_batched_dry_run_jit = jax.jit(batched_dry_run)


def run_batched_dry_run(batch: PreemptionBatch) -> BatchDryRunResult:
    """Dispatch the batched dry-run and report the executable key to the
    recompile-discipline tracker (the same discipline the solver jits
    follow: inputs land on the pad-bucket lattice, so the steady-state
    trace count must be zero)."""
    out = _batched_dry_run_jit(batch)
    retrace.note(
        "preempt-batch", _batched_dry_run_jit,
        lambda: retrace.signature(batch),
    )
    return out


run_batched_dry_run.jitted = _batched_dry_run_jit  # AOT prewarm hook


@hot_path
def static_feasible_batch(cluster, pods, selectors) -> jnp.ndarray:
    """bool[P, N]: the placement-independent Filter slice (NodeName /
    taints / affinity / validity) for EVERY preemptor of the pass at
    once — resources deliberately excluded, that is what eviction frees.
    One dispatch replaces the per-pod static snapshot the sequential
    path evaluates (scheduler/preemption.py _static_row_from_snap)."""
    from .filters import pod_view, selector_match, static_feasible_for_pod

    sel_mask = selector_match(cluster, selectors)
    p = pods.req.shape[0]

    def one(i):
        return static_feasible_for_pod(cluster, pod_view(pods, i), sel_mask)

    return jax.vmap(one)(jnp.arange(p, dtype=jnp.int32))


_static_feasible_jit = jax.jit(static_feasible_batch)


def run_static_feasible_batch(cluster, pods, selectors) -> jnp.ndarray:
    out = _static_feasible_jit(cluster, pods, selectors)
    retrace.note(
        "preempt-static", _static_feasible_jit,
        lambda: retrace.signature((cluster, pods, selectors)),
    )
    return out


run_static_feasible_batch.jitted = _static_feasible_jit
