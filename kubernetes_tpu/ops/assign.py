"""Batched assignment solves.

The reference schedules one pod at a time: pop, filter, score, pick, then
`assume` the pod into the cache so the next pod sees its resources
(schedule_one.go:66-133, :940-957).  `greedy_assign` reproduces exactly
those semantics inside a single compiled program: a lax.scan over the pod
axis whose carry *is* the assume bookkeeping (requested / ports updated
tensor-side between picks), so a 10k-pod batch needs one device dispatch
instead of 10k scheduling cycles.

Host round-trips per batch: one.  Selector/preferred match masks are
hoisted out of the scan — they depend only on labels, which placements
don't change.

Tie-breaking: first-max-index (deterministic).  The reference picks
uniformly at random among max-score nodes via reservoir sampling
(schedule_one.go:867-905); pass `tie_seed` to sample the same distribution
with a counter-based PRNG instead.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .filters import feasible_for_pod, pod_view, preferred_match, selector_match
from .schema import ClusterTensors, Snapshot
from .scores import DEFAULT_SCORE_CONFIG, ScoreConfig, score_for_pod

NEG_INF = jnp.float32(-jnp.inf)


class SolveResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, or -1 unschedulable
    scores: jnp.ndarray       # f32[P]: winning node's score (-inf if none)
    feasible_counts: jnp.ndarray  # i32[P]: feasible nodes seen by each pod
    cluster: ClusterTensors   # post-solve cluster (assumed placements applied)


def _pick(
    masked_scores: jnp.ndarray,
    feasible: jnp.ndarray,
    key: Optional[jax.Array],
) -> jnp.ndarray:
    """argmax with first-index ties, or uniform-among-ties when keyed
    (the reference's selectHost reservoir sampling)."""
    if key is None:
        return jnp.argmax(masked_scores)
    best = jnp.max(masked_scores)
    tie = feasible & (masked_scores == best)
    # Gumbel-max over the tie set = uniform choice among ties.
    g = jax.random.gumbel(key, masked_scores.shape)
    return jnp.argmax(jnp.where(tie, g, NEG_INF))


def greedy_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: Optional[int] = None,
) -> SolveResult:
    """Sequential-greedy solve of the whole pending batch on device.

    Semantically equivalent to running the reference's scheduling cycle
    once per pod in batch order with cache assume between cycles.
    """
    cluster, pods, sel, pref = jax.tree.map(jnp.asarray, tuple(snapshot))
    n = cluster.allocatable.shape[0]
    p = pods.req.shape[0]

    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    keys = (
        jax.random.split(jax.random.PRNGKey(tie_seed), p)
        if tie_seed is not None
        else None
    )

    def step(carry, i):
        requested, nonzero, ports = carry
        cl = cluster._replace(
            requested=requested, nonzero_requested=nonzero, port_bits=ports
        )
        pod = pod_view(pods, i)
        feas = feasible_for_pod(cl, pod, sel_mask)
        found = feas.any()
        scores = score_for_pod(cl, pod, feas, pref_mask, cfg)
        masked = jnp.where(feas, scores, NEG_INF)
        choice = _pick(masked, feas, keys[i] if keys is not None else None)
        idx = jnp.where(found, choice, -1).astype(jnp.int32)

        onehot = (jnp.arange(n) == choice) & found
        requested = requested + onehot[:, None] * pod.req[None, :]
        nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
        ports = jnp.where(onehot[:, None], ports | pod.port_bits[None, :], ports)
        out = (idx, jnp.where(found, masked[choice], NEG_INF), feas.sum().astype(jnp.int32))
        return (requested, nonzero, ports), out

    init = (cluster.requested, cluster.nonzero_requested, cluster.port_bits)
    (requested, nonzero, ports), (assignment, win_scores, feas_counts) = jax.lax.scan(
        step, init, jnp.arange(p)
    )
    final = cluster._replace(
        requested=requested, nonzero_requested=nonzero, port_bits=ports
    )
    return SolveResult(assignment, win_scores, feas_counts, final)


def greedy_assign_jit(cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """A jitted closure over the (static, hashable) score config."""

    @jax.jit
    def run(snapshot: Snapshot) -> SolveResult:
        return greedy_assign(snapshot, cfg)

    return run
