"""Batched assignment solves.

The reference schedules one pod at a time: pop, filter, score, pick, then
`assume` the pod into the cache so the next pod sees its resources
(schedule_one.go:66-133, :940-957).  `greedy_assign` reproduces exactly
those semantics inside a single compiled program: a lax.scan over the pod
axis whose carry *is* the assume bookkeeping (requested / ports updated
tensor-side between picks), so a 10k-pod batch needs one device dispatch
instead of 10k scheduling cycles.

Pods are solved in priority-then-batch-index order (the reference's
queuesort/priority_sort.go:52 pop order); results are scattered back to
input positions.

The scan step is kept minimal: everything placement-independent — the
NodeName/TaintToleration/NodeAffinity filter slice and the raw
affinity/taint score rows — is hoisted out per *pod class*
(schema.PodBatch.class_id groups pods with byte-identical static state),
so a step only re-evaluates resource fit, the carried constraint state,
and the closed-form allocation scores.

Host round-trips per batch: one.

Tie-breaking: first-max-index (deterministic).  The reference picks
uniformly at random among max-score nodes via reservoir sampling
(schedule_one.go:867-905); pass `tie_seed` to sample the same distribution
with a counter-based PRNG instead.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import retrace
from ..analysis.markers import hot_path
from .filters import (
    fits_resources,
    pod_view,
    preferred_match,
    selector_match,
    static_feasible_for_pod,
)
from .interpod import interpod_filter, interpod_update, prep_terms
from .schema import ClusterTensors, PodBatch, Snapshot, num_groups
from .scores import (
    DEFAULT_SCORE_CONFIG,
    ScoreConfig,
    node_affinity_raw,
    score_from_raw,
    taint_toleration_raw,
)
from .topology import prep_spread, spread_filter, spread_score, spread_update

NEG_INF = jnp.float32(-jnp.inf)


class FeatureFlags(NamedTuple):
    """Static gates: a workload only pays scan-step cost for the constraint
    families it actually uses (the analogue of the reference's PreFilter
    returning Skip to elide a plugin for a pod — framework.go:687)."""

    spread: bool = False       # any topology-spread constraints
    soft_spread: bool = False  # any ScheduleAnyway constraints (scoring)
    interpod: bool = False     # any inter-pod (anti-)affinity terms
    term_slots: Tuple[int, ...] = ()  # topology-key slots those terms use
    ports: bool = False        # any pending pod claims host ports (the
                               # dynamic port-conflict carry; the static
                               # check against bound pods is always on)
    interpod_aff: bool = False  # any AFFINITY-direction terms (the
                               # co-location + first-pod-escape family;
                               # the joint auction covers anti-affinity
                               # only, so this gates its routing)
    spread_slots: Tuple[int, ...] = ()  # topology-key slots spread rows use
    interpod_pref: bool = False  # any preferred (scoring) interpod terms
    images: bool = False         # any pending pod names a known image
    # Whether any BOUND pod contributes to each family's count tables.
    # Static so the preps' value-space scatter+gather folds away at
    # trace time when the tables are zero — they arrive as runtime
    # device arrays, so XLA cannot discover zero-ness on its own, and
    # the folded-out gathers are ~0.3 s/solve at 32k nodes.
    bound_spread: bool = False
    bound_terms: bool = False
    bound_pref: bool = False
    # TPU slice-topology carve-outs (ops/slices.py): active when shaped
    # pods meet a slice-labelled cluster.  slice_z/slice_dim size the
    # value-space grid [S, D, D, D] (static, like topo_z they are part
    # of the executable key); slice_require flips the carve-out
    # preference into a filter (the prefer-vs-require config knob).
    slices: bool = False
    slice_require: bool = False
    slice_z: int = 1
    slice_dim: int = 1


def required_topo_z(snapshot: Snapshot) -> int:  # graftlint: disable=purity -- host-side prep on the pre-transfer snapshot
    """Smallest valid topo-value capacity for this snapshot.  Using a
    smaller z would alias topology values together in the prep-time count
    scatter and silently corrupt spread/inter-pod state."""
    from ..utils.vocab import pad_dim

    return pad_dim(int(np.asarray(snapshot.cluster.topo_ids).max()) + 1, 1)


def required_topo_z_split(snapshot: Snapshot) -> Tuple[int, int]:  # graftlint: disable=purity -- host-side prep on the pre-transfer snapshot
    """(z_spread, z_terms): value capacities sized to the topology slots
    each family actually uses.  Hostname ids scale with the cluster (50k
    nodes → 50k values) while zone/region stay tiny; sizing each family's
    value-space buffers to ITS slots keeps a zone-spread batch's scatters
    at z≈64 instead of z≈cluster-size."""
    from ..utils.vocab import pad_dim

    topo = np.asarray(snapshot.cluster.topo_ids)

    def z_for(slots) -> int:
        if len(slots) == 0:
            return 1
        return pad_dim(int(topo[:, sorted(slots)].max()) + 1, 1)

    spread_valid = np.asarray(snapshot.spread.valid)
    spread_slots = set(np.asarray(snapshot.spread.slot)[spread_valid].tolist())
    term_valid = np.asarray(snapshot.terms.valid)
    term_slots = set(np.asarray(snapshot.terms.slot)[term_valid].tolist())
    pref_valid = np.asarray(snapshot.prefpod.valid)
    term_slots |= set(np.asarray(snapshot.prefpod.slot)[pref_valid].tolist())
    return z_for(spread_slots), z_for(term_slots)


def needs_topo(features: FeatureFlags) -> bool:
    """True when the solve carries any topology-value state — spread,
    required inter-pod terms, or PREFERRED inter-pod terms (forgetting
    the last aliased every domain to value 0 and silently zeroed the
    preferred-affinity scores on the dispatch path)."""
    return features.spread or features.interpod or features.interpod_pref


def features_of(  # graftlint: disable=purity -- host-side prep: cheap numpy reductions on the pre-transfer snapshot
    snapshot: Snapshot, no_bound_pods: bool = False,
    slice_policy: str = "prefer",
) -> FeatureFlags:
    """Derive the static gates host-side (cheap numpy reductions).

    no_bound_pods: the caller knows the cluster holds zero bound pods
    (ClusterState._pods empty), so the bound-count tables are zeros by
    construction — skips full scans of the largest snapshot arrays
    (tens of MB each at 20k+ nodes) on the per-batch encode path.

    slice_policy: the carve-out knob ("prefer" | "require" | "off",
    SchedulerConfiguration.slice_carveout_policy) — the slice family
    arms only when shaped pods meet a slice-labelled cluster AND the
    policy isn't off."""
    from ..utils.vocab import pad_dim

    spread_valid = np.asarray(snapshot.spread.valid)
    hard = np.asarray(snapshot.spread.hard)
    term_valid = np.asarray(snapshot.terms.valid)
    slots = np.asarray(snapshot.terms.slot)
    if no_bound_pods:
        bound_spread = bound_terms = bound_pref = False
    else:
        bound_spread = bool(np.asarray(snapshot.spread.node_matches).any())
        bound_terms = bool(
            np.asarray(snapshot.terms.node_matches).any()
            or np.asarray(snapshot.terms.node_owners).any()
        )
        bound_pref = bool(
            np.asarray(snapshot.prefpod.node_counts).any()
            or np.asarray(snapshot.prefpod.owner_weight).any()
        )
    shapes = np.asarray(snapshot.pods.pod_shape)
    sids = np.asarray(snapshot.cluster.slice_id)
    slices_on = (
        slice_policy != "off"
        and bool((shapes.prod(axis=1) > 0).any())
        and bool((sids >= 0).any())
    )
    if slices_on:
        slice_z = pad_dim(int(sids.max()) + 1, 1)
        slice_dim = pad_dim(
            max(int(np.asarray(snapshot.cluster.slice_dims).max()), 1), 1
        )
    else:
        slice_z = slice_dim = 1
    return FeatureFlags(
        spread=bool(spread_valid.any()),
        soft_spread=bool((spread_valid & ~hard).any()),
        interpod=bool(term_valid.any()),
        term_slots=tuple(sorted(set(slots[term_valid].tolist()))),
        ports=bool(np.asarray(snapshot.pods.port_bits).any()),
        interpod_aff=bool((np.asarray(snapshot.terms.aff_idx) >= 0).any()),
        spread_slots=tuple(
            sorted(set(np.asarray(snapshot.spread.slot)[spread_valid].tolist()))
        ),
        interpod_pref=bool(np.asarray(snapshot.prefpod.valid).any()),
        images=bool(
            (np.asarray(snapshot.images.pod_ids) >= 0).any()
            and np.asarray(snapshot.cluster.image_bits).any()
        ),
        bound_spread=bound_spread,
        bound_terms=bound_terms,
        bound_pref=bound_pref,
        slices=slices_on,
        slice_require=slices_on and slice_policy == "require",
        slice_z=slice_z,
        slice_dim=slice_dim,
    )


# Failure-reason codes: the FIRST filter stage that emptied the pod's
# candidate set.  The queue's event-scoped requeue (QueueingHints-lite)
# keys off these — e.g. an AssignedPodDelete frees resources but cannot
# fix a node-affinity mismatch, so REASON_STATIC pods stay parked
# (internal/queue/events.go's event→plugin map, reduced to stages).
REASON_NONE = -1      # placed
REASON_STATIC = 0     # NodeName/affinity/taints/validity (+ bound ports)
REASON_RESOURCES = 1  # NodeResourcesFit
REASON_PORTS = 2      # in-batch host-port conflicts
REASON_SPREAD = 3     # PodTopologySpread (hard)
REASON_INTERPOD = 4   # InterPodAffinity (required)
REASON_GANG = 5       # placed individually but released with its gang
REASON_UNENCODABLE = 6  # spec exceeds encoder caps / unsupported field —
                        # only a pod UPDATE can help; no event wakes it
REASON_SLICE = 7      # slice carve-out (require mode): no free contiguous
                      # sub-cuboid / anchored cuboid exhausted


def _axis_any(x: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    """Global `.any()` over the node axis: local under a single chip, an
    OR across shards (pmax of the local any) under shard_map."""
    if axis_name is None:
        return x.any()
    return jax.lax.pmax(x.any().astype(jnp.int32), axis_name) > 0


def _shard_layout(axis_name: Optional[str], n: int):
    """Node-axis layout helpers shared by the greedy/wavefront solvers —
    identity under a single chip, ownership-masked collectives under
    shard_map (the ops.auction idiom: one implementation, two layouts).

    Returns ``(offset, n_total, node_rows, node_col)``: `offset` is the
    shard's first global row, `n_total` the GLOBAL node count (psum of a
    constant folds to the static axis size, so it stays a Python int),
    ``node_rows(mat, idx)`` gathers rows of a node-major tensor at
    GLOBAL node ids (the owning shard contributes, psum replicates), and
    ``node_col(mat, idx)`` broadcasts the column of a [R, N] tensor at
    one GLOBAL id."""
    if axis_name is None:
        return 0, n, (lambda mat, idx: mat[idx]), (lambda mat, idx: mat[:, idx])
    offset = jax.lax.axis_index(axis_name) * n
    n_total = n * jax.lax.psum(1, axis_name)

    def node_rows(mat, idx):
        own = (idx >= offset) & (idx < offset + n)
        loc = jnp.clip(idx - offset, 0, n - 1)
        vals = mat[loc]
        mask = own.reshape(own.shape + (1,) * (vals.ndim - own.ndim))
        if vals.dtype == jnp.bool_:
            return jax.lax.psum(
                jnp.where(mask, vals, False).astype(jnp.int32), axis_name
            ) > 0
        return jax.lax.psum(
            jnp.where(mask, vals, jnp.zeros_like(vals)), axis_name
        )

    def node_col(mat, idx):
        own = (idx >= offset) & (idx < offset + n)
        loc = jnp.clip(idx - offset, 0, n - 1)
        col = mat[:, loc]
        if col.dtype == jnp.bool_:
            return jax.lax.psum(
                jnp.where(own, col, False).astype(jnp.int32), axis_name
            ) > 0
        return jax.lax.psum(
            jnp.where(own, col, jnp.zeros_like(col)), axis_name
        )

    return offset, n_total, node_rows, node_col


def _elect(masked: jnp.ndarray, offset, axis_name: str):
    """Global argmax election under shard_map: local champion, then a
    pmax/pmin pair picks (best score, lowest global index) — the
    first-max-index tie-break of the single-chip argmax, exactly.
    Returns (global index i32, best value)."""
    li = jnp.argmax(masked)
    lv = masked[li]
    best = jax.lax.pmax(lv, axis_name)
    cand = jnp.where(
        lv == best, (offset + li).astype(jnp.int32), jnp.int32(2 ** 31 - 1)
    )
    return jax.lax.pmin(cand, axis_name), best


class SolveResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, or -1 unschedulable
    scores: jnp.ndarray       # f32[P]: winning node's score (-inf if none)
    feasible_counts: jnp.ndarray  # i32[P]: feasible nodes seen by each pod
    cluster: ClusterTensors   # post-solve cluster (assumed placements applied)
    reasons: jnp.ndarray = None   # i32[P]: REASON_* for unplaced pods
    # wavefront-path telemetry (None on the classic scan): executed wave
    # count and fallback count (serialized waves + per-pod full re-evals)
    wave_count: jnp.ndarray = None      # i32[]
    wave_fallbacks: jnp.ndarray = None  # i32[]
    # slice carve-out telemetry (None unless features.slices): post-solve
    # cluster fragmentation and per-gang carve-out outcomes
    frag_score: jnp.ndarray = None          # f32[]
    carveouts: jnp.ndarray = None           # i32[]
    contiguous_gangs: jnp.ndarray = None    # i32[]
    carveout_fallbacks: jnp.ndarray = None  # i32[]


def class_statics(
    cluster: ClusterTensors,
    pods: PodBatch,
    sel_mask: jnp.ndarray,
    pref_mask: jnp.ndarray,
    reps: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-class hoisted tables: (static_feas[C, N], aff_raw[C, N],
    taint_raw[C, N]).  One row per static-equivalence class, computed from
    its representative pod; the scan gathers rows by class_id.  The static
    feasibility folds in the port check against *initial* (bound-pod)
    port claims; in-batch port conflicts ride the dynamic carry.

    reps: representative-pod indices to evaluate (defaults to the joint
    class_rep).  The auction passes pods.spec_rep — static state depends
    only on the spec factor, so the heavy label/taint row kernels run
    once per spec class (see PodBatch's factorization note)."""
    p = pods.req.shape[0]
    if reps is None:
        reps = jnp.clip(pods.class_rep, 0, p - 1)

    def one(rep):
        pod = pod_view(pods, rep)
        sfeas = static_feasible_for_pod(cluster, pod, sel_mask) & ~(
            (cluster.port_bits & pod.port_bits[None, :]).any(axis=-1)
        )
        return (
            sfeas,
            node_affinity_raw(pod, pref_mask),
            taint_toleration_raw(cluster, pod),
        )

    return jax.vmap(one)(reps)


def solve_order(pods: PodBatch) -> jnp.ndarray:
    """Priority-then-batch-index pop order (queuesort/priority_sort.go:52:
    higher priority first, earlier arrival breaking ties).  Stable argsort
    on negated priority ≡ lexicographic (-priority, index)."""
    return jnp.argsort(-pods.priority, stable=True).astype(jnp.int32)


def _pick(
    masked_scores: jnp.ndarray,
    feasible: jnp.ndarray,
    key: Optional[jax.Array],
) -> jnp.ndarray:
    """argmax with first-index ties, or uniform-among-ties when keyed
    (the reference's selectHost reservoir sampling)."""
    if key is None:
        return jnp.argmax(masked_scores)
    best = jnp.max(masked_scores)
    tie = feasible & (masked_scores == best)
    # Gumbel-max over the tie set = uniform choice among ties.
    g = jax.random.gumbel(key, masked_scores.shape)
    return jnp.argmax(jnp.where(tie, g, NEG_INF))


def _eval_pod(
    cl: ClusterTensors,
    pods: PodBatch,
    i: jnp.ndarray,
    cls: jnp.ndarray,
    sfeas_c: jnp.ndarray,
    aff_c: jnp.ndarray,
    taint_c: jnp.ndarray,
    extra_c: Optional[jnp.ndarray],
    new_ports,
    sp,
    tm,
    spread,
    terms,
    features: FeatureFlags,
    cfg: ScoreConfig,
    axis_name: Optional[str] = None,
    gang_sl: Optional[jnp.ndarray] = None,
    gang_lo: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The Filter+Score half of one scheduling step for pod i against the
    given carry state: (feas[N], masked_scores[N], found, reason,
    feasible_count).  Shared verbatim by the classic scan step, the
    wavefront pre-evaluation, and the wavefront's exact re-evaluation
    fallback, so the three paths cannot drift apart.

    gang_sl/gang_lo: the slice carve-out carry ([G] anchored slice id,
    [G, 3] carved corner) when features.slices and gangs are present —
    the carve-out family (ops/slices.py) filters (require mode) and
    score-biases (both modes) shaped pods toward contiguous sub-cuboids.

    Under shard_map (axis_name set) the node tensors hold one shard:
    feas/masked stay local while the per-stage anys, the feasible count,
    and the score normalization maxima span shards — found/reason/count
    come back replicated."""
    pod = pod_view(pods, i)
    s_static = sfeas_c[cls]
    s_any = _axis_any(s_static, axis_name)
    feas = s_static & fits_resources(cl, pod)
    a_res = _axis_any(feas, axis_name)
    if features.ports:
        feas = feas & ~((new_ports & pod.port_bits[None, :]).any(axis=-1))
    a_ports = _axis_any(feas, axis_name)
    if features.spread:
        feas = feas & spread_filter(sp, spread, i, axis_name=axis_name)
    a_spread = _axis_any(feas, axis_name)
    if features.interpod:
        feas = feas & interpod_filter(tm, terms, i)
    s_bonus = None
    if features.slices:
        from .slices import carveout_eval

        s_bonus, s_ok = carveout_eval(
            cl, pods, i, gang_sl, gang_lo, features, axis_name=axis_name
        )
        if features.slice_require:
            a_interpod = _axis_any(feas, axis_name)
            feas = feas & s_ok
    found = _axis_any(feas, axis_name)
    # first stage whose filter emptied the candidate set
    last = (
        jnp.where(~a_interpod, REASON_INTERPOD, REASON_SLICE)
        if features.slices and features.slice_require
        else REASON_INTERPOD
    )
    reason = jnp.where(
        found, REASON_NONE,
        jnp.where(
            ~s_any, REASON_STATIC,
            jnp.where(
                ~a_res, REASON_RESOURCES,
                jnp.where(
                    ~a_ports, REASON_PORTS,
                    jnp.where(~a_spread, REASON_SPREAD, last),
                ),
            ),
        ),
    ).astype(jnp.int32)
    sp_score = (
        spread_score(sp, spread, i, feas, axis_name=axis_name)
        if features.soft_spread
        else None
    )
    scores = score_from_raw(
        cl, pod, feas, aff_c[cls], taint_c[cls], cfg, axis_name=axis_name,
        spread_score=sp_score,
        extra=extra_c[cls] if extra_c is not None else None,
    )
    if s_bonus is not None:
        # the carve-out family rides OUTSIDE the normalized base sum:
        # exact-integer bonuses large enough that contiguous placements
        # rank strictly above fragmenting ones (ops/slices.py weights)
        scores = scores + s_bonus
    masked = jnp.where(feas, scores, NEG_INF)
    cnt = feas.sum().astype(jnp.int32)
    if axis_name is not None:
        cnt = jax.lax.psum(cnt, axis_name)
    return feas, masked, found, reason, cnt


def _solver_prep(
    snapshot: Snapshot, cfg: ScoreConfig, topo_z: int, features: FeatureFlags,
    axis_name: Optional[str] = None, statics=None,
):
    """Per-batch device prep shared by the scan and wavefront solvers:
    materialized tensors, class-hoisted static tables, and the spread /
    inter-pod prep states (the PreFilter/PreScore analogue).  Under
    shard_map the hoisted tables cover the local node shard; the
    value-space count preps and normalizers span shards via psum/pmax
    inside prep_spread/prep_terms/static_extra.

    statics: a precomputed (sfeas, aff, taint) triple
    (ops.partials.ClassStatics) warm-started from the device-resident
    PartialsCache — bit-identical to what class_statics would compute
    here (the cache's parity gate pins it), so the whole [C, N]
    selector/taint/affinity re-evaluation is skipped.  The selector
    mask is still computed when the spread family needs it
    (prep_spread's owner-eligibility input)."""
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]
    p = pods.req.shape[0]

    if statics is None:
        sel_mask = selector_match(cluster, sel)
        pref_mask = preferred_match(cluster, pref)
        sfeas_c, aff_c, taint_c = class_statics(
            cluster, pods, sel_mask, pref_mask
        )
    else:
        sfeas_c = jnp.asarray(statics.sfeas)
        aff_c = jnp.asarray(statics.aff)
        taint_c = jnp.asarray(statics.taint)
        sel_mask = (
            selector_match(cluster, sel) if features.spread else None
        )
    c_dim = sfeas_c.shape[0]
    extra_c = None
    if features.interpod_pref or features.images:
        # Hoisted per-class static score extras: preferred inter-pod
        # affinity (counts from BOUND pods at prep — scoring.go PreScore
        # over the cycle snapshot; in-batch placements don't attract
        # later batchmates within this solve, documented divergence, and
        # the normalization set is the class's static-feasible nodes) and
        # ImageLocality (image presence never changes mid-solve).
        from .interpod import prep_pref_pod
        from .scores import static_extra

        pp = (
            prep_pref_pod(
                cluster, prefpod, topo_z, axis_name=axis_name,
                has_bound=features.bound_pref,
            )
            if features.interpod_pref
            else None
        )
        reps_e = jnp.clip(pods.class_rep, 0, p - 1)
        extra_c = jax.vmap(
            lambda c, rep: static_extra(
                cluster, prefpod, images, features, cfg, rep, sfeas_c[c], pp,
                axis_name=axis_name,
            )
        )(jnp.arange(c_dim, dtype=jnp.int32), reps_e)
    sp0 = (
        prep_spread(
            cluster, sel_mask, spread, topo_z, axis_name=axis_name,
            has_bound=features.bound_spread,
        )
        if features.spread
        else None
    )
    tm0 = (
        prep_terms(
            cluster, terms, topo_z, axis_name=axis_name,
            slots=features.term_slots, has_bound=features.bound_terms,
        )
        if features.interpod
        else None
    )
    return (cluster, pods, spread, terms, sfeas_c, aff_c, taint_c, extra_c,
            sp0, tm0, c_dim, n, p)


def _gang_release(
    assignment, win_scores, reasons, requested, nonzero, pods, n_groups, n,
    offset=0,
):
    """All-or-nothing gang post-pass shared by the scan and wavefront
    solvers: release every placement of a group with an unplaced member.
    Only requested/nonzero need subtracting: ports and spread/interpod
    counts are rebuilt from *actually bound* pods at the next batch's
    prep, and the host never assumes released members.

    `n` is the LOCAL node count and `offset` the shard's first global
    row under shard_map (0 single-chip): each shard subtracts only the
    released rows it owns — out-of-window scatter targets drop."""
    g = pods.group_id
    gc = jnp.clip(g, 0, n_groups - 1)
    incomplete = jnp.zeros(n_groups, bool).at[gc].max(
        (assignment < 0) & pods.valid & (g >= 0)
    )
    dropped = (g >= 0) & incomplete[gc] & (assignment >= 0)
    tgt = jnp.where(
        dropped & (assignment >= offset) & (assignment < offset + n),
        assignment - offset, n,
    )
    w = dropped[:, None].astype(jnp.float32)
    requested = requested.at[tgt].add(-pods.req * w)
    nonzero = nonzero.at[tgt].add(-pods.nonzero_req * w)
    assignment = jnp.where(dropped, -1, assignment)
    win_scores = jnp.where(dropped, NEG_INF, win_scores)
    reasons = jnp.where(dropped, REASON_GANG, reasons)
    return assignment, win_scores, reasons, requested, nonzero


@hot_path
def greedy_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: Optional[int] = None,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    axis_name: Optional[str] = None,
    statics=None,
) -> SolveResult:
    """Sequential-greedy solve of the whole pending batch on device.

    Semantically equivalent to running the reference's scheduling cycle
    once per pod in priority order with cache assume between cycles — the
    scan carry holds everything a placement changes: resource usage,
    in-batch port claims, topology-spread counts, and inter-pod affinity
    term state.

    topo_z: padded topology-value vocab size (SnapshotMeta.topo_z or
    required_topo_z); auto-derived when None.  Both topo_z and features
    can only be auto-derived outside jit — jitted callers must pass them
    (greedy_assign_jit's wrapper does).

    n_groups (static): gang-group count.  When > 0, groups with any
    unplaced member release every placement after the scan (all-or-nothing,
    the coscheduling-PodGroup contract) — this is what lets gangs carrying
    spread/interpod/port constraints keep gang semantics instead of
    routing-away to a solver that drops them.  Later in-scan pods saw the
    released placements' resource/count impact (conservative: they may
    park and retry next batch); the released members return as
    unschedulable (-1).

    axis_name: mesh axis when called under shard_map with the NODE axis
    sharded (parallel.sharded.sharded_greedy_assign) — one
    implementation, two layouts, like ops.auction: pod-space state is
    replicated, node-space state sharded, the per-step election is a
    pmax/pmin pair, and constraint updates broadcast the winning node's
    column from its owning shard.  Placements are bit-identical to the
    single-chip scan (first-max-index resolves to the lowest global node
    index in both layouts).  Keyed (tie_seed) solves are single-chip
    only: reservoir sampling needs the full gumbel tie set per step."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    if axis_name is not None and tie_seed is not None:
        raise ValueError("keyed (tie_seed) solves are single-chip only")
    (cluster, pods, spread, terms, sfeas_c, aff_c, taint_c, extra_c,
     sp0, tm0, c_dim, n, p) = _solver_prep(
        snapshot, cfg, topo_z, features, axis_name=axis_name,
        statics=statics,
    )
    offset, n_total, node_rows, node_col = _shard_layout(axis_name, n)
    order = solve_order(pods)
    keys = (
        jax.random.split(jax.random.PRNGKey(tie_seed), p)
        if tie_seed is not None
        else None
    )
    # slice carve-out carry: per-gang anchored slice + carved corner
    # (written by the gang's first placed member, read by the rest)
    use_gang_carve = features.slices and n_groups > 0

    def step(carry, k):
        (requested, nonzero, new_ports, sp_counts, tm_present, tm_blocked,
         tm_global, gang_sl, gang_lo, gang_corner) = carry
        i = order[k]
        cl = cluster._replace(requested=requested, nonzero_requested=nonzero)
        pod = pod_view(pods, i)
        cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
        sp = tm = None
        if features.spread:
            sp = sp0._replace(counts_node=sp_counts)
        if features.interpod:
            tm = tm0._replace(
                present_bits=tm_present, blocked_bits=tm_blocked, global_any=tm_global
            )
        feas, masked, found, reason, feas_cnt = _eval_pod(
            cl, pods, i, cls, sfeas_c, aff_c, taint_c, extra_c,
            new_ports, sp, tm, spread, terms, features, cfg,
            axis_name=axis_name,
            gang_sl=gang_sl if use_gang_carve else None,
            gang_lo=gang_lo if use_gang_carve else None,
        )
        if axis_name is None:
            choice = _pick(masked, feas, keys[k] if keys is not None else None)
            win_val = masked[choice]
        else:
            choice, win_val = _elect(masked, offset, axis_name)
        idx = jnp.where(found, choice, -1).astype(jnp.int32)

        onehot = ((jnp.arange(n) + offset) == choice) & found
        requested = requested + onehot[:, None] * pod.req[None, :]
        nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
        if features.ports:
            new_ports = jnp.where(
                onehot[:, None], new_ports | pod.port_bits[None, :], new_ports
            )
        if features.spread:
            sp = spread_update(
                sp, spread, i, node_col(sp.v, choice),
                node_col(sp.eligible, choice), found,
            )
            sp_counts = sp.counts_node
        if features.interpod:
            tm = interpod_update(
                tm, terms, i, node_rows(cluster.topo_ids, choice), found,
                slots=features.term_slots,
            )
            tm_present, tm_blocked, tm_global = (
                tm.present_bits, tm.blocked_bits, tm.global_any
            )
        if use_gang_carve:
            from .slices import corner_mask as _corner_mask
            from .slices import free_devices as _free_devices

            g = pods.group_id[i]
            gc = jnp.clip(g, 0, n_groups - 1)
            shaped = pods.pod_shape[i].prod() > 0
            ch_sid = node_rows(cluster.slice_id, choice)
            ch_xyz = node_rows(cluster.torus_coords, choice)[:3]
            # was the anchor a genuine free-box corner (pre-placement
            # carry state)?  Drives the contiguous-vs-fallback counters:
            # a prefer-mode anchor dropped on a non-corner can still
            # cluster its members, but the REQUESTED carve-out was not
            # realized
            corner_n = _corner_mask(
                cl, _free_devices(cl), pods.pod_shape[i],
                features.slice_z, features.slice_dim, axis_name=axis_name,
            )
            ch_corner = node_rows(corner_n, choice)
            new_anchor = found & (g >= 0) & shaped & (gang_sl[gc] < 0)
            gang_sl = gang_sl.at[gc].set(
                jnp.where(new_anchor, ch_sid, gang_sl[gc])
            )
            gang_lo = gang_lo.at[gc].set(
                jnp.where(new_anchor, ch_xyz, gang_lo[gc])
            )
            gang_corner = gang_corner.at[gc].set(
                jnp.where(new_anchor, ch_corner, gang_corner[gc])
            )
        out = (i, idx, jnp.where(found, win_val, NEG_INF),
               feas_cnt, reason)
        carry = (requested, nonzero, new_ports, sp_counts, tm_present,
                 tm_blocked, tm_global, gang_sl, gang_lo, gang_corner)
        return carry, out

    zero = jnp.zeros(())
    init = (
        cluster.requested,
        cluster.nonzero_requested,
        jnp.zeros_like(cluster.port_bits) if features.ports else zero,
        sp0.counts_node if features.spread else zero,
        tm0.present_bits if features.interpod else zero,
        tm0.blocked_bits if features.interpod else zero,
        tm0.global_any if features.interpod else zero,
        jnp.full(n_groups, -1, jnp.int32) if use_gang_carve else zero,
        jnp.full((n_groups, 3), -1, jnp.int32) if use_gang_carve else zero,
        jnp.zeros(n_groups, bool) if use_gang_carve else zero,
    )
    (
        (requested, nonzero, new_ports, _sp_c, _tm_p, _tm_b, _tm_g,
         gang_sl_f, gang_lo_f, gang_corner_f),
        (pod_is, assign_o, win_o, feas_o, reason_o),
    ) = jax.lax.scan(step, init, jnp.arange(p))
    # Scatter scan outputs (priority order) back to batch positions.
    assignment = jnp.full(p, -1, jnp.int32).at[pod_is].set(assign_o)
    win_scores = jnp.full(p, NEG_INF).at[pod_is].set(win_o)
    feas_counts = jnp.zeros(p, jnp.int32).at[pod_is].set(feas_o)
    reasons = jnp.full(p, REASON_NONE, jnp.int32).at[pod_is].set(reason_o)

    # Gang post-pass: all-or-nothing release, mirroring ops.auction's
    # post-pass (shared with the wavefront solver via _gang_release).
    if n_groups > 0:
        assignment, win_scores, reasons, requested, nonzero = _gang_release(
            assignment, win_scores, reasons, requested, nonzero,
            pods, n_groups, n, offset=offset,
        )

    final = cluster._replace(
        requested=requested,
        nonzero_requested=nonzero,
        port_bits=(cluster.port_bits | new_ports) if features.ports
        else cluster.port_bits,
    )
    frag = carveouts = contiguous = fallbacks = None
    if features.slices:
        from .slices import fragmentation

        frag = fragmentation(
            final, features.slice_z, features.slice_dim,
            axis_name=axis_name,
        ).score
        carveouts = jnp.int32(0)
        contiguous = jnp.int32(0)
        fallbacks = jnp.int32(0)
        if use_gang_carve:
            # carve-out telemetry over the POST-RELEASE assignment:
            # anchored = the gang carved a box; complete = every shaped
            # member placed; contiguous = complete with every member
            # inside its box (require mode makes complete ⇒ contiguous)
            g = pods.group_id
            gc = jnp.clip(g, 0, n_groups - 1)
            member = pods.valid & (g >= 0) & (pods.pod_shape.prod(-1) > 0)
            any_member = jnp.zeros(n_groups, bool).at[gc].max(member)
            unplaced = jnp.zeros(n_groups, bool).at[gc].max(
                member & (assignment < 0)
            )
            complete = any_member & ~unplaced
            a = jnp.clip(assignment, 0, n_total - 1)
            a_sid = node_rows(cluster.slice_id, a)           # i32[P]
            a_xyz = node_rows(cluster.torus_coords, a)[:, :3]
            lo = gang_lo_f[gc]
            in_cub = (
                (a_sid == gang_sl_f[gc])
                & (a_xyz >= lo).all(-1)
                & (a_xyz < lo + pods.pod_shape).all(-1)
            )
            out_of_cub = jnp.zeros(n_groups, bool).at[gc].max(
                member & (assignment >= 0) & ~in_cub
            )
            anchored = (gang_sl_f >= 0) & any_member
            carveouts = anchored.sum().astype(jnp.int32)
            contiguous = (
                (complete & anchored & gang_corner_f & ~out_of_cub)
                .sum().astype(jnp.int32)
            )
            fallbacks = complete.sum().astype(jnp.int32) - contiguous
    return SolveResult(
        assignment, win_scores, feas_counts, final, reasons,
        frag_score=frag, carveouts=carveouts,
        contiguous_gangs=contiguous, carveout_fallbacks=fallbacks,
    )


def greedy_assign_jit(cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """A jitted closure over the (static, hashable) score config.
    topo_z and the feature gates are static: one executable per
    (shape-bucket, topo_z, features).  Features are auto-detected
    host-side when not supplied.

    `statics` (ops.partials.ClassStatics) selects the WARM twin: a
    distinct executable (three extra [C, N] operands, no in-program
    selector/taint/affinity re-evaluation) warm-started from the
    device-resident PartialsCache — the incremental O(changes) solve."""

    @partial(jax.jit, static_argnums=(1, 2, 3))
    def run(
        snapshot: Snapshot, topo_z: int, features: FeatureFlags, n_groups: int
    ) -> SolveResult:
        return greedy_assign(
            snapshot, cfg, topo_z=topo_z, features=features, n_groups=n_groups
        )

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run_warm(
        snapshot: Snapshot, statics, topo_z: int, features: FeatureFlags,
        n_groups: int,
    ) -> SolveResult:
        return greedy_assign(
            snapshot, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            # topo_z only shapes spread/inter-pod prep state; pinning it
            # to 1 when no family is active keeps the jit cache key
            # stable as topology vocabularies grow.
            topo_z = required_topo_z(snapshot) if needs_topo(features) else 1
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            # Bucket to a power of two: n_groups is a static jit arg, and
            # the post-pass clips, so padding costs nothing but stabilizes
            # the executable cache as gang counts vary batch to batch.
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if statics is not None:
            out = run_warm(snapshot, statics, topo_z, features, n_groups)
            retrace.note(
                "greedy-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, statics), (topo_z, features, n_groups)
                ),
            )
            return out
        out = run(snapshot, topo_z, features, n_groups)
        retrace.note(
            "greedy", run,
            lambda: retrace.signature(snapshot, (topo_z, features, n_groups)),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


# -- wavefront greedy -------------------------------------------------------
#
# The scan above pays one sequential device step per pod.  The wavefront
# solver partitions the priority-ordered batch into WAVES and pays one
# heavy step per wave: the [K, N] Filter+Score evaluation of all wave
# members runs batched against the wave-start carry, and the sequential
# decisions inside the wave run in an O(K) mini-scan that only *corrects*
# the precomputed scores at nodes picked earlier in the wave (the
# allocation scores are the only usage-dependent score family, and they
# are per-node closed forms).  Exact one-pod-at-a-time semantics are
# preserved:
#
#   * Wave membership guarantees no dynamic coupling: pairwise-disjoint
#     host-port bits and no spread/inter-pod row written by an earlier
#     member that a later member reads.  The device re-verifies this
#     (ports/spread/term pairwise masks) and serializes the whole wave
#     through the original step body when the partitioner got it wrong —
#     ANY contiguous partition of the solve order is therefore correct.
#   * Within a safe wave, a member's sequential score vector differs from
#     its wave-start vector only at nodes picked earlier in the wave, so
#     the mini-scan compares the corrected picked-node scores against the
#     best unpicked candidate from a precomputed top-(K+1) list —
#     first-max-index tie-breaks included (lax.top_k is index-stable).
#   * Resource tightening that FLIPS a member's fit at a picked node
#     would change its feasible set (and the score normalization over
#     it), so that member falls back to an exact full re-evaluation
#     against the live carry inside its mini-step (lax.cond — the rare
#     branch costs nothing when untaken).
#
# Gang all-or-nothing rides the same shared post-pass.  Keyed (tie_seed)
# solves stay on the classic scan — reservoir sampling needs the full
# gumbel tie set per step.

DEFAULT_WAVE_CAP = 32


class WavePlan(NamedTuple):
    """Host-side wave partition of one batch (plan_waves)."""

    members: np.ndarray  # i32[W_pad, K] pod indices in solve order, -1 pad
    n_waves: int         # real (non-empty) wave count


def _pack_idx_rows(idx: np.ndarray, dim: int) -> np.ndarray:
    """i32[P, M] index lists (-1 pad) -> packed u32[P, words] membership."""
    p = idx.shape[0]
    words = max(1, (dim + 31) // 32)
    out = np.zeros((p, words), dtype=np.uint32)
    rows, vals = np.nonzero(idx >= 0)
    ids = idx[rows, vals]
    # the shift count must be u32: `np.uint32(1) << (i32 & 31)` promotes
    # the whole expression to i64 under NumPy 2 (a tensor-contract
    # bitset-widening true positive)
    np.bitwise_or.at(
        out, (rows, ids >> 5), np.uint32(1) << (ids & 31).astype(np.uint32)
    )
    return out


def plan_waves(  # graftlint: disable=purity -- host-side prep: the wave partition walks host numpy (module docstring)
    snapshot: Snapshot,
    features: Optional[FeatureFlags] = None,
    wave_cap: int = DEFAULT_WAVE_CAP,
    headroom_frac: float = 1.0,
) -> WavePlan:
    """Partition the solve order into conflict-free waves (host numpy).

    A pod joins the open wave unless one of these would break:
      * size: the wave already holds `wave_cap` members;
      * ports: its host-port bits intersect a member's (the in-wave port
        carry must stay untouched for wave members);
      * spread/terms: a wave member WRITES a constraint row this pod
        READS (spread: pod_matches vs pod_idx; terms: matches_incoming ∪
        anti vs matches_incoming ∪ anti ∪ aff) — count/bit drift inside
        the wave would break the wave-start evaluation;
      * headroom: aggregate wave demand would exceed `headroom_frac` of
        the emptiest node's free capacity (elementwise) — a heuristic
        that keeps per-member fit-flip fallbacks rare, not a correctness
        condition (the device detects flips exactly).

    The partition is a pure performance hint: wavefront_assign re-checks
    coupling on device and serializes unsafe waves, so any output of this
    function yields placements identical to the scan."""
    from ..utils.vocab import pad_dim

    if features is None:
        features = features_of(snapshot)
    pods = snapshot.pods
    priority = np.asarray(pods.priority)
    p = priority.shape[0]
    order = np.argsort(-priority, kind="stable").astype(np.int32)

    use_ports = bool(features.ports)
    use_spread = bool(features.spread or features.soft_spread)
    use_terms = bool(features.interpod)
    port_bits = np.asarray(pods.port_bits) if use_ports else None
    if use_spread:
        sp_idx = np.asarray(snapshot.spread.pod_idx)
        reads_sp = _pack_idx_rows(sp_idx, np.asarray(snapshot.spread.valid).shape[0])
        pm = np.asarray(snapshot.spread.pod_matches)
        writes_sp = np.packbits(
            pm, axis=1, bitorder="little"
        )
        # pad packbits' u8 words up to the u32 row width of reads_sp
        w32 = reads_sp.shape[1] * 4
        if writes_sp.shape[1] < w32:
            writes_sp = np.pad(writes_sp, ((0, 0), (0, w32 - writes_sp.shape[1])))
        writes_sp = writes_sp[:, :w32].copy().view(np.uint32)
    if use_terms:
        t_dim = np.asarray(snapshot.terms.valid).shape[0]
        mi = np.asarray(snapshot.terms.matches_incoming)
        anti = _pack_idx_rows(np.asarray(snapshot.terms.anti_idx), t_dim)
        aff = _pack_idx_rows(np.asarray(snapshot.terms.aff_idx), t_dim)
        w = min(mi.shape[1], anti.shape[1])
        writes_tm = mi[:, :w] | anti[:, :w]
        reads_tm = writes_tm | aff[:, :w]

    req = np.asarray(pods.req)
    alloc = np.asarray(snapshot.cluster.allocatable)
    used = np.asarray(snapshot.cluster.requested)
    valid = np.asarray(snapshot.cluster.node_valid)
    free = np.where(valid[:, None], alloc - used, 0.0)
    slack = free.max(axis=0) * float(headroom_frac)

    waves: List[List[int]] = []
    cur: List[int] = []
    port_acc = None if not use_ports else np.zeros_like(port_bits[0])
    sp_acc = None if not use_spread else np.zeros_like(writes_sp[0])
    tm_acc = None if not use_terms else np.zeros_like(writes_tm[0])
    # f32, matching the schema's request dtype: an f64 accumulator here
    # promoted every downstream `demand + req[i]` comparison to f64 (a
    # tensor-contract finding), and request quantities stay inside f32's
    # exact-integer envelope by construction (schema.F32_EXACT_LIMIT)
    demand = np.zeros(req.shape[1], dtype=np.float32)

    def close():
        nonlocal cur, port_acc, sp_acc, tm_acc, demand
        if cur:
            waves.append(cur)
        cur = []
        if use_ports:
            port_acc = np.zeros_like(port_bits[0])
        if use_spread:
            sp_acc = np.zeros_like(writes_sp[0])
        if use_terms:
            tm_acc = np.zeros_like(writes_tm[0])
        demand = np.zeros(req.shape[1], dtype=np.float32)

    for i in order.tolist():
        conflict = len(cur) >= wave_cap
        if not conflict and cur:
            if use_ports and (port_acc & port_bits[i]).any():
                conflict = True
            elif use_spread and (sp_acc & reads_sp[i]).any():
                conflict = True
            elif use_terms and (tm_acc & reads_tm[i]).any():
                conflict = True
            elif ((demand + req[i]) > slack).any():
                conflict = True
        if conflict:
            close()
        cur.append(i)
        if use_ports:
            port_acc |= port_bits[i]
        if use_spread:
            sp_acc |= writes_sp[i]
        if use_terms:
            tm_acc |= writes_tm[i]
        demand += req[i]
    close()

    n_waves = len(waves)
    w_pad = pad_dim(max(n_waves, 1), 8)
    members = np.full((w_pad, wave_cap), -1, dtype=np.int32)
    for wi, wv in enumerate(waves):
        members[wi, : len(wv)] = wv
    return WavePlan(members=members, n_waves=n_waves)


def _rows_cluster(cap, requested, nonzero):
    """A K-row stand-in ClusterTensors for the per-node allocation score
    recomputes (resource_score_parts only touches these three fields)."""
    return ClusterTensors(
        allocatable=cap, requested=requested, nonzero_requested=nonzero,
        node_valid=None, name_id=None, label_bits=None, taint_bits=None,
        port_bits=None, topo_ids=None, image_bits=None, slice_id=None,
        torus_coords=None, slice_dims=None, slice_pos=None,
    )


@hot_path
def wavefront_assign(
    snapshot: Snapshot,
    wave_members: jnp.ndarray,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    axis_name: Optional[str] = None,
    statics=None,
    pod_axis_name: Optional[str] = None,
) -> SolveResult:
    """Wave-parallel greedy solve with exact scan parity (see module
    section comment).  wave_members: i32[W, K] pod indices covering every
    batch position in solve order (-1 pads), from plan_waves.

    pod_axis_name: mesh axis when called under shard_map with the POD
    axis sharded (parallel.sharded.podsharded_wavefront_assign) — the
    twin of the node-axis layout for wide-wave batches: node tables stay
    replicated, wave_members arrives K-sharded, and each device runs the
    heavy batched [K, N] evaluation only for its K/D member slice; one
    all_gather per wave rebuilds the full [K, N] score block, after
    which the top-(K+1), wave-safety, and O(K) mini-scan math runs
    replicated-identically on every device (node offset 0, no
    elections).  Placements are bit-identical to the single-shard
    wavefront.  Mutually exclusive with axis_name.

    axis_name: mesh axis when called under shard_map with the NODE axis
    sharded (parallel.sharded.sharded_wavefront_assign).  The batched
    [K, N] evaluation and the O(K) mini-scan both keep the node tensors
    sharded: each shard pre-evaluates its node shard and takes a local
    top-(K+1), an all_gather merges the per-shard candidate lists into
    the global top-(K+1) (equal scores resolve to the lowest global
    index in both layouts, so the merge is tie-stable), the mini-scan's
    picked-node score corrections run on ownership-masked psum-gathered
    rows (replicated, so every shard reaches the same choice with no
    further election), and only the rare fit-flip / serialized-wave
    fallbacks pay a per-pod pmax/pmin election.  Placements are
    bit-identical to the single-chip scan."""
    from .scores import resource_score_parts

    if features is None:
        features = features_of(snapshot)
    if features.slices:
        # every shaped pod writes the free mask that every other shaped
        # pod's corner evaluation reads — wave-start evaluation cannot
        # hold; TPUBatchScheduler._route keeps these on the classic scan
        raise ValueError(
            "slice carve-out batches (features.slices) route to the "
            "classic greedy scan, not the wavefront solver"
        )
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    (cluster, pods, spread, terms, sfeas_c, aff_c, taint_c, extra_c,
     sp0, tm0, c_dim, n, p) = _solver_prep(
        snapshot, cfg, topo_z, features, axis_name=axis_name,
        statics=statics,
    )
    offset, n_total, node_rows, node_col = _shard_layout(axis_name, n)
    wave_members = jnp.asarray(wave_members, jnp.int32)
    if pod_axis_name is not None:
        if axis_name is not None:
            raise ValueError(
                "axis_name (node shard) and pod_axis_name (pod shard) "
                "are mutually exclusive in one wavefront call"
            )
        # wave_members arrives K-sharded: rebuild the full [W, K] plan
        # once up front (shard-major reshape matches shard_map's
        # contiguous blocks; psum of a constant folds to the static
        # axis size, so k_dim stays a Python int)
        d_pods = jax.lax.psum(1, pod_axis_name)
        k_local = wave_members.shape[1]
        wave_members = jnp.moveaxis(
            jax.lax.all_gather(wave_members, pod_axis_name), 0, 1
        ).reshape(wave_members.shape[0], k_local * d_pods)
    k_dim = wave_members.shape[1]
    # local and GLOBAL top-(K+1) widths: each shard's list must be wide
    # enough that the merged global list still holds the best unpicked
    # candidate after up to K in-wave picks
    kk = min(k_dim + 1, n)
    kk_g = min(k_dim + 1, n_total)
    arange_k = jnp.arange(k_dim, dtype=jnp.int32)

    # per-pod coupling rows for the device-side wave-safety check
    if features.interpod:
        t_dim = terms.valid.shape[0]
        from .interpod import _idx_to_bits, _pack_bits_t

        anti_w = _pack_bits_t(_idx_to_bits(terms.anti_idx, t_dim))
        aff_w = _pack_bits_t(_idx_to_bits(terms.aff_idx, t_dim))
        tw = min(terms.matches_incoming.shape[1], anti_w.shape[1])
        tm_writes = terms.matches_incoming[:, :tw] | anti_w[:, :tw]
        tm_reads = tm_writes | aff_w[:, :tw]
    if features.spread or features.soft_spread:
        c_rows = spread.valid.shape[0]
        sp_reads_all = (
            jnp.arange(c_rows)[None, None, :] == spread.pod_idx[:, :, None]
        ).any(axis=1)  # bool[P, C]

    def wave_safe(mk, mvalid):
        """True when no member writes dynamic state an in-wave successor
        reads — the conflict-detection pass.  mk: clipped member ids."""
        tri = (arange_k[:, None] < arange_k[None, :]) & (
            mvalid[:, None] & mvalid[None, :]
        )
        ok = jnp.bool_(True)
        if features.ports:
            pb = pods.port_bits[mk]  # [K, PW]
            hit = (pb[:, None, :] & pb[None, :, :]).any(-1)
            ok = ok & ~(tri & hit).any()
        if features.spread or features.soft_spread:
            wr = spread.pod_matches[mk]  # [K, C]
            rd = sp_reads_all[mk]
            hit = (wr[:, None, :] & rd[None, :, :]).any(-1)
            ok = ok & ~(tri & hit).any()
        if features.interpod:
            wr = tm_writes[mk]
            rd = tm_reads[mk]
            hit = (wr[:, None, :] & rd[None, :, :]).any(-1)
            ok = ok & ~(tri & hit).any()
        return ok

    def wave_step(carry, members):
        (requested, nonzero, new_ports, sp_counts,
         tm_present, tm_blocked, tm_global, n_fb, n_waves) = carry
        mvalid = members >= 0
        mk = jnp.clip(members, 0, p - 1)
        req0, nz0 = requested, nonzero
        cl0 = cluster._replace(requested=requested, nonzero_requested=nonzero)
        sp = tm = None
        if features.spread:
            sp = sp0._replace(counts_node=sp_counts)
        if features.interpod:
            tm = tm0._replace(
                present_bits=tm_present, blocked_bits=tm_blocked,
                global_any=tm_global,
            )

        def run_wave(_):
            # heavy half, batched: every member evaluated from the
            # wave-start carry in one vectorized pass
            def eval_one(i):
                cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
                _, masked, found, reason, cnt = _eval_pod(
                    cl0, pods, i, cls, sfeas_c, aff_c, taint_c, extra_c,
                    new_ports, sp, tm, spread, terms, features, cfg,
                    axis_name=axis_name,
                )
                return masked, found, reason, cnt

            if pod_axis_name is None:
                masked_k, found_k, reason_k, cnt_k = jax.vmap(eval_one)(mk)
            else:
                # pod-axis twin: each device evaluates only its K/D
                # member slice against the replicated node tables; one
                # all_gather rebuilds the full [K, N] block, and every
                # shard runs the identical downstream math
                k_loc = k_dim // d_pods
                mk_l = jax.lax.dynamic_slice_in_dim(
                    mk, jax.lax.axis_index(pod_axis_name) * k_loc, k_loc
                )
                m_l, f_l, r_l, c_l = jax.vmap(eval_one)(mk_l)
                masked_k = jax.lax.all_gather(
                    m_l, pod_axis_name
                ).reshape(k_dim, -1)
                found_k = jax.lax.all_gather(
                    f_l, pod_axis_name
                ).reshape(k_dim)
                reason_k = jax.lax.all_gather(
                    r_l, pod_axis_name
                ).reshape(k_dim)
                cnt_k = jax.lax.all_gather(
                    c_l, pod_axis_name
                ).reshape(k_dim)
            topv, topi = jax.lax.top_k(masked_k, kk)
            if axis_name is not None:
                # merge the per-shard top-(K+1) lists into the global
                # one: all_gather stacks shard-major, so the flattened
                # candidate order is (shard, local rank) — equal values
                # resolve to the lowest global node index, exactly the
                # single-chip top_k tie order
                vg = jax.lax.all_gather(topv, axis_name)           # [D, K, kk]
                ig = jax.lax.all_gather(topi + offset, axis_name)  # [D, K, kk]
                vg = jnp.moveaxis(vg, 0, 1).reshape(k_dim, -1)
                ig = jnp.moveaxis(ig, 0, 1).reshape(k_dim, -1)
                topv, pos = jax.lax.top_k(vg, kk_g)
                topi = jnp.take_along_axis(ig, pos, axis=1)

            def fast(_):
                def mini(mc, j):
                    req_c, nz_c, picked, fb = mc
                    i = mk[j]
                    valid_j = mvalid[j]
                    pod = pod_view(pods, i)
                    cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
                    prev = (arange_k < j) & (picked >= 0)
                    # picked holds GLOBAL node ids; sharded, the row
                    # gathers below replicate the K picked rows to every
                    # shard so the correction math (and the choice) is
                    # identical everywhere — no per-pod election needed
                    pxc = jnp.clip(picked, 0, n_total - 1)
                    cap_rows = node_rows(cluster.allocatable, pxc)
                    req0_rows = node_rows(req0, pxc)
                    reqc_rows = node_rows(req_c, pxc)
                    skip = (pod.req[None, :] <= 0)
                    fits0 = (
                        skip | (req0_rows + pod.req[None, :] <= cap_rows)
                    ).all(-1)
                    fitsc = (
                        skip | (reqc_rows + pod.req[None, :] <= cap_rows)
                    ).all(-1)
                    flip = (
                        prev & node_rows(sfeas_c[cls], pxc)
                        & (fits0 != fitsc)
                    ).any() & valid_j

                    def full(_):
                        # exact re-evaluation against the live carry:
                        # ports/spread/terms are wave-start but untouched
                        # within a safe wave, so this IS the sequential
                        # state
                        clj = cluster._replace(
                            requested=req_c, nonzero_requested=nz_c
                        )
                        _, masked, found, reason, cnt = _eval_pod(
                            clj, pods, i, cls, sfeas_c, aff_c, taint_c,
                            extra_c, new_ports, sp, tm, spread, terms,
                            features, cfg, axis_name=axis_name,
                        )
                        found = found & valid_j
                        if axis_name is None:
                            choice = jnp.argmax(masked).astype(jnp.int32)
                            win = jnp.where(found, masked[choice], NEG_INF)
                        else:
                            choice, best = _elect(masked, offset, axis_name)
                            win = jnp.where(found, best, NEG_INF)
                        return (choice, win, cnt, reason, found,
                                jnp.int32(1))

                    def cheap(_):
                        # sequential scores differ from the wave-start
                        # vector only at picked nodes, and only in the
                        # (un-normalized) allocation parts — correct
                        # those entries in closed form
                        fit0, bal0 = resource_score_parts(
                            _rows_cluster(cap_rows, req0_rows,
                                          node_rows(nz0, pxc)),
                            pod, cfg,
                        )
                        fitc, balc = resource_score_parts(
                            _rows_cluster(cap_rows, reqc_rows,
                                          node_rows(nz_c, pxc)),
                            pod, cfg,
                        )
                        d_alloc = (
                            cfg.fit_weight * (fitc - fit0)
                            + cfg.balanced_weight * (balc - bal0)
                        )
                        base = node_rows(masked_k[j], pxc)
                        cand_ok = prev & (base > NEG_INF)
                        cand_val = base + d_alloc
                        tv, ti = topv[j], topi[j]
                        ispicked = (
                            (ti[:, None] == pxc[None, :]) & prev[None, :]
                        ).any(-1)
                        un_ok = ~ispicked & (tv > NEG_INF)
                        first = jnp.argmax(un_ok)
                        has_un = un_ok.any()
                        bu_val = jnp.where(has_un, tv[first], NEG_INF)
                        bu_idx = jnp.where(has_un, ti[first], n_total).astype(
                            jnp.int32
                        )
                        vals = jnp.concatenate(
                            [jnp.where(cand_ok, cand_val, NEG_INF),
                             bu_val[None]]
                        )
                        idxs = jnp.concatenate([pxc, bu_idx[None]])
                        best = jnp.max(vals)
                        found = found_k[j] & valid_j & (best > NEG_INF)
                        # first-max-index over the candidate union ==
                        # first-max-index over the corrected [N] vector
                        choice = jnp.min(
                            jnp.where((vals >= best) & (vals > NEG_INF),
                                      idxs, n_total)
                        ).astype(jnp.int32)
                        return (
                            choice, jnp.where(found, best, NEG_INF),
                            cnt_k[j], reason_k[j], found, jnp.int32(0),
                        )

                    choice, win, cnt, reason, found, used_full = (
                        jax.lax.cond(flip, full, cheap, None)
                    )
                    cc = jnp.clip(choice, 0, n_total - 1)
                    if axis_name is None:
                        tgt = cc
                    else:
                        # the owning shard's local row; everyone else
                        # scatters out of bounds (dropped)
                        in_sh = (cc >= offset) & (cc < offset + n)
                        tgt = jnp.where(in_sh, cc - offset, n)
                    wgt = found.astype(req_c.dtype)
                    req_c = req_c.at[tgt].add(pod.req * wgt)
                    nz_c = nz_c.at[tgt].add(pod.nonzero_req * wgt)
                    picked = picked.at[j].set(jnp.where(found, cc, -1))
                    out = (jnp.where(found, cc, -1).astype(jnp.int32),
                           win, cnt, reason)
                    return (req_c, nz_c, picked, fb + used_full), out

                (req2, nz2, picked, fb), (a_k, w_k, c_k, r_k) = jax.lax.scan(
                    mini,
                    (requested, nonzero,
                     jnp.full(k_dim, -1, jnp.int32), jnp.int32(0)),
                    arange_k,
                )
                # deferred dynamic-state updates: no member read these, so
                # they commit batched at wave end (adds/ORs commute)
                ports2 = new_ports
                if features.ports:
                    okp = picked >= 0
                    if axis_name is None:
                        tgt = jnp.where(okp, picked, n)  # OOB rows drop
                    else:
                        own = okp & (picked >= offset) & (
                            picked < offset + n
                        )
                        tgt = jnp.where(own, picked - offset, n)
                    bits = pods.port_bits[mk] * okp[:, None].astype(
                        jnp.uint32
                    )
                    ports2 = new_ports.at[tgt].add(bits)
                spc2 = sp_counts
                if features.spread:
                    # unrolled so XLA fuses the K count-updates into one
                    # pass over [C, N] instead of K carried array writes
                    st = sp0._replace(counts_node=sp_counts)
                    for j in range(k_dim):
                        ch = jnp.clip(a_k[j], 0, n_total - 1)
                        st = spread_update(
                            st, spread, mk[j], node_col(st.v, ch),
                            node_col(st.eligible, ch), a_k[j] >= 0,
                        )
                    spc2 = st.counts_node
                pr2, bl2, ga2 = tm_present, tm_blocked, tm_global
                if features.interpod:
                    st = tm0._replace(
                        present_bits=tm_present, blocked_bits=tm_blocked,
                        global_any=tm_global,
                    )
                    for j in range(k_dim):
                        ch = jnp.clip(a_k[j], 0, n_total - 1)
                        st = interpod_update(
                            st, terms, mk[j], node_rows(cluster.topo_ids, ch),
                            a_k[j] >= 0, slots=features.term_slots,
                        )
                    pr2, bl2, ga2 = (
                        st.present_bits, st.blocked_bits, st.global_any
                    )
                return ((req2, nz2, ports2, spc2, pr2, bl2, ga2, fb),
                        (a_k, w_k, c_k, r_k))

            def serial(_):
                # unsafe wave (in-wave coupling): run the original scan
                # step over the members — exact by construction
                def sstep(c, j):
                    (req_c, nz_c, ports_c, spc, pr, bl, ga) = c
                    i = mk[j]
                    valid_j = mvalid[j]
                    clj = cluster._replace(
                        requested=req_c, nonzero_requested=nz_c
                    )
                    spj = tmj = None
                    if features.spread:
                        spj = sp0._replace(counts_node=spc)
                    if features.interpod:
                        tmj = tm0._replace(
                            present_bits=pr, blocked_bits=bl, global_any=ga
                        )
                    cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
                    pod = pod_view(pods, i)
                    _, masked, found, reason, cnt = _eval_pod(
                        clj, pods, i, cls, sfeas_c, aff_c, taint_c,
                        extra_c, ports_c, spj, tmj, spread, terms,
                        features, cfg, axis_name=axis_name,
                    )
                    found = found & valid_j
                    if axis_name is None:
                        choice = jnp.argmax(masked).astype(jnp.int32)
                        win = jnp.where(found, masked[choice], NEG_INF)
                    else:
                        choice, best = _elect(masked, offset, axis_name)
                        win = jnp.where(found, best, NEG_INF)
                    cc = jnp.clip(choice, 0, n_total - 1)
                    onehot = ((jnp.arange(n) + offset) == cc) & found
                    wgt = found.astype(req_c.dtype)
                    req_c = req_c + onehot[:, None] * pod.req[None, :] * wgt
                    nz_c = (
                        nz_c + onehot[:, None] * pod.nonzero_req[None, :] * wgt
                    )
                    if features.ports:
                        ports_c = jnp.where(
                            onehot[:, None], ports_c | pod.port_bits[None, :],
                            ports_c,
                        )
                    if features.spread:
                        spj = spread_update(
                            spj, spread, i, node_col(spj.v, cc),
                            node_col(spj.eligible, cc), found,
                        )
                        spc = spj.counts_node
                    if features.interpod:
                        tmj = interpod_update(
                            tmj, terms, i, node_rows(cluster.topo_ids, cc),
                            found, slots=features.term_slots,
                        )
                        pr, bl, ga = (
                            tmj.present_bits, tmj.blocked_bits,
                            tmj.global_any,
                        )
                    out = (jnp.where(found, cc, -1).astype(jnp.int32),
                           win, cnt, reason)
                    return (req_c, nz_c, ports_c, spc, pr, bl, ga), out

                (req2, nz2, ports2, spc2, pr2, bl2, ga2), outs = (
                    jax.lax.scan(
                        sstep,
                        (requested, nonzero, new_ports, sp_counts,
                         tm_present, tm_blocked, tm_global),
                        arange_k,
                    )
                )
                return ((req2, nz2, ports2, spc2, pr2, bl2, ga2,
                         mvalid.sum().astype(jnp.int32)), outs)

            safe = wave_safe(mk, mvalid)
            (req2, nz2, ports2, spc2, pr2, bl2, ga2, fb), outs = (
                jax.lax.cond(safe, fast, serial, None)
            )
            return ((req2, nz2, ports2, spc2, pr2, bl2, ga2,
                     n_fb + fb, n_waves + 1), outs)

        def skip_wave(_):
            outs = (
                jnp.full(k_dim, -1, jnp.int32),
                jnp.full(k_dim, NEG_INF),
                jnp.zeros(k_dim, jnp.int32),
                jnp.full(k_dim, REASON_NONE, jnp.int32),
            )
            return ((requested, nonzero, new_ports, sp_counts, tm_present,
                     tm_blocked, tm_global, n_fb, n_waves), outs)

        new_carry, outs = jax.lax.cond(
            mvalid.any(), run_wave, skip_wave, None
        )
        return new_carry, outs

    zero = jnp.zeros(())
    init = (
        cluster.requested,
        cluster.nonzero_requested,
        jnp.zeros_like(cluster.port_bits) if features.ports else zero,
        sp0.counts_node if features.spread else zero,
        tm0.present_bits if features.interpod else zero,
        tm0.blocked_bits if features.interpod else zero,
        tm0.global_any if features.interpod else zero,
        jnp.int32(0),
        jnp.int32(0),
    )
    (requested, nonzero, new_ports, *_rest, n_fb, n_waves), (
        assign_w, win_w, cnt_w, reason_w
    ) = jax.lax.scan(wave_step, init, wave_members)

    flat_members = wave_members.reshape(-1)
    pod_is = jnp.where(flat_members >= 0, flat_members, p)  # OOB drop
    assignment = jnp.full(p, -1, jnp.int32).at[pod_is].set(
        assign_w.reshape(-1)
    )
    win_scores = jnp.full(p, NEG_INF).at[pod_is].set(win_w.reshape(-1))
    feas_counts = jnp.zeros(p, jnp.int32).at[pod_is].set(cnt_w.reshape(-1))
    reasons = jnp.full(p, REASON_NONE, jnp.int32).at[pod_is].set(
        reason_w.reshape(-1)
    )

    if n_groups > 0:
        assignment, win_scores, reasons, requested, nonzero = _gang_release(
            assignment, win_scores, reasons, requested, nonzero,
            pods, n_groups, n, offset=offset,
        )

    final = cluster._replace(
        requested=requested,
        nonzero_requested=nonzero,
        port_bits=(cluster.port_bits | new_ports) if features.ports
        else cluster.port_bits,
    )
    return SolveResult(
        assignment, win_scores, feas_counts, final, reasons,
        wave_count=n_waves, wave_fallbacks=n_fb,
    )


def wavefront_assign_jit(cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """Jitted wavefront solver: one executable per (shape-bucket, topo_z,
    features, n_groups, wave shape).  The wave plan is a device argument
    (i32[W, K]) so repartitions of the same shapes reuse the executable."""

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run(
        snapshot: Snapshot, wave_members, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return wavefront_assign(
            snapshot, wave_members, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(3, 4, 5))
    def run_warm(
        snapshot: Snapshot, wave_members, statics, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return wavefront_assign(
            snapshot, wave_members, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        wave_members=None,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        wave_cap: int = DEFAULT_WAVE_CAP,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = required_topo_z(snapshot) if needs_topo(features) else 1
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if wave_members is None:
            wave_members = plan_waves(
                snapshot, features=features, wave_cap=wave_cap
            ).members
        members = jnp.asarray(wave_members, jnp.int32)
        if statics is not None:
            out = run_warm(snapshot, members, statics, topo_z, features,
                           n_groups)
            retrace.note(
                "wavefront-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, members, statics),
                    (topo_z, features, n_groups),
                ),
            )
            return out
        out = run(snapshot, members, topo_z, features, n_groups)
        retrace.note(
            "wavefront", run,
            lambda: retrace.signature(
                (snapshot, members), (topo_z, features, n_groups)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


@hot_path
def evaluate_single(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(feasible[N], scores[N]) for pod 0 of the snapshot — the full
    Filter + Score chain with no placement (what an extender's
    filter/prioritize verbs need: the node SET, not one pick).

    Same kernels the solvers use: static filters + resources + spread +
    inter-pod affinity; scores are the weighted normalized sum
    (runtime/framework.go RunScorePlugins semantics)."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot) if needs_topo(features) else 1
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    from .interpod import interpod_filter, pref_pod_raw, prep_pref_pod, prep_terms
    from .topology import prep_spread, spread_filter, spread_score

    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    pod = pod_view(pods, 0)
    feas = static_feasible_for_pod(cluster, pod, sel_mask) & ~(
        (cluster.port_bits & pod.port_bits[None, :]).any(axis=-1)
    )
    feas = feas & fits_resources(cluster, pod)
    sp_score = None
    if features.spread:
        sp = prep_spread(
            cluster, sel_mask, spread, topo_z,
            has_bound=features.bound_spread,
        )
        feas = feas & spread_filter(sp, spread, 0)
        if features.soft_spread:
            sp_score = spread_score(sp, spread, 0, feas)
    if features.interpod:
        tm = prep_terms(
            cluster, terms, topo_z, slots=features.term_slots,
            has_bound=features.bound_terms,
        )
        feas = feas & interpod_filter(tm, terms, 0)
    s_bonus = None
    if features.slices:
        # single-pod view: anchor semantics only (no gang carry)
        from .slices import carveout_eval

        s_bonus, s_ok = carveout_eval(
            cluster, pods, 0, None, None, features
        )
        if features.slice_require:
            feas = feas & s_ok
    extra = None
    if features.interpod_pref or features.images:
        from .scores import static_extra

        pp = (
            prep_pref_pod(
                cluster, prefpod, topo_z, has_bound=features.bound_pref
            )
            if features.interpod_pref
            else None
        )
        extra = static_extra(
            cluster, prefpod, images, features, cfg, 0, feas, pp
        )
    scores = score_from_raw(
        cluster, pod, feas,
        node_affinity_raw(pod, pref_mask),
        taint_toleration_raw(cluster, pod),
        cfg, spread_score=sp_score, extra=extra,
    )
    if s_bonus is not None:
        scores = scores + s_bonus
    return feas, jnp.where(feas, scores, NEG_INF)
