"""Batched assignment solves.

The reference schedules one pod at a time: pop, filter, score, pick, then
`assume` the pod into the cache so the next pod sees its resources
(schedule_one.go:66-133, :940-957).  `greedy_assign` reproduces exactly
those semantics inside a single compiled program: a lax.scan over the pod
axis whose carry *is* the assume bookkeeping (requested / ports updated
tensor-side between picks), so a 10k-pod batch needs one device dispatch
instead of 10k scheduling cycles.

Host round-trips per batch: one.  Selector/preferred match masks are
hoisted out of the scan — they depend only on labels, which placements
don't change.

Tie-breaking: first-max-index (deterministic).  The reference picks
uniformly at random among max-score nodes via reservoir sampling
(schedule_one.go:867-905); pass `tie_seed` to sample the same distribution
with a counter-based PRNG instead.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .filters import feasible_for_pod, pod_view, preferred_match, selector_match
from .interpod import interpod_filter, interpod_update, prep_terms
from .schema import ClusterTensors, Snapshot
from .scores import DEFAULT_SCORE_CONFIG, ScoreConfig, score_for_pod
from .topology import prep_spread, spread_filter, spread_score, spread_update

NEG_INF = jnp.float32(-jnp.inf)


class FeatureFlags(NamedTuple):
    """Static gates: a workload only pays scan-step cost for the constraint
    families it actually uses (the analogue of the reference's PreFilter
    returning Skip to elide a plugin for a pod — framework.go:687)."""

    spread: bool = False       # any topology-spread constraints
    soft_spread: bool = False  # any ScheduleAnyway constraints (scoring)
    interpod: bool = False     # any inter-pod (anti-)affinity terms
    term_slots: Tuple[int, ...] = ()  # topology-key slots those terms use


def required_topo_z(snapshot: Snapshot) -> int:
    """Smallest valid topo-value capacity for this snapshot.  Using a
    smaller z would alias topology values together in the prep-time count
    scatter and silently corrupt spread/inter-pod state."""
    from ..utils.vocab import pad_dim

    return pad_dim(int(np.asarray(snapshot.cluster.topo_ids).max()) + 1, 1)


def features_of(snapshot: Snapshot) -> FeatureFlags:
    """Derive the static gates host-side (cheap numpy reductions)."""
    spread_valid = np.asarray(snapshot.spread.valid)
    hard = np.asarray(snapshot.spread.hard)
    term_valid = np.asarray(snapshot.terms.valid)
    slots = np.asarray(snapshot.terms.slot)
    return FeatureFlags(
        spread=bool(spread_valid.any()),
        soft_spread=bool((spread_valid & ~hard).any()),
        interpod=bool(term_valid.any()),
        term_slots=tuple(sorted(set(slots[term_valid].tolist()))),
    )


class SolveResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, or -1 unschedulable
    scores: jnp.ndarray       # f32[P]: winning node's score (-inf if none)
    feasible_counts: jnp.ndarray  # i32[P]: feasible nodes seen by each pod
    cluster: ClusterTensors   # post-solve cluster (assumed placements applied)


def _pick(
    masked_scores: jnp.ndarray,
    feasible: jnp.ndarray,
    key: Optional[jax.Array],
) -> jnp.ndarray:
    """argmax with first-index ties, or uniform-among-ties when keyed
    (the reference's selectHost reservoir sampling)."""
    if key is None:
        return jnp.argmax(masked_scores)
    best = jnp.max(masked_scores)
    tie = feasible & (masked_scores == best)
    # Gumbel-max over the tie set = uniform choice among ties.
    g = jax.random.gumbel(key, masked_scores.shape)
    return jnp.argmax(jnp.where(tie, g, NEG_INF))


def greedy_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: Optional[int] = None,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
) -> SolveResult:
    """Sequential-greedy solve of the whole pending batch on device.

    Semantically equivalent to running the reference's scheduling cycle
    once per pod in batch order with cache assume between cycles — the
    scan carry holds everything a placement changes: resource usage,
    ports, topology-spread counts, and inter-pod affinity term state.

    topo_z: padded topology-value vocab size (SnapshotMeta.topo_z or
    required_topo_z); auto-derived when None.  Both topo_z and features
    can only be auto-derived outside jit — jitted callers must pass them
    (greedy_assign_jit's wrapper does).
    """
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    cluster, pods, sel, pref, spread, terms = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]
    p = pods.req.shape[0]

    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    sp0 = prep_spread(cluster, sel_mask, spread, topo_z) if features.spread else None
    tm0 = (
        prep_terms(cluster, terms, topo_z, slots=features.term_slots)
        if features.interpod
        else None
    )
    keys = (
        jax.random.split(jax.random.PRNGKey(tie_seed), p)
        if tie_seed is not None
        else None
    )

    def step(carry, i):
        requested, nonzero, ports, sp_counts, tm_present, tm_blocked, tm_global = carry
        cl = cluster._replace(
            requested=requested, nonzero_requested=nonzero, port_bits=ports
        )
        pod = pod_view(pods, i)
        feas = feasible_for_pod(cl, pod, sel_mask)
        sp = tm = None
        if features.spread:
            sp = sp0._replace(counts_node=sp_counts)
            feas = feas & spread_filter(sp, spread, i)
        if features.interpod:
            tm = tm0._replace(
                present_bits=tm_present, blocked_bits=tm_blocked, global_any=tm_global
            )
            feas = feas & interpod_filter(tm, terms, i)
        found = feas.any()
        sp_score = (
            spread_score(sp, spread, i, feas) if features.soft_spread else None
        )
        scores = score_for_pod(cl, pod, feas, pref_mask, cfg, spread_score=sp_score)
        masked = jnp.where(feas, scores, NEG_INF)
        choice = _pick(masked, feas, keys[i] if keys is not None else None)
        idx = jnp.where(found, choice, -1).astype(jnp.int32)

        onehot = (jnp.arange(n) == choice) & found
        requested = requested + onehot[:, None] * pod.req[None, :]
        nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
        ports = jnp.where(onehot[:, None], ports | pod.port_bits[None, :], ports)
        if features.spread:
            sp = spread_update(
                sp, spread, i, sp.v[:, choice], sp.eligible[:, choice], found
            )
            sp_counts = sp.counts_node
        if features.interpod:
            tm = interpod_update(
                tm, terms, i, cluster.topo_ids[choice], found,
                slots=features.term_slots,
            )
            tm_present, tm_blocked, tm_global = (
                tm.present_bits, tm.blocked_bits, tm.global_any
            )
        out = (idx, jnp.where(found, masked[choice], NEG_INF), feas.sum().astype(jnp.int32))
        carry = (requested, nonzero, ports, sp_counts, tm_present, tm_blocked, tm_global)
        return carry, out

    zero = jnp.zeros(())
    init = (
        cluster.requested,
        cluster.nonzero_requested,
        cluster.port_bits,
        sp0.counts_node if features.spread else zero,
        tm0.present_bits if features.interpod else zero,
        tm0.blocked_bits if features.interpod else zero,
        tm0.global_any if features.interpod else zero,
    )
    (requested, nonzero, ports, *_rest), (assignment, win_scores, feas_counts) = (
        jax.lax.scan(step, init, jnp.arange(p))
    )
    final = cluster._replace(
        requested=requested, nonzero_requested=nonzero, port_bits=ports
    )
    return SolveResult(assignment, win_scores, feas_counts, final)


def greedy_assign_jit(cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """A jitted closure over the (static, hashable) score config.
    topo_z and the feature gates are static: one executable per
    (shape-bucket, topo_z, features).  Features are auto-detected
    host-side when not supplied."""

    @partial(jax.jit, static_argnums=(1, 2))
    def run(snapshot: Snapshot, topo_z: int, features: FeatureFlags) -> SolveResult:
        return greedy_assign(snapshot, cfg, topo_z=topo_z, features=features)

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = required_topo_z(snapshot)
        return run(snapshot, topo_z, features)

    return call
