"""Batched assignment solves.

The reference schedules one pod at a time: pop, filter, score, pick, then
`assume` the pod into the cache so the next pod sees its resources
(schedule_one.go:66-133, :940-957).  `greedy_assign` reproduces exactly
those semantics inside a single compiled program: a lax.scan over the pod
axis whose carry *is* the assume bookkeeping (requested / ports updated
tensor-side between picks), so a 10k-pod batch needs one device dispatch
instead of 10k scheduling cycles.

Pods are solved in priority-then-batch-index order (the reference's
queuesort/priority_sort.go:52 pop order); results are scattered back to
input positions.

The scan step is kept minimal: everything placement-independent — the
NodeName/TaintToleration/NodeAffinity filter slice and the raw
affinity/taint score rows — is hoisted out per *pod class*
(schema.PodBatch.class_id groups pods with byte-identical static state),
so a step only re-evaluates resource fit, the carried constraint state,
and the closed-form allocation scores.

Host round-trips per batch: one.

Tie-breaking: first-max-index (deterministic).  The reference picks
uniformly at random among max-score nodes via reservoir sampling
(schedule_one.go:867-905); pass `tie_seed` to sample the same distribution
with a counter-based PRNG instead.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .filters import (
    fits_resources,
    pod_view,
    preferred_match,
    selector_match,
    static_feasible_for_pod,
)
from .interpod import interpod_filter, interpod_update, prep_terms
from .schema import ClusterTensors, PodBatch, Snapshot, num_groups
from .scores import (
    DEFAULT_SCORE_CONFIG,
    ScoreConfig,
    node_affinity_raw,
    score_from_raw,
    taint_toleration_raw,
)
from .topology import prep_spread, spread_filter, spread_score, spread_update

NEG_INF = jnp.float32(-jnp.inf)


class FeatureFlags(NamedTuple):
    """Static gates: a workload only pays scan-step cost for the constraint
    families it actually uses (the analogue of the reference's PreFilter
    returning Skip to elide a plugin for a pod — framework.go:687)."""

    spread: bool = False       # any topology-spread constraints
    soft_spread: bool = False  # any ScheduleAnyway constraints (scoring)
    interpod: bool = False     # any inter-pod (anti-)affinity terms
    term_slots: Tuple[int, ...] = ()  # topology-key slots those terms use
    ports: bool = False        # any pending pod claims host ports (the
                               # dynamic port-conflict carry; the static
                               # check against bound pods is always on)
    interpod_aff: bool = False  # any AFFINITY-direction terms (the
                               # co-location + first-pod-escape family;
                               # the joint auction covers anti-affinity
                               # only, so this gates its routing)
    spread_slots: Tuple[int, ...] = ()  # topology-key slots spread rows use
    interpod_pref: bool = False  # any preferred (scoring) interpod terms
    images: bool = False         # any pending pod names a known image
    # Whether any BOUND pod contributes to each family's count tables.
    # Static so the preps' value-space scatter+gather folds away at
    # trace time when the tables are zero — they arrive as runtime
    # device arrays, so XLA cannot discover zero-ness on its own, and
    # the folded-out gathers are ~0.3 s/solve at 32k nodes.
    bound_spread: bool = False
    bound_terms: bool = False
    bound_pref: bool = False


def required_topo_z(snapshot: Snapshot) -> int:
    """Smallest valid topo-value capacity for this snapshot.  Using a
    smaller z would alias topology values together in the prep-time count
    scatter and silently corrupt spread/inter-pod state."""
    from ..utils.vocab import pad_dim

    return pad_dim(int(np.asarray(snapshot.cluster.topo_ids).max()) + 1, 1)


def required_topo_z_split(snapshot: Snapshot) -> Tuple[int, int]:
    """(z_spread, z_terms): value capacities sized to the topology slots
    each family actually uses.  Hostname ids scale with the cluster (50k
    nodes → 50k values) while zone/region stay tiny; sizing each family's
    value-space buffers to ITS slots keeps a zone-spread batch's scatters
    at z≈64 instead of z≈cluster-size."""
    from ..utils.vocab import pad_dim

    topo = np.asarray(snapshot.cluster.topo_ids)

    def z_for(slots) -> int:
        if len(slots) == 0:
            return 1
        return pad_dim(int(topo[:, sorted(slots)].max()) + 1, 1)

    spread_valid = np.asarray(snapshot.spread.valid)
    spread_slots = set(np.asarray(snapshot.spread.slot)[spread_valid].tolist())
    term_valid = np.asarray(snapshot.terms.valid)
    term_slots = set(np.asarray(snapshot.terms.slot)[term_valid].tolist())
    pref_valid = np.asarray(snapshot.prefpod.valid)
    term_slots |= set(np.asarray(snapshot.prefpod.slot)[pref_valid].tolist())
    return z_for(spread_slots), z_for(term_slots)


def needs_topo(features: FeatureFlags) -> bool:
    """True when the solve carries any topology-value state — spread,
    required inter-pod terms, or PREFERRED inter-pod terms (forgetting
    the last aliased every domain to value 0 and silently zeroed the
    preferred-affinity scores on the dispatch path)."""
    return features.spread or features.interpod or features.interpod_pref


def features_of(
    snapshot: Snapshot, no_bound_pods: bool = False
) -> FeatureFlags:
    """Derive the static gates host-side (cheap numpy reductions).

    no_bound_pods: the caller knows the cluster holds zero bound pods
    (ClusterState._pods empty), so the bound-count tables are zeros by
    construction — skips full scans of the largest snapshot arrays
    (tens of MB each at 20k+ nodes) on the per-batch encode path."""
    spread_valid = np.asarray(snapshot.spread.valid)
    hard = np.asarray(snapshot.spread.hard)
    term_valid = np.asarray(snapshot.terms.valid)
    slots = np.asarray(snapshot.terms.slot)
    if no_bound_pods:
        bound_spread = bound_terms = bound_pref = False
    else:
        bound_spread = bool(np.asarray(snapshot.spread.node_matches).any())
        bound_terms = bool(
            np.asarray(snapshot.terms.node_matches).any()
            or np.asarray(snapshot.terms.node_owners).any()
        )
        bound_pref = bool(
            np.asarray(snapshot.prefpod.node_counts).any()
            or np.asarray(snapshot.prefpod.owner_weight).any()
        )
    return FeatureFlags(
        spread=bool(spread_valid.any()),
        soft_spread=bool((spread_valid & ~hard).any()),
        interpod=bool(term_valid.any()),
        term_slots=tuple(sorted(set(slots[term_valid].tolist()))),
        ports=bool(np.asarray(snapshot.pods.port_bits).any()),
        interpod_aff=bool((np.asarray(snapshot.terms.aff_idx) >= 0).any()),
        spread_slots=tuple(
            sorted(set(np.asarray(snapshot.spread.slot)[spread_valid].tolist()))
        ),
        interpod_pref=bool(np.asarray(snapshot.prefpod.valid).any()),
        images=bool(
            (np.asarray(snapshot.images.pod_ids) >= 0).any()
            and np.asarray(snapshot.cluster.image_bits).any()
        ),
        bound_spread=bound_spread,
        bound_terms=bound_terms,
        bound_pref=bound_pref,
    )


# Failure-reason codes: the FIRST filter stage that emptied the pod's
# candidate set.  The queue's event-scoped requeue (QueueingHints-lite)
# keys off these — e.g. an AssignedPodDelete frees resources but cannot
# fix a node-affinity mismatch, so REASON_STATIC pods stay parked
# (internal/queue/events.go's event→plugin map, reduced to stages).
REASON_NONE = -1      # placed
REASON_STATIC = 0     # NodeName/affinity/taints/validity (+ bound ports)
REASON_RESOURCES = 1  # NodeResourcesFit
REASON_PORTS = 2      # in-batch host-port conflicts
REASON_SPREAD = 3     # PodTopologySpread (hard)
REASON_INTERPOD = 4   # InterPodAffinity (required)
REASON_GANG = 5       # placed individually but released with its gang
REASON_UNENCODABLE = 6  # spec exceeds encoder caps / unsupported field —
                        # only a pod UPDATE can help; no event wakes it


class SolveResult(NamedTuple):
    assignment: jnp.ndarray   # i32[P]: node index, or -1 unschedulable
    scores: jnp.ndarray       # f32[P]: winning node's score (-inf if none)
    feasible_counts: jnp.ndarray  # i32[P]: feasible nodes seen by each pod
    cluster: ClusterTensors   # post-solve cluster (assumed placements applied)
    reasons: jnp.ndarray = None   # i32[P]: REASON_* for unplaced pods


def class_statics(
    cluster: ClusterTensors,
    pods: PodBatch,
    sel_mask: jnp.ndarray,
    pref_mask: jnp.ndarray,
    reps: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-class hoisted tables: (static_feas[C, N], aff_raw[C, N],
    taint_raw[C, N]).  One row per static-equivalence class, computed from
    its representative pod; the scan gathers rows by class_id.  The static
    feasibility folds in the port check against *initial* (bound-pod)
    port claims; in-batch port conflicts ride the dynamic carry.

    reps: representative-pod indices to evaluate (defaults to the joint
    class_rep).  The auction passes pods.spec_rep — static state depends
    only on the spec factor, so the heavy label/taint row kernels run
    once per spec class (see PodBatch's factorization note)."""
    p = pods.req.shape[0]
    if reps is None:
        reps = jnp.clip(pods.class_rep, 0, p - 1)

    def one(rep):
        pod = pod_view(pods, rep)
        sfeas = static_feasible_for_pod(cluster, pod, sel_mask) & ~(
            (cluster.port_bits & pod.port_bits[None, :]).any(axis=-1)
        )
        return (
            sfeas,
            node_affinity_raw(pod, pref_mask),
            taint_toleration_raw(cluster, pod),
        )

    return jax.vmap(one)(reps)


def solve_order(pods: PodBatch) -> jnp.ndarray:
    """Priority-then-batch-index pop order (queuesort/priority_sort.go:52:
    higher priority first, earlier arrival breaking ties).  Stable argsort
    on negated priority ≡ lexicographic (-priority, index)."""
    return jnp.argsort(-pods.priority, stable=True).astype(jnp.int32)


def _pick(
    masked_scores: jnp.ndarray,
    feasible: jnp.ndarray,
    key: Optional[jax.Array],
) -> jnp.ndarray:
    """argmax with first-index ties, or uniform-among-ties when keyed
    (the reference's selectHost reservoir sampling)."""
    if key is None:
        return jnp.argmax(masked_scores)
    best = jnp.max(masked_scores)
    tie = feasible & (masked_scores == best)
    # Gumbel-max over the tie set = uniform choice among ties.
    g = jax.random.gumbel(key, masked_scores.shape)
    return jnp.argmax(jnp.where(tie, g, NEG_INF))


def greedy_assign(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    tie_seed: Optional[int] = None,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
) -> SolveResult:
    """Sequential-greedy solve of the whole pending batch on device.

    Semantically equivalent to running the reference's scheduling cycle
    once per pod in priority order with cache assume between cycles — the
    scan carry holds everything a placement changes: resource usage,
    in-batch port claims, topology-spread counts, and inter-pod affinity
    term state.

    topo_z: padded topology-value vocab size (SnapshotMeta.topo_z or
    required_topo_z); auto-derived when None.  Both topo_z and features
    can only be auto-derived outside jit — jitted callers must pass them
    (greedy_assign_jit's wrapper does).

    n_groups (static): gang-group count.  When > 0, groups with any
    unplaced member release every placement after the scan (all-or-nothing,
    the coscheduling-PodGroup contract) — this is what lets gangs carrying
    spread/interpod/port constraints keep gang semantics instead of
    routing-away to a solver that drops them.  Later in-scan pods saw the
    released placements' resource/count impact (conservative: they may
    park and retry next batch); the released members return as
    unschedulable (-1)."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]
    p = pods.req.shape[0]

    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    sfeas_c, aff_c, taint_c = class_statics(cluster, pods, sel_mask, pref_mask)
    c_dim = sfeas_c.shape[0]
    extra_c = None
    if features.interpod_pref or features.images:
        # Hoisted per-class static score extras: preferred inter-pod
        # affinity (counts from BOUND pods at prep — scoring.go PreScore
        # over the cycle snapshot; in-batch placements don't attract
        # later batchmates within this solve, documented divergence, and
        # the normalization set is the class's static-feasible nodes) and
        # ImageLocality (image presence never changes mid-solve).
        from .interpod import prep_pref_pod
        from .scores import static_extra

        pp = (
            prep_pref_pod(
                cluster, prefpod, topo_z, has_bound=features.bound_pref
            )
            if features.interpod_pref
            else None
        )
        reps_e = jnp.clip(pods.class_rep, 0, p - 1)
        extra_c = jax.vmap(
            lambda c, rep: static_extra(
                cluster, prefpod, images, features, cfg, rep, sfeas_c[c], pp
            )
        )(jnp.arange(c_dim, dtype=jnp.int32), reps_e)
    sp0 = (
        prep_spread(
            cluster, sel_mask, spread, topo_z,
            has_bound=features.bound_spread,
        )
        if features.spread
        else None
    )
    tm0 = (
        prep_terms(
            cluster, terms, topo_z, slots=features.term_slots,
            has_bound=features.bound_terms,
        )
        if features.interpod
        else None
    )
    order = solve_order(pods)
    keys = (
        jax.random.split(jax.random.PRNGKey(tie_seed), p)
        if tie_seed is not None
        else None
    )

    def step(carry, k):
        requested, nonzero, new_ports, sp_counts, tm_present, tm_blocked, tm_global = carry
        i = order[k]
        cl = cluster._replace(requested=requested, nonzero_requested=nonzero)
        pod = pod_view(pods, i)
        cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
        s_static = sfeas_c[cls]
        feas = s_static & fits_resources(cl, pod)
        a_res = feas.any()
        if features.ports:
            feas = feas & ~((new_ports & pod.port_bits[None, :]).any(axis=-1))
        a_ports = feas.any()
        sp = tm = None
        if features.spread:
            sp = sp0._replace(counts_node=sp_counts)
            feas = feas & spread_filter(sp, spread, i)
        a_spread = feas.any()
        if features.interpod:
            tm = tm0._replace(
                present_bits=tm_present, blocked_bits=tm_blocked, global_any=tm_global
            )
            feas = feas & interpod_filter(tm, terms, i)
        found = feas.any()
        # first stage whose filter emptied the candidate set
        reason = jnp.where(
            found, REASON_NONE,
            jnp.where(
                ~s_static.any(), REASON_STATIC,
                jnp.where(
                    ~a_res, REASON_RESOURCES,
                    jnp.where(
                        ~a_ports, REASON_PORTS,
                        jnp.where(~a_spread, REASON_SPREAD, REASON_INTERPOD),
                    ),
                ),
            ),
        ).astype(jnp.int32)
        sp_score = (
            spread_score(sp, spread, i, feas) if features.soft_spread else None
        )
        scores = score_from_raw(
            cl, pod, feas, aff_c[cls], taint_c[cls], cfg, spread_score=sp_score,
            extra=extra_c[cls] if extra_c is not None else None,
        )
        masked = jnp.where(feas, scores, NEG_INF)
        choice = _pick(masked, feas, keys[k] if keys is not None else None)
        idx = jnp.where(found, choice, -1).astype(jnp.int32)

        onehot = (jnp.arange(n) == choice) & found
        requested = requested + onehot[:, None] * pod.req[None, :]
        nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
        if features.ports:
            new_ports = jnp.where(
                onehot[:, None], new_ports | pod.port_bits[None, :], new_ports
            )
        if features.spread:
            sp = spread_update(
                sp, spread, i, sp.v[:, choice], sp.eligible[:, choice], found
            )
            sp_counts = sp.counts_node
        if features.interpod:
            tm = interpod_update(
                tm, terms, i, cluster.topo_ids[choice], found,
                slots=features.term_slots,
            )
            tm_present, tm_blocked, tm_global = (
                tm.present_bits, tm.blocked_bits, tm.global_any
            )
        out = (i, idx, jnp.where(found, masked[choice], NEG_INF),
               feas.sum().astype(jnp.int32), reason)
        carry = (requested, nonzero, new_ports, sp_counts, tm_present, tm_blocked, tm_global)
        return carry, out

    zero = jnp.zeros(())
    init = (
        cluster.requested,
        cluster.nonzero_requested,
        jnp.zeros_like(cluster.port_bits) if features.ports else zero,
        sp0.counts_node if features.spread else zero,
        tm0.present_bits if features.interpod else zero,
        tm0.blocked_bits if features.interpod else zero,
        tm0.global_any if features.interpod else zero,
    )
    (requested, nonzero, new_ports, *_rest), (pod_is, assign_o, win_o, feas_o, reason_o) = (
        jax.lax.scan(step, init, jnp.arange(p))
    )
    # Scatter scan outputs (priority order) back to batch positions.
    assignment = jnp.full(p, -1, jnp.int32).at[pod_is].set(assign_o)
    win_scores = jnp.full(p, NEG_INF).at[pod_is].set(win_o)
    feas_counts = jnp.zeros(p, jnp.int32).at[pod_is].set(feas_o)
    reasons = jnp.full(p, REASON_NONE, jnp.int32).at[pod_is].set(reason_o)

    # Gang post-pass: release every placement of a group with an unplaced
    # member (all-or-nothing), mirroring ops.auction's post-pass.  Only
    # requested/nonzero need subtracting: ports and spread/interpod counts
    # are rebuilt from *actually bound* pods at the next batch's prep, and
    # the host never assumes released members.
    if n_groups > 0:
        g = pods.group_id
        gc = jnp.clip(g, 0, n_groups - 1)
        incomplete = jnp.zeros(n_groups, bool).at[gc].max(
            (assignment < 0) & pods.valid & (g >= 0)
        )
        dropped = (g >= 0) & incomplete[gc] & (assignment >= 0)
        nodes = jnp.clip(assignment, 0, n - 1)
        w = dropped[:, None].astype(jnp.float32)
        requested = requested.at[nodes].add(-pods.req * w)
        nonzero = nonzero.at[nodes].add(-pods.nonzero_req * w)
        assignment = jnp.where(dropped, -1, assignment)
        win_scores = jnp.where(dropped, NEG_INF, win_scores)
        reasons = jnp.where(dropped, REASON_GANG, reasons)

    final = cluster._replace(
        requested=requested,
        nonzero_requested=nonzero,
        port_bits=(cluster.port_bits | new_ports) if features.ports
        else cluster.port_bits,
    )
    return SolveResult(assignment, win_scores, feas_counts, final, reasons)


def greedy_assign_jit(cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """A jitted closure over the (static, hashable) score config.
    topo_z and the feature gates are static: one executable per
    (shape-bucket, topo_z, features).  Features are auto-detected
    host-side when not supplied."""

    @partial(jax.jit, static_argnums=(1, 2, 3))
    def run(
        snapshot: Snapshot, topo_z: int, features: FeatureFlags, n_groups: int
    ) -> SolveResult:
        return greedy_assign(
            snapshot, cfg, topo_z=topo_z, features=features, n_groups=n_groups
        )

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            # topo_z only shapes spread/inter-pod prep state; pinning it
            # to 1 when no family is active keeps the jit cache key
            # stable as topology vocabularies grow.
            topo_z = required_topo_z(snapshot) if needs_topo(features) else 1
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            # Bucket to a power of two: n_groups is a static jit arg, and
            # the post-pass clips, so padding costs nothing but stabilizes
            # the executable cache as gang counts vary batch to batch.
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        return run(snapshot, topo_z, features, n_groups)

    return call


def evaluate_single(
    snapshot: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(feasible[N], scores[N]) for pod 0 of the snapshot — the full
    Filter + Score chain with no placement (what an extender's
    filter/prioritize verbs need: the node SET, not one pick).

    Same kernels the solvers use: static filters + resources + spread +
    inter-pod affinity; scores are the weighted normalized sum
    (runtime/framework.go RunScorePlugins semantics)."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot) if needs_topo(features) else 1
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    from .interpod import interpod_filter, pref_pod_raw, prep_pref_pod, prep_terms
    from .topology import prep_spread, spread_filter, spread_score

    sel_mask = selector_match(cluster, sel)
    pref_mask = preferred_match(cluster, pref)
    pod = pod_view(pods, 0)
    feas = static_feasible_for_pod(cluster, pod, sel_mask) & ~(
        (cluster.port_bits & pod.port_bits[None, :]).any(axis=-1)
    )
    feas = feas & fits_resources(cluster, pod)
    sp_score = None
    if features.spread:
        sp = prep_spread(
            cluster, sel_mask, spread, topo_z,
            has_bound=features.bound_spread,
        )
        feas = feas & spread_filter(sp, spread, 0)
        if features.soft_spread:
            sp_score = spread_score(sp, spread, 0, feas)
    if features.interpod:
        tm = prep_terms(
            cluster, terms, topo_z, slots=features.term_slots,
            has_bound=features.bound_terms,
        )
        feas = feas & interpod_filter(tm, terms, 0)
    extra = None
    if features.interpod_pref or features.images:
        from .scores import static_extra

        pp = (
            prep_pref_pod(
                cluster, prefpod, topo_z, has_bound=features.bound_pref
            )
            if features.interpod_pref
            else None
        )
        extra = static_extra(
            cluster, prefpod, images, features, cfg, 0, feas, pp
        )
    scores = score_from_raw(
        cluster, pod, feas,
        node_affinity_raw(pod, pref_mask),
        taint_toleration_raw(cluster, pod),
        cfg, spread_score=sp_score, extra=extra,
    )
    return feas, jnp.where(feas, scores, NEG_INF)
