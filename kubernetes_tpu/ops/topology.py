"""PodTopologySpread as tensor ops.

The reference precomputes per-(topologyKey, value) match counts and a
critical-path minimum in PreFilter, then filters on
  matchNum + selfMatch - globalMin > maxSkew
(podtopologyspread/filtering.go:313-365) and scores soft constraints by
log-weighted match counts (scoring.go:190-310).

Count state lives in NODE space, not value space: counts_node[c, n] is
the match count of node n's topology value for constraint c.  Every
per-step consumer then needs only contiguous row slices and vectorized
masked reductions — no element gathers, which dominate a fused TPU scan
body (value-space [C, Z] state cost ~0.9 ms/step in gathers; node-space
costs ~a C x N fused madd).  A placement updates all nodes sharing the
chosen node's value in one comparison-multiply-add, and the critical-path
minimum equals the masked min over eligible nodes because every eligible
value has at least one eligible node.

Omitted vs reference (documented divergences):
  * NodeInclusionPolicies support only the reference defaults
    Honor(affinity)/Ignore(taints); the encoder raises on other values.
  * minDomains uses the prep-time eligible-domain count (sizes), not a
    per-cycle recount over filtered nodes — identical whenever eligible
    nodes are schedulable.
  * matchLabelKeys are merged into the selector at encode
    (schema._merge_match_label_keys).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .schema import ClusterTensors, SpreadTable

_BIG = jnp.float32(1e9)


class SpreadState(NamedTuple):
    counts_node: jnp.ndarray  # f32[C, N] match count of n's topo value
    eligible: jnp.ndarray     # bool[C, N] nodes counted for this constraint
    v: jnp.ndarray            # i32[C, N] node's topo value per constraint (-1 absent)
    sizes: jnp.ndarray        # f32[C] distinct eligible values (scoring weight)


# coherence: rebuilt-per-solve -- spread grids derive from THIS snapshot's
# cluster tensors; a cached copy would count against a stale generation
def prep_spread(
    cluster: ClusterTensors,
    sel_mask: jnp.ndarray,
    spread: SpreadTable,
    z: int,
    axis_name: str | None = None,
    has_bound: bool = True,
) -> SpreadState:
    """One-time (per batch) assembly — the PreFilter/PreScore analogue.
    Eligibility honours the owner pod's node selector/affinity and
    requires every topology key the owner's constraints use.  z bounds
    the prep-only value-space scatter that folds bound-pod counts.
    Under shard_map pass axis_name: value-space counts psum across node
    shards before mapping back to (local) node space, so a topology
    domain spanning shards is counted whole.  has_bound=False
    (FeatureFlags.bound_spread) statically elides the bound-count
    scatter+gather (the tables are runtime arrays — XLA cannot fold
    them even when zero); the distinct-value sizes pass stays, it does
    not depend on bound pods."""
    c_dim, tk = spread.owner_keys.shape
    n = cluster.node_valid.shape[0]

    owner_ok = jnp.where(
        (spread.owner_sel_idx < 0)[:, None],
        jnp.ones((c_dim, n), dtype=bool),
        sel_mask[jnp.clip(spread.owner_sel_idx, 0, sel_mask.shape[0] - 1)],
    )
    keys_present = cluster.topo_ids >= 0                       # [N, TK]
    keys_ok = (
        (~spread.owner_keys[:, None, :]) | keys_present[None, :, :]
    ).all(axis=-1)                                             # [C, N]
    eligible = owner_ok & keys_ok & cluster.node_valid[None, :] & spread.valid[:, None]

    v = jnp.take_along_axis(
        cluster.topo_ids, spread.slot[None, :], axis=1
    ).T                                                        # [C, N]
    vc = jnp.clip(v, 0, z - 1)

    if has_bound:
        def per_c(vc_row, ok_row, vrow, nm_row):
            ok = ok_row & (vrow >= 0)
            counts = jnp.zeros(z, jnp.float32).at[vc_row].add(nm_row * ok)
            mask = jnp.zeros(z, bool).at[vc_row].max(ok)
            return counts, mask

        counts_z, vmask = jax.vmap(per_c)(vc, eligible, v, spread.node_matches)
    else:
        def per_c_mask(vc_row, ok_row, vrow):
            ok = ok_row & (vrow >= 0)
            return jnp.zeros(z, bool).at[vc_row].max(ok)

        counts_z = None
        vmask = jax.vmap(per_c_mask)(vc, eligible, v)
    if axis_name is not None:
        if counts_z is not None:
            counts_z = jax.lax.psum(counts_z, axis_name)
        vmask = jax.lax.psum(vmask.astype(jnp.int32), axis_name) > 0
    if counts_z is not None:
        # back to node space for the scan
        counts_node = jnp.take_along_axis(counts_z, vc, axis=-1)
        counts_node = jnp.where(v >= 0, counts_node, 0.0)
    else:
        counts_node = jnp.zeros((c_dim, n), jnp.float32)
    return SpreadState(
        counts_node=counts_node,
        eligible=eligible,
        v=v,
        sizes=vmask.sum(axis=-1).astype(jnp.float32),
    )


def spread_filter(
    state: SpreadState,
    spread: SpreadTable,
    p: jnp.ndarray,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """Hard (DoNotSchedule) constraint check for pod p over all nodes:
    bool[N].  Under shard_map the critical-path min spans shards (pmin)."""
    cidx = spread.pod_idx[p]                        # [MC]
    active = cidx >= 0
    c = jnp.clip(cidx, 0, state.counts_node.shape[0] - 1)

    counts = state.counts_node[c]                   # [MC, N] contiguous rows
    elig = state.eligible[c]
    v = state.v[c]
    min_match = jnp.min(jnp.where(elig, counts, _BIG), axis=-1)  # [MC]
    sizes = state.sizes[c]                                       # [MC]
    if axis_name is not None:
        min_match = jax.lax.pmin(min_match, axis_name)
        # sizes already span shards (prep psums the value mask)
    min_match = jnp.where(min_match >= _BIG, 0.0, min_match)
    # minDomains: fewer eligible domains than required => global min is 0
    # (filtering.go minMatchNum; 0 in the table means unset)
    md = spread.min_domains[c]
    min_match = jnp.where((md > 0) & (sizes < md), 0.0, min_match)
    self_match = spread.pod_matches[p][c]           # [MC]
    skew = counts + self_match[:, None] - min_match[:, None]
    ok = (skew <= spread.max_skew[c][:, None]) & (v >= 0)
    enforced = active & spread.hard[c]
    return (ok | ~enforced[:, None]).all(axis=0)


def spread_score(
    state: SpreadState,
    spread: SpreadTable,
    p: jnp.ndarray,
    feasible: jnp.ndarray,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """Soft (ScheduleAnyway) constraint score, already normalized to [0,100]
    (scoring.go Score + NormalizeScore: lower matching count => higher
    score, log topology-size weights, maxSkew-1 damping)."""
    cidx = spread.pod_idx[p]
    soft = (cidx >= 0) & ~spread.hard[jnp.clip(cidx, 0, spread.hard.shape[0] - 1)]
    any_soft = soft.any()
    c = jnp.clip(cidx, 0, state.counts_node.shape[0] - 1)

    v = state.v[c]                                  # [MC, N]
    has_key = v >= 0
    # IgnoredNodes: feasible nodes missing any soft constraint's key.
    ignored = (soft[:, None] & ~has_key).any(axis=0)
    scored = feasible & ~ignored

    # Topology size drives the log-damping weight.  The reference counts
    # distinct values among the pod's *feasible* nodes per cycle
    # (scoring.go initPreScoreState); we use the distinct *eligible*
    # values precomputed at prep, which is identical whenever eligible
    # nodes are schedulable and avoids an O(N) scatter in every scan step.
    # With a single soft constraint the normalized ranking is invariant to
    # this weight, so the divergence only reweights multi-constraint pods.
    weight = jnp.log(state.sizes[c] + 2.0)          # [MC]

    cnt = state.counts_node[c]                      # [MC, N]
    per_c = cnt * weight[:, None] + (spread.max_skew[c][:, None] - 1.0)
    raw = jnp.round(jnp.where(soft[:, None], per_c, 0.0).sum(axis=0))

    mx = jnp.max(jnp.where(scored, raw, -_BIG))
    mn = jnp.min(jnp.where(scored, raw, _BIG))
    if axis_name is not None:
        mx = jax.lax.pmax(mx, axis_name)
        mn = jax.lax.pmin(mn, axis_name)
    norm = jnp.where(
        mx <= 0.0,
        100.0,
        jnp.floor(100.0 * (mx + mn - raw) / jnp.maximum(mx, 1e-30)),
    )
    out = jnp.where(scored, norm, 0.0)
    return jnp.where(any_soft, out, 0.0)


def spread_update(
    state: SpreadState,
    spread: SpreadTable,
    p: jnp.ndarray,
    v_at: jnp.ndarray,
    elig_at: jnp.ndarray,
    found: jnp.ndarray,
) -> SpreadState:
    """Account a placement: every constraint whose selector the placed pod
    matches (and whose eligible set contains the node) gains one match on
    every node sharing the placement's topology value.  v_at/elig_at are
    the chosen node's column of state.v / state.eligible ([C]); in the
    sharded solve the owning shard psum-broadcasts them so every shard
    applies the same update to its node rows."""
    add = (
        spread.pod_matches[p] & elig_at & (v_at >= 0) & found
    ).astype(jnp.float32)
    counts = state.counts_node + add[:, None] * (state.v == v_at[:, None])
    return state._replace(counts_node=counts)
