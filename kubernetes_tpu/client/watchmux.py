"""Selector-driven HTTP watch multiplexer — thousands of watch streams
on a handful of threads.

The thread-per-stream cost of :meth:`RestClient.watch` caps a fleet
harness at a few hundred informers; real fleets run tens of thousands.
:class:`HttpWatchMux` drives every stream off a small pool of
``selectors`` event loops: each stream is a non-blocking socket
speaking the server's chunked newline-JSON watch protocol, parsed
incrementally (status line → headers → chunk framing → event lines)
with no thread parked on any one of them.

Failover is the reflector contract spread across replicas: a dropped
socket (replica killed, mid-frame disconnect, write-deadline close)
reconnects to the NEXT url in the replica list from the highest rv
delivered — the shared event ring replays the gap.  A 410/Expired
answer (rv fell out of the ring) triggers a relist through
:class:`RestClient` and a fresh watch from the list's rv; the cache is
rebuilt and the rv audit resets for the new stream segment, exactly as
a reflector's does.

:class:`MuxInformer` is the per-stream cache + audit.  The audit
checks the ordering the sharded store actually guarantees: rv strictly
increasing PER NAMESPACE (a namespace maps to one store shard, and
each shard's fan-out delivers in ascending commit order — events of
one kind on DIFFERENT shards may legitimately interleave, see
api/store.py's watch-path notes).  ``violations`` stays empty iff no
namespace ever saw rv go backwards within a segment — including
across a replica failover, which is what the serving chaos family
asserts (tests/test_chaos.py SERVING_SEEDS)."""

from __future__ import annotations

import errno
import json
import selectors
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from ..api import store as st
from ..api import wire
from .rest import RestClient

# stream states
_CONNECTING = "connecting"
_SENDING = "sending"
_HEADERS = "headers"
_STREAMING = "streaming"
_CLOSED = "closed"


class _ChunkDecoder:
    """Incremental HTTP/1.1 chunked-transfer decoder.  Feed raw bytes,
    read back payload bytes; flags the terminal 0-chunk (the server
    ended the stream — the client must relist-and-rewatch, same as
    RestClient.watch's trailing Expired)."""

    def __init__(self) -> None:
        self.buf = bytearray()
        self.left = 0  # >0: bytes left in chunk; -2: eat trailing CRLF
        self.eof = False

    def feed(self, data: bytes) -> bytes:
        self.buf += data
        out = bytearray()
        while not self.eof:
            if self.left > 0:
                take = min(self.left, len(self.buf))
                if not take:
                    break
                out += self.buf[:take]
                del self.buf[:take]
                self.left -= take
                if self.left == 0:
                    self.left = -2
            elif self.left == -2:
                if len(self.buf) < 2:
                    break
                del self.buf[:2]
                self.left = 0
            else:
                i = self.buf.find(b"\r\n")
                if i < 0:
                    break
                size = int(bytes(self.buf[:i]).split(b";")[0] or b"0", 16)
                del self.buf[: i + 2]
                if size == 0:
                    self.eof = True
                    break
                self.left = size
        return bytes(out)


class MuxInformer:
    """Cache + audit for one multiplexed watch stream.

    ``on_event(typ, obj, rv, recv_ts)`` fires for every non-bookmark
    event after the cache applies it — the harness hooks it to compute
    watch-delivery latency against the commit-time table.  ``last_rv``
    is the resume cursor: the MAX rv delivered (cross-shard interleave
    can deliver a lower rv after a higher one; resuming must never move
    the cursor backwards).  ``violations`` collects per-namespace rv
    regressions — the ordering the store's per-shard fan-out does
    guarantee; segments reset on relist, never on plain failover."""

    def __init__(
        self,
        kind: str,
        on_event: Optional[Callable[[str, Any, int, float], None]] = None,
    ) -> None:
        self.kind = kind
        self.on_event = on_event
        self.cache: Dict[str, Any] = {}
        self.last_rv = 0
        self.events_delivered = 0
        self.bookmarks = 0
        self.relists = 0
        self.failovers = 0
        self.violations: List[str] = []
        self.synced = False
        self._ns_rv: Dict[str, int] = {}

    @staticmethod
    def _key(obj: Any) -> str:
        return f"{obj.meta.namespace}/{obj.meta.name}"

    def apply_list(self, items: List[Any], rv: int) -> None:
        self.cache = {self._key(o): o for o in items}
        self.last_rv = rv
        self._ns_rv = {}  # new segment: the audit restarts with it
        self.relists += 1
        self.synced = True

    def apply_event(self, typ: str, obj: Any, rv: int) -> None:
        ns = obj.meta.namespace
        seen = self._ns_rv.get(ns, 0)
        if rv <= seen:
            self.violations.append(
                f"{self.kind}: ns {ns!r} rv went backwards {seen} -> {rv}"
                f" ({typ} {self._key(obj)})"
            )
        self._ns_rv[ns] = max(seen, rv)
        if rv > self.last_rv:
            self.last_rv = rv
        if typ == "DELETED":
            self.cache.pop(self._key(obj), None)
        else:
            self.cache[self._key(obj)] = obj
        self.events_delivered += 1
        if self.on_event is not None:
            self.on_event(typ, obj, rv, time.monotonic())


class _Stream:
    """One non-blocking watch connection inside a mux loop."""

    def __init__(self, informer: MuxInformer, url_index: int) -> None:
        self.informer = informer
        self.url_index = url_index
        self.sock: Optional[socket.socket] = None
        self.state = _CLOSED
        self.outbuf = b""
        self.hdrbuf = bytearray()
        self.status: Optional[int] = None
        self.decoder = _ChunkDecoder()
        self.linebuf = bytearray()
        self.retry_at = 0.0  # monotonic deadline before reconnecting
        self.needs_relist = False


class _MuxLoop:
    """One selector event loop owning a partition of the streams."""

    def __init__(self, mux: "HttpWatchMux", name: str) -> None:
        self.mux = mux
        self.sel = selectors.DefaultSelector()
        self.lock = threading.Lock()
        self.pending: List[_Stream] = []
        self.thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )

    def add(self, stream: _Stream) -> None:
        with self.lock:
            self.pending.append(stream)

    def _run(self) -> None:
        mux = self.mux
        while not mux._stop.is_set():
            now = time.monotonic()
            with self.lock:
                due = [s for s in self.pending if s.retry_at <= now]
                self.pending = [
                    s for s in self.pending if s.retry_at > now
                ]
            for s in due:
                try:
                    if s.needs_relist:
                        mux._relist(s)
                    self._connect(s)
                except Exception:
                    # failed relist/connect (replica mid-restart):
                    # rotate and retry after the backoff
                    s.url_index += 1
                    self._close(s)
            events = self.sel.select(timeout=0.05)
            for key, mask in events:
                stream = key.data
                try:
                    if stream.state == _CONNECTING and (
                        mask & selectors.EVENT_WRITE
                    ):
                        self._finish_connect(stream)
                    elif stream.state == _SENDING and (
                        mask & selectors.EVENT_WRITE
                    ):
                        self._flush_request(stream)
                    elif mask & selectors.EVENT_READ:
                        self._read(stream)
                except Exception:
                    self._failover(stream)

    # -- connection lifecycle ------------------------------------------

    def _connect(self, stream: _Stream) -> None:
        host, port, _ = self.mux._target(stream)
        inf = stream.informer
        path = f"/api/v1/watch/{inf.kind}"
        if inf.last_rv:
            path += f"?from_rv={inf.last_rv}"
        req = (
            f"GET {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
            f"Accept: application/json\r\n"
        )
        if self.mux._token:
            req += f"Authorization: Bearer {self.mux._token}\r\n"
        req += "\r\n"
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        stream.sock = sock
        stream.outbuf = req.encode()
        stream.hdrbuf = bytearray()
        stream.status = None
        stream.decoder = _ChunkDecoder()
        stream.linebuf = bytearray()
        err = sock.connect_ex((host, port))
        if err in (0, errno.EISCONN):
            stream.state = _SENDING
            self.sel.register(sock, selectors.EVENT_WRITE, stream)
        elif err in (errno.EINPROGRESS, errno.EWOULDBLOCK):
            stream.state = _CONNECTING
            self.sel.register(sock, selectors.EVENT_WRITE, stream)
        else:
            raise OSError(err, "connect failed")

    def _finish_connect(self, stream: _Stream) -> None:
        err = stream.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err:
            raise OSError(err, "connect failed")
        stream.state = _SENDING
        self._flush_request(stream)

    def _flush_request(self, stream: _Stream) -> None:
        while stream.outbuf:
            try:
                n = stream.sock.send(stream.outbuf)
            except (BlockingIOError, InterruptedError):
                return
            stream.outbuf = stream.outbuf[n:]
        stream.state = _HEADERS
        self.sel.modify(stream.sock, selectors.EVENT_READ, stream)

    def _read(self, stream: _Stream) -> None:
        try:
            data = stream.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        if not data:
            # replica died or write-deadline closed us: plain failover
            # from last_rv — the ring replays the gap
            raise ConnectionResetError("stream closed by server")
        if stream.state == _HEADERS:
            stream.hdrbuf += data
            end = stream.hdrbuf.find(b"\r\n\r\n")
            if end < 0:
                return
            head = bytes(stream.hdrbuf[:end]).decode("latin-1")
            status_line = head.split("\r\n", 1)[0]
            stream.status = int(status_line.split(" ", 2)[1])
            body = bytes(stream.hdrbuf[end + 4:])
            stream.hdrbuf = bytearray()
            if stream.status == 410:
                # rv fell out of the ring: relist, then rewatch
                stream.needs_relist = True
                raise st.Expired("watch rv expired")
            if stream.status != 200:
                raise OSError(f"watch HTTP {stream.status}")
            stream.state = _STREAMING
            data = body
            if not data:
                return
        payload = stream.decoder.feed(data)
        if payload:
            self._deliver(stream, payload)
        if stream.decoder.eof:
            # terminal chunk: the SERVER ended the stream (overflow
            # termination / shutdown) — relist-and-rewatch, the same
            # contract RestClient.watch raises Expired for
            stream.needs_relist = True
            raise st.Expired("watch stream ended by server")

    def _deliver(self, stream: _Stream, payload: bytes) -> None:
        stream.linebuf += payload
        while True:
            i = stream.linebuf.find(b"\n")
            if i < 0:
                return
            line = bytes(stream.linebuf[:i]).strip()
            del stream.linebuf[: i + 1]
            if not line:
                continue
            doc = json.loads(line)
            inf = stream.informer
            if doc["type"] == "BOOKMARK":
                inf.bookmarks += 1
                if doc["rv"] > inf.last_rv:
                    inf.last_rv = doc["rv"]
                continue
            inf.apply_event(
                doc["type"], wire.from_wire(doc["object"]), doc["rv"]
            )

    # -- failure handling ----------------------------------------------

    def _close(self, stream: _Stream, requeue: bool = True) -> None:
        if stream.sock is not None:
            try:
                self.sel.unregister(stream.sock)
            except (KeyError, ValueError):
                pass
            try:
                stream.sock.close()
            except OSError:
                pass
            stream.sock = None
        stream.state = _CLOSED
        if requeue:
            stream.retry_at = time.monotonic() + HttpWatchMux.RETRY_DELAY
            self.add(stream)

    def _failover(self, stream: _Stream) -> None:
        """Rotate to the next replica and reconnect from last_rv."""
        if stream.state == _STREAMING:
            stream.informer.failovers += 1
        stream.url_index += 1
        self._close(stream)


class HttpWatchMux:
    """Multiplex N watch streams over the replica set on a few threads.

    ``urls`` is the replica base-url list (APIServerReplicaSet.urls());
    it may be refreshed with :meth:`set_urls` after a restart swaps a
    replica onto a new port.  ``token`` rides every request the same
    way RestClient sends it.  ``threads`` selector loops split the
    streams round-robin — one loop handles hundreds of streams, but a
    thousand-informer fleet wants a few so JSON decode parallelizes
    across cores."""

    RETRY_DELAY = 0.2  # backoff before reconnecting a failed stream

    def __init__(
        self,
        urls: List[str],
        token: Optional[str] = None,
        relist_timeout: float = 10.0,
        threads: int = 4,
    ) -> None:
        if not urls:
            raise ValueError("HttpWatchMux needs at least one replica url")
        self._urls = list(urls)
        self._token = token
        self._relist_timeout = relist_timeout
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._streams: List[_Stream] = []
        self._loops = [
            _MuxLoop(self, name=f"watchmux-{i}")
            for i in range(max(1, threads))
        ]

    # -- public surface ------------------------------------------------

    def add_informer(
        self,
        kind: str,
        from_rv: Optional[int] = None,
        on_event: Optional[Callable[[str, Any, int, float], None]] = None,
    ) -> MuxInformer:
        inf = MuxInformer(kind, on_event=on_event)
        if from_rv is not None:
            inf.last_rv = from_rv
            inf.synced = True
        stream = _Stream(inf, len(self._streams) % len(self._urls))
        if from_rv is None:
            stream.needs_relist = True
        self._streams.append(stream)
        self._loops[(len(self._streams) - 1) % len(self._loops)].add(stream)
        return inf

    def set_urls(self, urls: List[str]) -> None:
        with self._lock:
            self._urls = list(urls)

    def start(self) -> None:
        for loop in self._loops:
            loop.thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        for loop in self._loops:
            if loop.thread.is_alive():
                loop.thread.join(timeout=timeout)
        for s in self._streams:
            if s.sock is not None:
                try:
                    s.sock.close()
                except OSError:
                    pass
                s.sock = None

    def informers(self) -> List[MuxInformer]:
        return [s.informer for s in self._streams]

    def violations(self) -> List[str]:
        out: List[str] = []
        for s in self._streams:
            out.extend(s.informer.violations)
        return out

    # -- loop helpers ----------------------------------------------------

    def _target(self, stream: _Stream) -> Tuple[str, int, str]:
        with self._lock:
            url = self._urls[stream.url_index % len(self._urls)]
        parts = urlsplit(url)
        return parts.hostname or "127.0.0.1", parts.port or 80, url

    def _relist(self, stream: _Stream) -> None:
        """Blocking relist through RestClient against the current
        replica.  Runs on the owning loop thread: relists are rare (rv
        outran the ring) and bounded by relist_timeout, an acceptable
        stall for the loop's partition."""
        _, _, url = self._target(stream)
        client = RestClient(
            url, timeout=self._relist_timeout, token=self._token
        )
        items, rv = client.list(stream.informer.kind)
        stream.informer.apply_list(items, rv)
        stream.needs_relist = False
